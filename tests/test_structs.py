"""Data model + resource math tests (semantics ref: nomad/structs/*_test.go)."""

import pytest

from nomad_tpu import mock
from nomad_tpu.structs import (
    Allocation,
    Bitmap,
    NetworkIndex,
    allocs_fit,
    compute_class,
    escaped_constraints,
    parse_attribute,
    parse_port_ranges,
    score_fit,
)
from nomad_tpu.structs.model import (
    AllocatedCpuResources,
    AllocatedMemoryResources,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    ComparableResources,
    Constraint,
    Job,
    NetworkResource,
    Port,
    filter_terminal_allocs,
    remove_allocs,
)


def _alloc_res(cpu, mem, disk=0) -> AllocatedResources:
    return AllocatedResources(
        tasks={
            "web": AllocatedTaskResources(
                cpu=AllocatedCpuResources(cpu_shares=cpu),
                memory=AllocatedMemoryResources(memory_mb=mem),
            )
        },
        shared=AllocatedSharedResources(disk_mb=disk),
    )


class TestAllocsFit:
    def test_fits_empty(self):
        n = mock.node()
        fit, dim, used = allocs_fit(n, [])
        assert fit, dim
        # only the node reserved resources are counted
        assert used.flattened.cpu.cpu_shares == 100
        assert used.flattened.memory.memory_mb == 256

    def test_fit_and_overcommit(self):
        # ref funcs_test.go TestAllocsFit
        n = mock.node()
        a = Allocation(id="a1", allocated_resources=_alloc_res(2000, 2048, 1024))
        fit, dim, used = allocs_fit(n, [a])
        assert fit
        assert used.flattened.cpu.cpu_shares == 2100
        # Double the alloc → still fits in 4000/8192 (4100/4352) but triple won't
        fit, dim, _ = allocs_fit(n, [a, a.copy(), a.copy()])
        assert not fit
        assert dim == "cpu"

    def test_terminal_allocs_ignored(self):
        n = mock.node()
        a = Allocation(id="a1", allocated_resources=_alloc_res(100_000, 1))
        a.desired_status = "stop"
        fit, _, _ = allocs_fit(n, [a])
        assert fit

    def test_port_collision(self):
        n = mock.node()
        net = NetworkResource(
            device="eth0",
            ip="192.168.0.100",
            reserved_ports=[Port(label="main", value=8000)],
        )
        res = _alloc_res(100, 100)
        res.tasks["web"].networks = [net]
        a1 = Allocation(id="a1", allocated_resources=res)
        a2 = Allocation(id="a2", allocated_resources=res.copy())
        fit, dim, _ = allocs_fit(n, [a1, a2])
        assert not fit
        assert dim == "reserved port collision"

    def test_device_oversubscription(self):
        n = mock.tpu_node()
        dev_id = n.node_resources.devices[0].instances[0].id
        res = _alloc_res(100, 100)
        from nomad_tpu.structs.model import AllocatedDeviceResource

        res.tasks["web"].devices = [
            AllocatedDeviceResource(
                vendor="google", type="tpu", name="v5e", device_ids=[dev_id]
            )
        ]
        a1 = Allocation(id="a1", allocated_resources=res)
        a2 = Allocation(id="a2", allocated_resources=res.copy())
        fit, dim, _ = allocs_fit(n, [a1, a2], check_devices=True)
        assert not fit
        assert dim == "device oversubscribed"
        fit, _, _ = allocs_fit(n, [a1], check_devices=True)
        assert fit


class TestScoreFit:
    # ref funcs_test.go TestScoreFit
    def _node(self):
        n = mock.node()
        n.node_resources.cpu.cpu_shares = 4096
        n.node_resources.memory.memory_mb = 8192
        n.reserved_resources = None
        return n

    def test_perfect_fit(self):
        n = self._node()
        util = ComparableResources(
            flattened=AllocatedTaskResources(
                cpu=AllocatedCpuResources(cpu_shares=4096),
                memory=AllocatedMemoryResources(memory_mb=8192),
            )
        )
        assert score_fit(n, util) == 18.0

    def test_zero_util(self):
        n = self._node()
        util = ComparableResources()
        assert score_fit(n, util) == 0.0

    def test_mid_util(self):
        n = self._node()
        util = ComparableResources(
            flattened=AllocatedTaskResources(
                cpu=AllocatedCpuResources(cpu_shares=2048),
                memory=AllocatedMemoryResources(memory_mb=4096),
            )
        )
        score = score_fit(n, util)
        assert abs(score - 13.675445) < 1e-4


class TestNetworkIndex:
    def test_set_node_reserved_ports(self):
        n = mock.node()
        idx = NetworkIndex()
        collide = idx.set_node(n)
        assert not collide
        assert idx.used_ports["192.168.0.100"].check(22)

    def test_assign_network_dynamic(self):
        n = mock.node()
        idx = NetworkIndex()
        idx.set_node(n)
        ask = NetworkResource(
            mbits=50, dynamic_ports=[Port(label="http"), Port(label="admin")]
        )
        offer, err = idx.assign_network(ask)
        assert offer is not None, err
        assert offer.ip == "192.168.0.100"
        ports = {p.value for p in offer.dynamic_ports}
        assert len(ports) == 2
        for p in ports:
            assert 20000 <= p < 32000

    def test_assign_network_reserved_collision(self):
        n = mock.node()
        idx = NetworkIndex()
        idx.set_node(n)
        ask = NetworkResource(mbits=1, reserved_ports=[Port(label="ssh", value=22)])
        offer, err = idx.assign_network(ask)
        assert offer is None
        assert err == "reserved port collision"

    def test_bandwidth_exceeded(self):
        n = mock.node()
        idx = NetworkIndex()
        idx.set_node(n)
        ask = NetworkResource(mbits=2000)
        offer, err = idx.assign_network(ask)
        assert offer is None
        assert err == "bandwidth exceeded"

    def test_overcommitted(self):
        idx = NetworkIndex()
        idx.set_node(mock.node())
        idx.add_reserved(
            NetworkResource(device="eth0", ip="192.168.0.100", mbits=2000)
        )
        assert idx.overcommitted()

    def test_parse_port_ranges(self):
        assert parse_port_ranges("80,100-103,205") == [80, 100, 101, 102, 103, 205]
        with pytest.raises(ValueError):
            parse_port_ranges("200-100")


class TestBitmap:
    def test_basics(self):
        b = Bitmap(128)
        b.set(5)
        assert b.check(5)
        assert not b.check(6)
        assert b.indexes_in_range(True, 0, 127) == [5]
        assert 5 not in b.indexes_in_range(False, 0, 127)
        c = b.copy()
        c.unset(5)
        assert b.check(5) and not c.check(5)


class TestComputedClass:
    def test_identical_nodes_same_class(self):
        n1, n2 = mock.node(), mock.node()
        assert n1.computed_class == n2.computed_class

    def test_unique_attrs_excluded(self):
        n1, n2 = mock.node(), mock.node()
        n2.attributes["unique.hostname"] = "xyz"
        compute_class(n2)
        assert n1.computed_class == n2.computed_class

    def test_class_changes_with_attrs(self):
        n1, n2 = mock.node(), mock.node()
        n2.attributes["kernel.name"] = "darwin"
        compute_class(n2)
        assert n1.computed_class != n2.computed_class

    def test_devices_affect_class(self):
        assert mock.node().computed_class != mock.tpu_node().computed_class

    def test_escaped_constraints(self):
        cs = [
            Constraint(l_target="${node.unique.id}", r_target="x", operand="="),
            Constraint(l_target="${attr.kernel.name}", r_target="linux", operand="="),
        ]
        esc = escaped_constraints(cs)
        assert len(esc) == 1
        assert esc[0].l_target == "${node.unique.id}"


class TestAttribute:
    def test_parse(self):
        a = parse_attribute("11GiB")
        assert a.int_val == 11 and a.unit == "GiB"
        assert parse_attribute("3.14").float_val == 3.14
        assert parse_attribute("true").bool_val is True
        assert parse_attribute("hello").string_val == "hello"

    def test_unit_compare(self):
        a = parse_attribute("1GiB")
        b = parse_attribute("1024MiB")
        cmp, ok = a.compare(b)
        assert ok and cmp == 0
        c = parse_attribute("2000MB")
        cmp, ok = a.compare(c)
        assert ok and cmp == -1

    def test_incomparable(self):
        a = parse_attribute("1GiB")
        b = parse_attribute("100MHz")
        _, ok = a.compare(b)
        assert not ok


class TestModelHelpers:
    def test_serialization_roundtrip(self):
        j = mock.job()
        j2 = Job.from_dict(j.to_dict())
        assert j2.to_dict() == j.to_dict()
        assert j2.task_groups[0].tasks[0].resources.cpu == 500

    def test_remove_and_filter_allocs(self):
        a1, a2 = mock.alloc(), mock.alloc()
        assert [x.id for x in remove_allocs([a1, a2], [a2])] == [a1.id]
        a2.client_status = "failed"
        a2.name = a1.name
        live, term = filter_terminal_allocs([a1, a2])
        assert [x.id for x in live] == [a1.id]
        assert term[a1.name].id == a2.id

    def test_copy_preserves_typed_device_attributes(self):
        n = mock.tpu_node().copy()
        attr = n.node_resources.devices[0].attributes["memory"]
        cmp, ok = attr.compare(parse_attribute("16GiB"))
        assert ok and cmp == 0

    def test_next_reschedule_time_guards(self):
        a = mock.alloc()
        a.modify_time = 12345
        a.client_status = "running"
        assert a.next_reschedule_time() == (0, False)
        a.client_status = "failed"
        t, eligible = a.next_reschedule_time()
        assert eligible and t == 12345 + 5 * 1_000_000_000

    def test_score_fit_zero_capacity_node(self):
        n = mock.node()
        n.node_resources.cpu.cpu_shares = 100  # equals reserved cpu
        assert score_fit(n, ComparableResources()) == 0.0

    def test_spec_changed(self):
        j = mock.job()
        j2 = j.copy()
        j2.modify_index += 10
        assert not j.specchanged(j2)
        j2.priority += 1
        assert j.specchanged(j2)
