"""Vault token lifecycle, the service catalog, and prometheus metrics
(ref nomad/vault.go, command/agent/consul/ service sync,
config.go telemetry sinks)."""

import os
import tempfile
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.api.client import ApiClient
from nomad_tpu.api.http import HTTPServer
from nomad_tpu.client.client import Client
from nomad_tpu.core.server import Server
from nomad_tpu.raft import InmemTransport, RaftConfig
from nomad_tpu.structs.model import Service, Vault


def make_server(extra=None):
    cfg = {
        "seed": 42,
        "heartbeat_ttl": 600.0,
        "raft": {
            "node_id": "s0",
            "address": "raft0",
            "voters": {"s0": "raft0"},
            "transport": InmemTransport(),
            "config": RaftConfig(
                heartbeat_interval=0.02,
                election_timeout_min=0.05,
                election_timeout_max=0.10,
            ),
        },
    }
    cfg.update(extra or {})
    s = Server(cfg)
    s.start(num_workers=1, wait_for_leader=5.0)
    return s


def wait_until(fn, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class TestVaultLifecycle:
    def test_token_derived_delivered_and_revoked(self, tmp_path):
        """A task with a vault stanza gets a token in secrets/vault_token
        and VAULT_TOKEN; the accessor is tracked in raft state and revoked
        when the alloc terminates (vault.go DeriveVaultToken/RevokeTokens)."""
        server = make_server({"vault": {"enabled": True}})
        client = Client(server, data_dir=str(tmp_path))
        client.start()
        try:
            job = mock.batch_job()
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.vault = Vault(policies=["app-secrets"])
            task.config = {
                "command": "/bin/sh",
                "args": [
                    "-c",
                    'cp "$NOMAD_SECRETS_DIR/vault_token" tok_file;'
                    ' echo -n "$VAULT_TOKEN" > tok_env; sleep 1',
                ],
            }
            task.resources.networks = []
            server.job_register(job)

            wait_until(
                lambda: server.state.vault_accessors(),
                msg="accessor tracked while task runs",
            )
            (accessor,) = server.state.vault_accessors()
            assert accessor["task"] == "web"
            assert server.vault.provider.is_live(accessor["accessor"])

            wait_until(
                lambda: all(
                    a.client_status == "complete"
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                ),
                msg="task completes",
            )
            (alloc,) = server.state.allocs_by_job(job.namespace, job.id)
            base = tmp_path / "allocs" / alloc.id / "web"
            from_file = (base / "tok_file").read_text().strip()
            from_env = (base / "tok_env").read_text().strip()
            assert from_file.startswith("s.") and from_file == from_env

            # revoked with the alloc's terminal update
            wait_until(
                lambda: not server.state.vault_accessors(),
                msg="accessor revoked on termination",
            )
            assert not server.vault.provider.is_live(accessor["accessor"])
            client.stop()
        finally:
            server.stop()

    def test_disabled_vault_fails_stanza_tasks(self, tmp_path):
        server = make_server()  # vault not enabled
        try:
            with pytest.raises(ValueError):
                server.vault.derive_token("nope", "web")
        finally:
            server.stop()


class TestServiceCatalog:
    def test_services_from_running_allocs(self, tmp_path):
        server = make_server()
        client = Client(server, data_dir=str(tmp_path))
        client.start()
        http = HTTPServer(server, port=0)
        http.start()
        api = ApiClient(address=f"http://127.0.0.1:{http.port}")
        try:
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "mock_driver"
            task.config = {"run_for": "30s"}
            task.services = [
                Service(name="web-api", port_label="http", tags=["prod"])
            ]
            server.job_register(job)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    for a in server.state.allocs_by_job(job.namespace, job.id)
                ),
                msg="alloc running",
            )
            wait_until(
                lambda: any(
                    e["Status"] == "passing"
                    for e in api.get("/v1/services")[0]
                    if e["ServiceName"] == "web-api"
                ),
                msg="service passing in catalog",
            )
            (entry,) = api.get("/v1/service/web-api")[0]
            assert entry["Tags"] == ["prod"]
            assert entry["Port"] > 0 and entry["Address"], entry
            client.stop()
        finally:
            http.stop()
            server.stop()


class TestPrometheusMetrics:
    def test_text_exposition(self):
        server = make_server()
        http = HTTPServer(server, port=0)
        http.start()
        try:
            import urllib.request

            body = urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/v1/metrics?format=prometheus"
            ).read().decode()
            assert "# TYPE nomad_tpu_state_index gauge" in body
            assert "nomad_tpu_plan_queue_depth" in body
            # still JSON without the format param
            import json

            payload = json.loads(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{http.port}/v1/metrics"
                ).read()
            )
            assert "broker" in payload
        finally:
            http.stop()
            server.stop()


class TestStageTimers:
    def test_scheduler_stages_measured(self):
        """Per-stage timers (the go-metrics MeasureSince role: worker
        invoke, plan evaluate/submit/apply) and job-summary gauges appear
        in /v1/metrics after one scheduling round."""
        import time as time_mod

        from nomad_tpu import metrics as metrics_mod
        from nomad_tpu import mock
        from nomad_tpu.agent import DevAgent
        from nomad_tpu.api import ApiClient

        metrics_mod.reset()
        agent = DevAgent(num_clients=1, server_config={"seed": 3})
        agent.start()
        http = HTTPServer(agent.server, port=0, agent=agent)
        http.start()
        api = ApiClient(address=f"http://127.0.0.1:{http.port}")
        try:
            job = mock.job()
            job.task_groups[0].tasks[0].driver = "mock_driver"
            job.task_groups[0].tasks[0].config = {"run_for": "30s"}
            job.task_groups[0].tasks[0].resources.networks = []
            eval_id = agent.server.job_register(job)
            deadline = time_mod.monotonic() + 10
            while time_mod.monotonic() < deadline:
                ev = agent.server.state.eval_by_id(eval_id)
                if ev is not None and ev.status == "complete":
                    break
                time_mod.sleep(0.05)
            m = api.metrics()
            timers = m["stages"]["timers"]
            for stage in (
                "worker.invoke_scheduler.service",
                "plan.evaluate",
                "plan.submit",
                "plan.raft_apply",
            ):
                assert stage in timers, f"missing stage timer {stage}"
                assert timers[stage]["count"] >= 1
                assert timers[stage]["p99_ms"] >= 0
            assert m["stages"]["counters"]["worker.evals_processed.service"] >= 1
            assert job.id in m["job_summary"]
        finally:
            http.stop()
            agent.stop()
