"""Trace-plane tests (nomad_tpu/trace): span tree construction and
retention, metric unification (eval.e2e / stage splits ride spans),
end-to-end connectivity over the real server path (broker → worker →
device → plan → fsm → mirror), chaos survival (sever/retry, plan-commit
ApplyTimeout barrier), behavior-identity with tracing on vs off, the
critical-path analyzer, the span-hygiene checkers, and the tier-1
trace-overhead gate."""

import json
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu import metrics
from nomad_tpu.core.server import Server
from nomad_tpu.raft import InmemTransport, RaftConfig
from nomad_tpu.testing import faults
from nomad_tpu.trace import (
    SpanContext,
    TraceStore,
    attribute,
    orphan_count,
    tracer,
)


@pytest.fixture(autouse=True)
def _clean_trace():
    """The tracer and metrics registries are process-global: every test
    starts from and returns to a clean slate."""
    metrics.reset()
    tracer.reset()
    yield
    faults.uninstall()
    tracer.reset()
    metrics.reset()


def make_server(num_workers=1, extra=None):
    cfg = {
        "seed": 42,
        "heartbeat_ttl": 600.0,
        "raft": {
            "node_id": "s0",
            "address": "raft0",
            "voters": {"s0": "raft0"},
            "transport": InmemTransport(),
            "config": RaftConfig(
                heartbeat_interval=0.02,
                election_timeout_min=0.05,
                election_timeout_max=0.10,
            ),
        },
    }
    cfg.update(extra or {})
    s = Server(cfg)
    s.start(num_workers=num_workers, wait_for_leader=5.0)
    return s


def wait_until(fn, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def wait_evals_terminal(server, eval_ids, timeout=30.0):
    wait_until(
        lambda: all(
            (ev := server.state.eval_by_id(e)) is not None
            and ev.terminal_status()
            for e in eval_ids
        ),
        timeout=timeout,
        msg="evals terminal",
    )


def trace_for_eval(eval_id):
    for record in tracer.store.records():
        for span in record["spans"]:
            if (
                span["name"] == "eval.e2e"
                and span["tags"].get("eval_id") == eval_id
            ):
                return record
    return None


def span_names(record):
    return {s["name"] for s in record["spans"]}


def simple_job(job_id=None, count=4):
    job = mock.job()
    if job_id:
        job.id = job_id
    tg = job.task_groups[0]
    tg.count = count
    tg.tasks[0].resources.networks = []
    return job


# ---------------------------------------------------------------------------
# span core + store retention
# ---------------------------------------------------------------------------


class TestSpanCore:
    def test_eval_lifecycle_builds_connected_tree(self):
        tracer.eval_root("ev-1", tags={"job": "j1"})
        ctx = tracer.ctx_for_eval("ev-1")
        assert isinstance(ctx, SpanContext) and ctx.sampled
        with tracer.span("worker.process", parent=ctx):
            with tracer.span("eval.evaluate"):
                pass
            now = time.monotonic()
            tracer.record_span("plan.queue_wait", ctx, now - 0.01, now)
        tracer.finish_eval("ev-1")
        records = tracer.store.records()
        assert len(records) == 1
        record = records[0]
        assert span_names(record) == {
            "eval.e2e", "worker.process", "eval.evaluate",
            "plan.queue_wait",
        }
        assert orphan_count(record) == 0
        # nested parentage: eval.evaluate's parent is worker.process
        by_name = {s["name"]: s for s in record["spans"]}
        assert (
            by_name["eval.evaluate"]["parent_id"]
            == by_name["worker.process"]["span_id"]
        )
        # the registry released the eval
        assert tracer.ctx_for_eval("ev-1") is None

    def test_span_metric_unification_and_exemplars(self):
        """A span with metric= replaces metrics.measure: the timer flows
        whether or not a trace is active, and an active sampled trace
        links the sample as an exemplar."""
        with tracer.span("plan.submit", metric="plan.submit"):
            pass  # no parent ctx: metric only
        snap = metrics.snapshot()
        assert snap["timers"]["plan.submit"]["count"] == 1
        assert "plan.submit" not in snap["exemplars"]

        tracer.eval_root("ev-m")
        ctx = tracer.ctx_for_eval("ev-m")
        with tracer.span("plan.submit", parent=ctx, metric="plan.submit"):
            pass
        tracer.finish_eval("ev-m")
        snap = metrics.snapshot()
        assert snap["timers"]["plan.submit"]["count"] == 2
        trace_ids = {e["trace_id"] for e in snap["exemplars"]["plan.submit"]}
        assert ctx.trace_id in trace_ids

    def test_disabled_tracer_keeps_eval_e2e_metric(self):
        tracer.enabled = False
        tracer.eval_root("ev-d")
        tracer.finish_eval("ev-d")
        snap = metrics.snapshot()
        assert snap["timers"]["eval.e2e"]["count"] == 1
        assert snap["exemplars"] == {}
        assert tracer.store.stats()["retained"] == 0

    def test_sampling_is_trace_id_stable_and_consumes_no_rng(self):
        import random

        state = random.getstate()
        tracer.sample_rate = 0.0
        tracer.eval_root("ev-s")
        tracer.finish_eval("ev-s")
        assert tracer.store.stats()["retained"] == 0
        # eval.e2e still sampled into the timer (timing-only root)
        assert metrics.snapshot()["timers"]["eval.e2e"]["count"] == 1
        assert random.getstate() == state, "tracing consumed global RNG"

    def test_store_ring_slowest_and_error_keeps(self):
        store = TraceStore(retain=2, slow_keep=1, error_keep=1)

        def finish(tid, duration_ms, error=False):
            store.open_trace(tid)
            if error:
                store.add_span({
                    "trace_id": tid, "span_id": f"{tid}-c",
                    "parent_id": f"{tid}-r", "name": "child",
                    "start": 0.0, "duration_ms": 1.0, "tags": {},
                    "flags": [], "error": "boom",
                })
            store.finish_trace(tid, {
                "trace_id": tid, "span_id": f"{tid}-r", "parent_id": None,
                "name": "eval.e2e", "start": 0.0,
                "duration_ms": duration_ms, "tags": {}, "flags": [],
                "error": None,
            })

        finish("t-slowest", 500.0)
        finish("t-err", 5.0, error=True)
        for i in range(4):
            finish(f"t-{i}", 10.0 + i)
        stats = store.stats()
        assert stats["ring"] == 2
        # the slowest trace survived ring eviction in the slow keep
        assert store.get("t-slowest") is not None
        assert store.get("t-err") is not None
        listed_err = store.list(errors=True)
        assert [r["trace_id"] for r in listed_err] == ["t-err"]
        listed_slow = store.list(slowest=True)
        assert listed_slow[0]["trace_id"] == "t-slowest"
        # evicted middle traces are really gone
        assert store.get("t-0") is None

    def test_late_spans_attach_to_retained_trace(self):
        tracer.eval_root("ev-l")
        ctx = tracer.ctx_for_eval("ev-l")
        tracer.finish_eval("ev-l")
        now = time.monotonic()
        tracer.record_span("mirror.patch", ctx, now, now + 0.001)
        record = tracer.store.records()[0]
        assert "mirror.patch" in span_names(record)
        assert tracer.store.stats()["late_spans"] == 1


class TestMetricsHistograms:
    def test_base2_buckets_bound_cardinality(self):
        for value in range(1, 100001):
            metrics.observe("test.hist", value)
        hist = metrics.snapshot()["hists"]["test.hist"]
        assert len(hist) <= 18  # log2(100000) ≈ 16.6 buckets + 0/1
        assert all(isinstance(k, int) for k in hist)
        assert sum(hist.values()) == 100000

    def test_percentile_hist_and_timer(self):
        for _ in range(99):
            metrics.observe("test.p", 2)
        metrics.observe("test.p", 64)
        # p50 inside the [2,3] bucket → its upper bound
        assert metrics.percentile("test.p", 0.5) == 3
        assert metrics.percentile("test.p", 0.999) == 127
        metrics.sample("test.t", 0.5)
        metrics.sample("test.t", 1.5)
        assert metrics.percentile("test.t", 0.99) == 1.5
        assert metrics.percentile("nope", 0.5) is None

    def test_exemplars_capped(self):
        for i in range(10):
            metrics.sample("test.e", 0.01, exemplar=f"trace-{i}")
        ex = metrics.snapshot()["exemplars"]["test.e"]
        assert len(ex) == metrics.EXEMPLARS_PER_METRIC
        assert ex[-1]["trace_id"] == "trace-9"


# ---------------------------------------------------------------------------
# end-to-end over the real server path
# ---------------------------------------------------------------------------


class TestEvalTraceEndToEnd:
    def test_submit_to_ack_is_one_connected_tree(self):
        server = make_server(num_workers=2)
        try:
            for i in range(3):
                n = mock.node()
                n.id = f"node-{i}"
                server.node_register(n)
            with tracer.root("job.submit", tags={"job": "j-e2e"}):
                eval_id = server.job_register(simple_job("j-e2e"))
            wait_evals_terminal(server, [eval_id])
            time.sleep(0.3)
            record = trace_for_eval(eval_id)
            assert record is not None, "no retained trace for the eval"
            names = span_names(record)
            for required in (
                "job.submit", "eval.e2e", "worker.process",
                "eval.evaluate", "plan.submit", "plan.queue_wait",
                "plan.evaluate", "plan.commit", "fsm.apply_plan",
            ):
                assert required in names, f"missing {required}: {names}"
            assert orphan_count(record) == 0
            # the eval.e2e exemplar points at this retained trace
            exemplars = metrics.snapshot()["exemplars"]["eval.e2e"]
            assert record["trace_id"] in {
                e["trace_id"] for e in exemplars
            }
        finally:
            server.stop()

    def test_nack_retry_stays_one_tree(self):
        """A worker that fails mid-eval nacks; the retry lands in the
        SAME trace with the nack marker visible — not a second tree."""
        plane = faults.install(faults.FaultPlane(seed=7))
        plane.rule(
            "point", "error", method="worker.post_dequeue", count=1
        )
        server = make_server(num_workers=1, extra={
            # immediate re-enqueue after the injected nack
            "initial_nack_delay": 0.0,
        })
        try:
            for i in range(3):
                n = mock.node()
                n.id = f"node-{i}"
                server.node_register(n)
            eval_id = server.job_register(simple_job("j-nack"))
            wait_evals_terminal(server, [eval_id])
            time.sleep(0.3)
            record = trace_for_eval(eval_id)
            assert record is not None
            names = [s["name"] for s in record["spans"]]
            assert "eval.nack" in names
            # two worker.process attempts (first errored), one tree
            attempts = [
                s for s in record["spans"] if s["name"] == "worker.process"
            ]
            assert len(attempts) == 2
            assert any(s["error"] for s in attempts)
            assert orphan_count(record) == 0
        finally:
            server.stop()


class TestDrainDeviceTrace:
    def test_drain_storm_trace_spans_device_and_mirror(self):
        """The acceptance tree: a 4-worker drain-config run under a small
        storm yields connected traces spanning broker, worker, device
        dispatch/compute/materialize, plan verify, raft apply, and FSM —
        including across an injected sever/retry — and the critical-path
        analyzer attributes stages from retained traces alone.

        The server comes up with ZERO workers and the drain opens
        (start_workers) only after every eval is in the ready queue:
        whether two evals are ever simultaneously ready is otherwise a
        scheduling accident — on a loaded 1-core box the workers kept
        winning the race one eval at a time, every dequeue_batch came
        back singleton, and the single-eval path's small-eval oracle
        gate meant NO eval ever rode the fused device path (the exact
        flake this test shipped with)."""
        plane = faults.install(faults.FaultPlane(seed=11))
        # one injected worker failure mid-storm: nack → retry must stay
        # inside its eval's tree
        plane.rule(
            "point", "error", method="worker.post_dequeue", count=1,
            after=2,
        )
        server = make_server(num_workers=0, extra={
            "batch_drain": 4,
            "default_scheduler": "tpu-batch",
            "plan_apply_batch": 4,
            "initial_nack_delay": 0.0,
        })
        try:
            for i in range(8):
                n = mock.node()
                n.id = f"node-{i:02d}"
                n.node_resources.networks = []
                server.node_register(n)
            eval_ids = [
                server.job_register(simple_job(f"j-drain-{j}", count=8))
                for j in range(8)
            ]
            wait_until(
                lambda: server.eval_broker.stats()["total_ready"]
                >= len(eval_ids),
                msg="all evals ready before the drain opens",
            )
            server.start_workers(4)
            wait_evals_terminal(server, eval_ids, timeout=120.0)
            time.sleep(0.5)
            records = [
                r for r in (trace_for_eval(e) for e in eval_ids) if r
            ]
            assert records, "no retained drain traces"
            device_records = [
                r for r in records
                if "drain.device_compute" in span_names(r)
            ]
            assert device_records, "no trace rode the fused device path"
            # a fully-rejected plan (optimistic race with a sibling) may
            # legitimately never commit — assert the complete stage set
            # on a trace that did
            committed = [
                r for r in device_records
                if "plan.commit" in span_names(r)
            ]
            assert committed, "no device trace committed a plan"
            names = span_names(committed[0])
            for required in (
                "eval.e2e", "worker.process", "drain.park", "drain.build",
                "drain.kernel_dispatch", "drain.device_compute",
                "drain.materialize", "plan.submit", "plan.evaluate",
                "plan.commit", "fsm.apply_plan",
            ):
                assert required in names, f"missing {required}: {names}"
            for r in records:
                assert orphan_count(r) == 0
            # the injected failure produced a nack marker in SOME tree
            assert any(
                "eval.nack" in span_names(r) for r in records
            ), "injected sever/retry not visible in any tree"
            # critical-path attribution from retained traces alone
            report = attribute(tracer.store.records())
            assert report["traces"] >= len(device_records)
            assert report["bottleneck"] is not None
            stage_names = set(report["stages"])
            assert stage_names & {
                "plan.submit", "plan.queue_wait", "plan.commit",
                "drain.park", "drain.device_compute", "eval.evaluate",
            }
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# chaos: plan-commit indeterminacy barrier is a span
# ---------------------------------------------------------------------------


class TestApplyTimeoutBarrierSpan:
    @staticmethod
    def _mk_plan(store, job, tag, eval_id, ncpu, count):
        from nomad_tpu.structs.model import Plan

        plan = Plan()
        plan.priority = 50
        plan.eval_id = eval_id
        plan.snapshot_index = store.latest_index()
        allocs = []
        for i in range(count):
            a = mock.alloc()
            a.id = f"{tag}-{i}"
            a.name = f"{job.id}.web[{tag}-{i}]"
            a.node_id = "n-0"
            a.job_id = job.id
            a.eval_id = eval_id
            a.job = job
            for t in a.allocated_resources.tasks.values():
                t.cpu.cpu_shares = ncpu
                t.memory.memory_mb = 1
                t.networks = []
            a.allocated_resources.shared.networks = []
            allocs.append(a)
        plan.node_allocation["n-0"] = allocs
        return plan

    def test_barrier_resolution_is_visible_in_the_tree(self):
        import threading

        from nomad_tpu.core.plan_apply import Planner
        from nomad_tpu.raft import ApplyTimeout
        from nomad_tpu.state import StateStore

        store = StateStore()
        node = mock.node()
        node.id = "n-0"
        node.node_resources.networks = []
        store.upsert_node(1, node)
        job = mock.job()
        job.id = "j-barrier"
        store.upsert_job(2, job)

        tracer.eval_root("ev-barrier")
        planner = Planner(store)
        applied = threading.Event()
        first = {"seen": False}

        def commit_batch_fn(items):
            if not first["seen"]:
                first["seen"] = True

                def late_apply():
                    time.sleep(0.3)
                    for plan, result, pevals in items:
                        store.upsert_plan_results(None, plan, result)
                    applied.set()

                threading.Thread(target=late_apply, daemon=True).start()
                raise ApplyTimeout(store.latest_index() + 1)
            for plan, result, pevals in items:
                store.upsert_plan_results(None, plan, result)
            return store.latest_index()

        def barrier_fn(exc):
            assert applied.wait(10), "barrier outran the in-flight entry"

        planner.commit_batch_fn = commit_batch_fn
        planner.commit_fn = None
        planner.barrier_fn = barrier_fn
        planner.start()
        try:
            pending = planner.queue.enqueue(
                self._mk_plan(store, job, "a", "ev-barrier", 100, 3)
            )
            result, error = pending.wait(timeout=10)
            assert error is None and result is not None
        finally:
            planner.stop()
        tracer.finish_eval("ev-barrier")
        record = tracer.store.records()[0]
        names = span_names(record)
        assert "plan.commit_barrier" in names, names
        barrier = next(
            s for s in record["spans"]
            if s["name"] == "plan.commit_barrier"
        )
        assert barrier["tags"]["resolved"] is True
        assert "plan.commit" in names
        assert orphan_count(record) == 0


# ---------------------------------------------------------------------------
# RPC propagation: sever + retry stays one trace
# ---------------------------------------------------------------------------


class TestRpcTracePropagation:
    def test_trace_survives_rpc_sever_and_retry(self):
        from nomad_tpu.rpc import ConnPool, ServerProxy
        from nomad_tpu.rpc.server import RpcServer

        rpc = RpcServer(port=0)
        handler_trace = {}

        def ping(payload):
            ctx = tracer.current()
            handler_trace["ctx"] = ctx
            return {"ok": True}

        rpc.register("Test.Ping", ping)
        rpc.start()
        plane = faults.install(faults.FaultPlane(seed=3))
        plane.rule(
            "rpc", "sever", method="Test.Ping", count=1
        )
        try:
            proxy = ServerProxy([rpc.address], pool=ConnPool(timeout=5.0))
            with tracer.root("job.submit") as root:
                out = proxy._call("Test.Ping", {})
            assert out == {"ok": True}
            trace_id = root.trace_id
            record = tracer.store.get(trace_id)
            assert record is not None
            rpc_spans = [
                s for s in record["spans"] if s["name"] == "rpc.Test.Ping"
            ]
            # the severed attempt AND the successful retry, same trace
            assert len(rpc_spans) == 2
            assert sum(1 for s in rpc_spans if s["error"]) == 1
            # the handler observed the propagated context
            assert handler_trace["ctx"] is not None
            assert handler_trace["ctx"].trace_id == trace_id
            server_spans = [
                s for s in record["spans"]
                if s["name"] == "rpc.server.Test.Ping"
            ]
            assert len(server_spans) == 1
            assert orphan_count(record) == 0
        finally:
            rpc.stop()


# ---------------------------------------------------------------------------
# behavior identity: tracing must not change placements or state
# ---------------------------------------------------------------------------


def _strip_times(obj):
    if isinstance(obj, dict):
        return {
            k: _strip_times(v)
            for k, v in obj.items()
            if not (isinstance(k, str) and k.endswith("time"))
        }
    if isinstance(obj, list):
        return [_strip_times(v) for v in obj]
    return obj


class TestTraceDeterminism:
    def test_placements_identical_traced_vs_untraced(self):
        """The seeded scheduler pass places byte-identically with
        tracing on (active root context, spans firing) vs off — spans
        consume no RNG and alter no ordering."""
        import bench
        from nomad_tpu.state import StateStore

        # ONE build, read-only passes (NullPlanner): the arms see the
        # identical world, differing ONLY in the tracing flag
        state = StateStore()
        state.upsert_nodes(1, bench.build_nodes(64))
        job = bench.build_job(300, spread=True)
        state.upsert_job(2, job)

        tracer.enabled = True
        with tracer.root("bench.pass"):
            _, placed_traced = bench.run_once(state, job, seed=11)
        tracer.enabled = False
        _, placed_untraced = bench.run_once(state, job, seed=11)
        tracer.enabled = True
        assert placed_traced, "nothing placed"
        assert json.dumps(placed_traced, sort_keys=True) == json.dumps(
            placed_untraced, sort_keys=True
        )

    def test_applied_state_identical_traced_vs_untraced(self):
        """The full commit path (verify → commit → store) produces
        identical persisted state (modulo wall-clock stamps) with
        tracing on vs off on a seeded cluster."""
        from nomad_tpu.core.plan_apply import Planner
        from nomad_tpu.state import StateStore

        def run(traced: bool):
            tracer.reset()
            tracer.enabled = traced
            store = StateStore()
            node = mock.node()
            node.id = "n-det"
            node.secret_id = "secret-det"
            node.node_resources.networks = []
            store.upsert_node(1, node)
            job = mock.job()
            job.id = "j-det"
            store.upsert_job(2, job)
            if traced:
                tracer.eval_root("ev-det")
            planner = Planner(store)
            planner.start()
            try:
                plan = TestApplyTimeoutBarrierSpan._mk_plan(
                    store, job, "det", "ev-det", 50, 4
                )
                pending = planner.queue.enqueue(plan)
                result, error = pending.wait(timeout=10)
                assert error is None and result is not None
            finally:
                planner.stop()
            return _strip_times(store.persist())

        traced_state = run(True)
        untraced_state = run(False)
        tracer.enabled = True
        assert json.dumps(
            traced_state, sort_keys=True, default=str
        ) == json.dumps(untraced_state, sort_keys=True, default=str)


# ---------------------------------------------------------------------------
# critical path analyzer
# ---------------------------------------------------------------------------


def _mk_record(stage_ms: dict, total_ms: float) -> dict:
    """Synthetic trace: root eval.e2e with sequential children."""
    spans = []
    cursor = 1000.0
    root_id = "root"
    for name, ms in stage_ms.items():
        spans.append({
            "trace_id": "t", "span_id": f"s-{name}", "parent_id": root_id,
            "name": name, "start": cursor, "duration_ms": ms,
            "tags": {}, "flags": [], "error": None,
        })
        cursor += ms / 1e3
    spans.append({
        "trace_id": "t", "span_id": root_id, "parent_id": None,
        "name": "eval.e2e", "start": 1000.0, "duration_ms": total_ms,
        "tags": {}, "flags": [], "error": None,
    })
    return {
        "trace_id": "t", "root": "eval.e2e", "start": 1000.0,
        "duration_ms": total_ms, "error": False, "spans": spans,
    }


class TestCriticalPath:
    def test_applier_tail_names_the_serialized_applier(self):
        """The ROADMAP item 2 shape: queue-wait dominates while
        plan.evaluate stays ~1-2ms → the verdict names the applier."""
        records = [
            _mk_record(
                {
                    "eval.evaluate": 2.0,
                    "plan.queue_wait": 200.0,
                    "plan.evaluate": 1.5,
                    "plan.commit": 30.0,
                },
                250.0,
            )
            for _ in range(10)
        ]
        report = attribute(records)
        assert report["bottleneck"] == "plan.queue_wait"
        assert "serialized plan applier" in report["verdict"]
        share = report["tail"]["stages"]["plan.queue_wait"]["share"]
        assert share > 0.5

    def test_commit_tail_names_consensus_not_the_applier(self):
        """Post-pipeline (PR 13): a plan.commit-dominated tail is raft
        consensus latency — the applier keeps verifying while entries
        commit — so the verdict must steer operators at raft/fold
        tuning, not the applier loop."""
        records = [
            _mk_record(
                {
                    "eval.evaluate": 2.0,
                    "plan.queue_wait": 3.0,
                    "plan.evaluate": 1.5,
                    "plan.commit": 200.0,
                },
                220.0,
            )
            for _ in range(10)
        ]
        report = attribute(records)
        assert report["bottleneck"] == "plan.commit"
        assert "consensus commit latency" in report["verdict"]
        assert "serialized plan applier" not in report["verdict"]

    def test_parent_self_time_excludes_children(self):
        record = _mk_record({"child": 40.0}, 100.0)
        from nomad_tpu.trace import attribute_trace

        acc, _ = attribute_trace(record)
        assert abs(acc["child"] - 0.040) < 1e-6
        assert abs(acc["eval.e2e"] - 0.060) < 1e-6

    def test_parallel_stages_reported_not_path_counted(self):
        """drain.device_compute overlaps the host tree by design: its
        time must not dilute the critical-path shares, but it must not
        vanish either."""
        record = _mk_record(
            {"eval.evaluate": 40.0, "drain.device_compute": 35.0}, 100.0
        )
        from nomad_tpu.trace import attribute_trace

        acc, par = attribute_trace(record)
        assert "drain.device_compute" not in acc
        assert abs(par["drain.device_compute"] - 0.035) < 1e-6
        report = attribute([record])
        assert "drain.device_compute" not in report["stages"]
        assert report["parallel"]["drain.device_compute"] > 0

    def test_orphan_detection(self):
        record = _mk_record({"a": 10.0}, 20.0)
        record["spans"].append({
            "trace_id": "t", "span_id": "orphan", "parent_id": "missing",
            "name": "lost", "start": 1000.0, "duration_ms": 1.0,
            "tags": {}, "flags": [], "error": None,
        })
        assert orphan_count(record) == 1

    def test_empty_store(self):
        report = attribute([])
        assert report["traces"] == 0
        assert report["verdict"] == "no retained traces"


# ---------------------------------------------------------------------------
# HTTP + CLI surfaces
# ---------------------------------------------------------------------------


class TestHttpTraceSurface:
    def test_trace_endpoints_serve_retained_trees(self):
        from nomad_tpu.api.client import APIError, ApiClient
        from nomad_tpu.api.http import HTTPServer

        server = make_server(num_workers=1)
        http = HTTPServer(server, port=0)
        http.start()
        try:
            for i in range(3):
                n = mock.node()
                n.id = f"node-{i}"
                server.node_register(n)
            client = ApiClient(address=f"http://127.0.0.1:{http.port}")
            out = client.register_job(simple_job("j-http").to_dict())
            eval_id = out["EvalID"]
            wait_evals_terminal(server, [eval_id])
            time.sleep(0.3)

            listing = client.traces(limit=10)
            assert listing["stats"]["retained"] >= 1
            assert listing["traces"], "trace list empty"
            trace_id = listing["traces"][0]["trace_id"]

            record = client.trace(trace_id)
            assert record["trace_id"] == trace_id
            assert record["orphans"] == 0
            names = {s["name"] for s in record["spans"]}
            # HTTP-minted root: submit → eval in one tree
            assert "job.submit" in names and "eval.e2e" in names

            report = client.trace_critical_path()
            assert report["traces"] >= 1
            assert report["bottleneck"] is not None

            with pytest.raises(APIError) as err:
                client.trace("deadbeef")
            assert err.value.status == 404

            # /v1/metrics carries trace-plane stats
            payload = client.metrics()
            assert payload["trace"]["retained"] >= 1
        finally:
            http.stop()
            server.stop()

    def test_cli_trace_commands(self, capsys):
        from nomad_tpu.api.http import HTTPServer
        from nomad_tpu.cli.main import main as cli_main

        server = make_server(num_workers=1)
        http = HTTPServer(server, port=0)
        http.start()
        try:
            for i in range(3):
                n = mock.node()
                n.id = f"node-{i}"
                server.node_register(n)
            eval_id = server.job_register(simple_job("j-cli"))
            wait_evals_terminal(server, [eval_id])
            time.sleep(0.3)
            addr = f"http://127.0.0.1:{http.port}"

            assert cli_main(["-address", addr, "trace", "list"]) == 0
            out = capsys.readouterr().out
            assert "retained=" in out
            trace_id = tracer.store.list(limit=1)[0]["trace_id"]

            assert cli_main(
                ["-address", addr, "trace", "get", trace_id]
            ) == 0
            out = capsys.readouterr().out
            assert "eval.e2e" in out and "orphans=0" in out

            assert cli_main(
                ["-address", addr, "trace", "critical-path"]
            ) == 0
            out = capsys.readouterr().out
            assert "verdict:" in out
        finally:
            http.stop()
            server.stop()


# ---------------------------------------------------------------------------
# span-hygiene checkers
# ---------------------------------------------------------------------------


class TestSpanHygieneChecker:
    def _run(self, src, rule):
        from nomad_tpu.analysis import Project, run

        project = Project.from_sources(
            {"nomad_tpu/core/fixture.py": src}
        )
        return [f for f in run(project, [rule])]

    def test_unclosed_manual_span_flagged(self):
        src = (
            "def f(tracer):\n"
            "    s = tracer.start_span('x')\n"
            "    s.set_tag('a', 1)\n"
        )
        findings = self._run(src, "span-unclosed")
        assert len(findings) == 1
        assert findings[0].rule == "span-unclosed"

    def test_with_span_and_finally_end_clean(self):
        src = (
            "def f(tracer):\n"
            "    with tracer.span('x'):\n"
            "        pass\n"
            "def g(tracer):\n"
            "    s = tracer.start_span('y')\n"
            "    try:\n"
            "        pass\n"
            "    finally:\n"
            "        s.end()\n"
        )
        assert self._run(src, "span-unclosed") == []

    def test_lock_held_blocking_in_span_flagged(self):
        src = (
            "def f(self, tracer):\n"
            "    with self._lock:\n"
            "        with tracer.span('x'):\n"
            "            self._cond.wait(1.0)\n"
        )
        findings = self._run(src, "span-lock-blocking")
        assert len(findings) == 1

    def test_lock_held_blocking_in_compound_header_flagged(self):
        src = (
            "def f(self, tracer):\n"
            "    with self._lock:\n"
            "        with tracer.span('x'):\n"
            "            if self._cond.wait(1.0):\n"
            "                pass\n"
        )
        findings = self._run(src, "span-lock-blocking")
        assert len(findings) == 1

    def test_blocking_in_span_without_lock_clean(self):
        src = (
            "def f(self, tracer):\n"
            "    with tracer.span('x'):\n"
            "        self._event.wait(1.0)\n"
        )
        assert self._run(src, "span-lock-blocking") == []

    def test_out_of_scope_paths_exempt(self):
        from nomad_tpu.analysis import Project, run

        src = "def f(tracer):\n    s = tracer.start_span('x')\n"
        project = Project.from_sources(
            {"nomad_tpu/loadgen/fixture.py": src}
        )
        assert run(project, ["span-unclosed"]) == []


# ---------------------------------------------------------------------------
# tier-1 overhead gate
# ---------------------------------------------------------------------------


class TestTraceOverheadGate:
    #: pinned floor for the headline pass (BENCH r4 best 0.389s on the
    #: driver box) — the per-eval trace budget derives from it so the
    #: gate can't drift silently when the bench gets faster
    HEADLINE_FLOOR_S = 0.35

    def test_per_eval_trace_cost_within_pinned_budget(self):
        """The headline eval runs ONE trace (a root + ~a dozen spans +
        a few cross-thread records). Gate: that per-eval cost must stay
        under the pinned overhead budget applied to the headline floor —
        microbenched, so CI noise on the shared box can't flake a full
        A/B while still bounding the same quantity bench.py reports as
        trace_overhead_pct."""
        from bench import TRACE_OVERHEAD_BUDGET_PCT

        budget_s = self.HEADLINE_FLOOR_S * TRACE_OVERHEAD_BUDGET_PCT / 100
        n = 300
        t0 = time.monotonic()
        for i in range(n):
            eval_id = f"ev-bench-{i}"
            tracer.eval_root(eval_id, tags={"job": "j"})
            ctx = tracer.ctx_for_eval(eval_id)
            with tracer.span("worker.process", parent=ctx):
                with tracer.span("eval.evaluate", metric="bench.m"):
                    pass
                with tracer.span("plan.submit", metric="plan.submit"):
                    pass
            now = time.monotonic()
            tracer.record_span(
                "plan.queue_wait", ctx, now - 0.001, now,
                metric="plan.queue_wait",
            )
            tracer.record_span("plan.commit", ctx, now, now)
            tracer.record_span("fsm.apply_plan", ctx, now, now)
            tracer.finish_eval(eval_id)
        per_eval = (time.monotonic() - t0) / n
        assert per_eval < budget_s, (
            f"per-eval trace cost {per_eval * 1e3:.2f}ms exceeds the "
            f"pinned budget {budget_s * 1e3:.1f}ms "
            f"({TRACE_OVERHEAD_BUDGET_PCT}% of the "
            f"{self.HEADLINE_FLOOR_S}s headline floor)"
        )
        # retention stayed bounded through the churn
        stats = tracer.stats()
        assert stats["retained"] <= (
            tracer.store.retain
            + tracer.store.slow_keep
            + tracer.store.error_keep
        )
