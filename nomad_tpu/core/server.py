"""Server core: raft-replicated control plane wiring state, FSM, broker,
plan applier, workers, heartbeats, and the RPC endpoint surface
(ref nomad/server.go, nomad/leader.go, nomad/*_endpoint.go).

Every state mutation flows through ``_apply`` → raft log → FSM → state
store, exactly as the reference routes writes through raftApply
(nomad/rpc.go). Leader-only subsystems (eval broker, blocked-evals
tracker, plan queue, heartbeat timers, failed-eval reaper) are enabled in
``_establish_leadership`` and disabled in ``_revoke_leadership``
(ref leader.go:180 establishLeadership / revokeLeadership). A single-node
server bootstraps itself as leader in milliseconds (the reference's
-dev mode with in-memory raft, server.go:105).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import Optional

from .. import metrics
from ..testing import faults as _faults
from ..raft import InmemTransport, NotLeaderError, Raft, RaftConfig
from ..raft.log import InmemLogStore, SnapshotStore, StableStore
from ..state.store import StateStore
from ..structs.model import (
    EVAL_STATUS_CANCELLED,
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    EVAL_TRIGGER_RETRY_FAILED_ALLOC,
    JOB_MAX_PRIORITY,
    JOB_MIN_PRIORITY,
    JOB_TYPE_BATCH,
    JOB_TYPE_CORE,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSTEM,
    NODE_STATUS_DOWN,
    NODE_STATUS_READY,
    Allocation,
    Evaluation,
    Job,
    Node,
    fast_alloc_clone,
    generate_uuid,
    now_ns,
)
from ..structs.node_class import compute_class
from . import fsm as fsm_mod
from .blocked_evals import BlockedEvals
from .broker import EvalBroker, shared_timer_wheel
from .deployment_watcher import DeploymentsWatcher, install_deployment_endpoints
from .drainer import NodeDrainer
from . import overload as overload_mod
from .overload import OverloadController, current_deadline
from .periodic import PeriodicDispatch, derive_dispatch_job
from .fsm import FSM
from .plan_apply import Planner
from .worker import Worker

logger = logging.getLogger("nomad_tpu.server")

DEFAULT_HEARTBEAT_TTL = 30.0
#: seconds a failed proxy HTTP address stays quarantined
HTTP_ADDR_QUARANTINE = 10.0


class Server:
    """ref nomad/server.go:91"""

    def __init__(self, config: Optional[dict] = None):
        self.config = config or {}
        # trace{} stanza (OBSERVABILITY.md): enabled, sample_rate,
        # retain, slow_keep, error_keep. The tracer is process-wide
        # (metrics-registry idiom); only keys present are applied, so
        # multiple in-process servers don't fight over defaults
        trace_cfg = self.config.get("trace")
        if trace_cfg:
            from ..trace import tracer as _tracer

            _tracer.configure(**trace_cfg)
        self.state = StateStore()
        # plan_pipeline{} stanza (OBSERVABILITY.md): the applier pipeline
        # depth, the device dense-verify gate, and the eval broker's
        # ready-queue shard count all tune the ROADMAP item 1 knee
        pp_cfg = dict(self.config.get("plan_pipeline") or {})
        self.eval_broker = EvalBroker(
            nack_timeout=self.config.get("nack_timeout", 60.0),
            delivery_limit=self.config.get("delivery_limit", 3),
            initial_nack_delay=self.config.get("initial_nack_delay", 1.0),
            subsequent_nack_delay=self.config.get("subsequent_nack_delay", 20.0),
            ready_shards=int(pp_cfg.get("ready_shards", 1)),
        )
        self.blocked_evals = BlockedEvals(self.eval_broker)
        self.periodic = None  # PeriodicDispatch attaches in agent wiring
        self.deployment_watcher = None  # set by DeploymentsWatcher below
        self.drainer = None
        # coarse time→index witness map feeding GC thresholds
        # (ref fsm.go TimeTable; not snapshot-persisted — after a restart
        # the table refills and GC conservatively pauses for one threshold)
        from .core_sched import TimeTable

        self.time_table = TimeTable(
            granularity=float(self.config.get("time_table_granularity", 60.0))
        )
        # cluster event stream (events/broker.py): FSM-sourced, so every
        # server — leader or follower — can serve /v1/event/stream.
        # Configured by the telemetry-style event_broker{} stanza; on by
        # default (the ring is a few thousand slim dicts).
        eb_cfg = self.config.get("event_broker") or {}
        self.event_broker = None
        if eb_cfg.get("enabled", True):
            from ..events import EventBroker

            self.event_broker = EventBroker(
                size=int(eb_cfg.get("event_buffer_size", 4096)),
                subscriber_buffer=int(eb_cfg.get("subscriber_buffer", 1024)),
                # snapshot-on-subscribe reads the store's COW generations
                # (state/store.py snapshot_events): cold watchers start
                # from a consistent snapshot at index N instead of full
                # blocking queries, and lost-gap resumes become
                # snapshot+deltas
                state=self.state,
                snapshot_on_subscribe=bool(
                    eb_cfg.get("snapshot_on_subscribe", True)
                ),
                max_subscribers=int(eb_cfg.get("max_subscribers", 0)),
                frame_batch=int(eb_cfg.get("frame_batch", 64)),
            )
        self.fsm = FSM(
            state=self.state,
            eval_broker=self.eval_broker,
            blocked_evals=self.blocked_evals,
            time_table=self.time_table,
            event_broker=self.event_broker,
        )
        # committed-plane columnar view (tpu/mirror.py): the TPU drain
        # path's dense state plane. The planes themselves live in the
        # state store and are patched by the same write transaction that
        # swaps the tables (state/planes.py), so the view needs no event
        # subscription and is constructed unconditionally.
        from ..tpu.mirror import ColumnarMirror

        self.columnar_mirror = ColumnarMirror(self.state)
        # operator debug plane (nomad_tpu/debug; OBSERVABILITY.md): the
        # flight recorder is the whole-process tape the watchdog rules
        # and debug bundles read. Constructed always (cheap: one deque),
        # its sampling thread starts with the server unless the debug{}
        # stanza disables it. Bundles auto-capture on watchdog trips
        # only when a bundle_dir is configured — a default agent never
        # surprises the operator with disk writes.
        dbg_cfg = dict(self.config.get("debug") or {})
        from ..debug import FlightRecorder, Watchdog

        self.flight_recorder = FlightRecorder(
            self,
            interval=float(dbg_cfg.get("flight_interval", 1.0)),
            retain=int(dbg_cfg.get("flight_retain", 512)),
        )
        self.watchdog = None
        wd_cfg = dbg_cfg.get("watchdog", {})
        if wd_cfg is not False:
            self.watchdog = Watchdog(
                self,
                self.flight_recorder,
                config=wd_cfg if isinstance(wd_cfg, dict) else {},
                bundle_dir=str(dbg_cfg.get("bundle_dir") or ""),
            )
            self.flight_recorder.observer = self.watchdog.on_sample
        self._flight_enabled = bool(dbg_cfg.get("flight_recorder", True))
        # overload control plane (core/overload.py; OBSERVABILITY.md "The
        # overload plane"): constructed ONLY when the overload{} stanza
        # is present — no stanza means no admission, no brownout, no
        # default deadline: byte-identical pre-overload behavior (the
        # A/B contract pinned by tests/test_overload.py)
        self.overload: Optional[OverloadController] = None
        # stream-shed hooks: the HTTP layer's StreamMux registers its
        # set_class_shed here (the core server doesn't own the HTTP
        # plane — the CLI wires them, so this is a callback seam). With
        # no overload plane the ladder never reaches the stream rungs
        # and registered hooks are never invoked.
        # nta: ignore[unbounded-cache] WHY: one registration per stream
        # mux, and a server wires at most one HTTP layer — growth is
        # O(process wiring), not O(traffic); hooks live for the server.
        self._stream_shed_hooks: list = []
        self._stream_shed_on: set = set()
        ov_cfg = dict(self.config.get("overload") or {})
        if ov_cfg and ov_cfg.get("enabled", True):
            self.overload = OverloadController(
                ov_cfg,
                load_fn=self._overload_load,
                brownout_actions=self._brownout_actions(),
            )
            # the broker refuses expired evals at dequeue; this callback
            # turns each refusal into a terminal failed-eval update so
            # the submitter sees a loud outcome, never a vanished eval
            self.eval_broker.on_deadline_exceeded = (
                lambda ev: self.eval_deadline_exceeded(ev, "broker")
            )
            # drive the brownout ladder at the flight recorder's cadence,
            # chained in FRONT of the watchdog observer so both see every
            # sample (brownout transitions are deterministic per run)
            prev_observer = self.flight_recorder.observer

            def _overload_observer(sample, _prev=prev_observer):
                try:
                    self.overload.on_sample()
                except Exception:
                    logger.exception("overload on_sample failed")
                if _prev is not None:
                    _prev(sample)

            self.flight_recorder.observer = _overload_observer
        self.planner = Planner(self.state)
        # max independently-verified plans folded into ONE raft entry
        # (server stanza `plan_apply_batch`; the observed fold sizes are
        # exported as the plan.apply_batch_size histogram in /v1/metrics)
        self.planner.max_apply_batch = max(
            1, int(self.config.get("plan_apply_batch",
                                   self.planner.max_apply_batch))
        )
        # applier pipeline knobs (plan_pipeline{}): commit-overlap depth
        # and the device-resident dense verify against the mirror planes
        self.planner.max_inflight = max(
            1, int(pp_cfg.get("max_inflight", self.planner.max_inflight))
        )
        self.planner.device_verify = bool(pp_cfg.get("device_verify", True))
        self.planner.device_verify_min = int(
            pp_cfg.get("device_verify_min", self.planner.device_verify_min)
        )
        # late-bound: the mirror is constructed above but may be closed/
        # absent; the applier degrades to the host oracle either way
        self.planner.mirror_fn = lambda: self.columnar_mirror
        self.planner.commit_fn = self._commit_plan
        self.planner.commit_batch_fn = self._commit_plan_batch
        self.planner.barrier_fn = self._plan_commit_barrier
        self.planner.preemption_evals_fn = self._make_preemption_evals
        self.planner.token_check_fn = self._plan_token_live
        self.workers: list[Worker] = []
        self.heartbeat_ttl = self.config.get("heartbeat_ttl", DEFAULT_HEARTBEAT_TTL)
        # node id -> cancelable handle on the SHARED timer wheel. These
        # were threading.Timer — one OS thread per tracked node for the
        # whole TTL, which capped the fleet at the environment's thread
        # limit (~4K); the 10K-node churn soak dies there instantly
        self._heartbeat_timers: dict = {}
        # expiry handoff: the wheel runs callbacks inline on its ONE
        # process-wide thread, and an expiry is two raft applies + eval
        # fan-out — thousands at once when a leader loses its clients —
        # so the wheel callback only enqueues here; a lazily-started
        # per-server drainer does the work
        self._hb_expire_q: queue.Queue = queue.Queue()
        self._hb_expire_thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._running = False
        self._leader = False
        self._leader_cond = threading.Condition()
        self._reaper: Optional[threading.Thread] = None
        self._gc_scheduler: Optional[threading.Thread] = None
        #: this server's advertised HTTP address (set by HTTPServer.start
        #: via advertise_http); served to peers over Status.HTTPAddr
        self.http_advertise_addr: Optional[str] = None
        #: rpc_addr → peer HTTP address learned over Status.HTTPAddr
        self._peer_http_addrs: dict[str, str] = {}
        #: http addr → monotonic time a proxy to it last failed
        self._bad_http_addrs: dict[str, float] = {}
        # both maps are touched from concurrent HTTP handler threads;
        # check-then-pop sequences need real mutual exclusion, and expired
        # quarantine entries are pruned so the map can't grow unboundedly
        self._http_addr_lock = threading.Lock()
        # secret → compiled ACL, invalidated by acl table indexes in the key
        self._acl_cache: dict = {}

        DeploymentsWatcher(self)  # installs itself as self.deployment_watcher
        NodeDrainer(self)  # installs itself as self.drainer
        PeriodicDispatch(self)  # attaches as self.periodic + FSM hook
        #: this server's region; regions are independent raft domains
        #: federated over gossip (ref regions_endpoint.go, serf.go WAN)
        self.region = self.config.get("region", "global")
        #: ACL-replication health, fed by replicate_acl_once and read by
        #: the flight recorder (debug/flight.py) so the per-region
        #: acl_replication_lag watchdog rule can see replication stall
        #: while it is happening. Keys: configured, authoritative_region,
        #: rounds, failures, last_success_wall, started_wall, last_error.
        self.acl_replication_status: dict = {"configured": False}
        self.raft = self._setup_raft()
        #: members with a grace-delayed voter-removal recheck in flight
        #: (one per member; see _remove_dead_server_after_grace)
        self._dead_server_pending: set = set()
        self._dead_server_lock = threading.Lock()
        self.gossip = self._setup_gossip()
        from .vault import VaultClient

        self.vault = VaultClient(self)

    # ------------------------------------------------------------------
    # raft wiring (ref server.go:1075 setupRaft)
    # ------------------------------------------------------------------
    def _setup_raft(self) -> Raft:
        rc = self.config.get("raft", {})
        node_id = rc.get("node_id", self.config.get("name", "server-1"))
        address = rc.get("address", node_id)
        if self.config.get("gossip") and not self.config.get("bootstrap"):
            # gossip auto-discovery (ref serf.go): non-bootstrap servers
            # start with no voters and wait for the leader to add them via
            # a raft CONFIG entry — they never self-elect
            voters = rc.get("voters", {})
        else:
            voters = rc.get("voters", {node_id: address})
        single = len(voters) == 1
        # timing knobs (``raft`` stanza): the dev defaults are tuned for
        # an idle box — multi-server clusters under real load (and the
        # federated chaos topology, which runs many servers in one
        # process) need election timeouts with GIL-stall headroom, or
        # followers fire elections against a perfectly healthy leader
        raft_config = rc.get("config") or RaftConfig(
            # single-voter dev servers elect in ~10ms (raftInmem dev mode)
            heartbeat_interval=rc.get(
                "heartbeat_interval", 0.02 if single else 0.05
            ),
            election_timeout_min=rc.get(
                "election_timeout_min", 0.01 if single else 0.15
            ),
            election_timeout_max=rc.get(
                "election_timeout_max", 0.03 if single else 0.30
            ),
            snapshot_threshold=rc.get("snapshot_threshold", 8192),
        )
        return Raft(
            node_id=node_id,
            address=address,
            voters=voters,
            fsm=self.fsm,
            transport=rc.get("transport") or InmemTransport(),
            log_store=rc.get("log_store") or InmemLogStore(),
            stable=rc.get("stable") or StableStore(),
            snapshots=rc.get("snapshots") or SnapshotStore(),
            config=raft_config,
            on_leadership=self._leadership_changed,
        )

    def _setup_gossip(self):
        """Gossip membership wiring (ref nomad/serf.go setupSerf +
        serf event handler feeding raft membership)."""
        gcfg = self.config.get("gossip")
        if not gcfg:
            return None
        import random as random_mod

        from ..gossip import Gossip

        seed = self.config.get("seed")
        return Gossip(
            name=self.raft.node_id,
            bind=tuple(gcfg.get("bind", ("127.0.0.1", 0))),
            tags={
                "raft": self.raft.address,
                "role": "server",
                "region": self.region,
            },
            probe_interval=float(gcfg.get("probe_interval", 0.3)),
            ack_timeout=float(gcfg.get("ack_timeout", 0.3)),
            suspect_timeout=float(gcfg.get("suspect_timeout", 1.5)),
            reap_timeout=float(gcfg.get("reap_timeout", 3.0)),
            on_event=self._gossip_event,
            rng=random_mod.Random(seed),
            # serf encryption: server { encrypt = "<base64>" } in agent HCL
            encrypt_key=gcfg.get("encrypt")
            or self.config.get("encrypt", ""),
            # runtime-installed keys survive restarts when a data dir
            # exists (serf's keyring file)
            keyring_path=(
                os.path.join(self.config["data_dir"], "keyring.json")
                if self.config.get("data_dir")
                else ""
            ),
        )

    def _gossip_event(self, event: str, member):
        """Serf events → raft membership, leader-side only (followers
        converge through the replicated CONFIG entries); ref serf.go
        nodeJoin/nodeFailed + autopilot dead-server cleanup."""
        if not self._leader:
            return
        # regions are independent raft domains joined only by gossip
        # (ref serf.go WAN federation): never add a foreign region's
        # server as a voter
        if member.tags.get("region", "global") != self.region:
            return
        try:
            if event == "join":
                raft_addr = member.tags.get("raft")
                if raft_addr and self.raft.voters.get(member.name) != raft_addr:
                    # new server, or a known server back with a different
                    # raft address (restart with dynamic bind): either way
                    # the CONFIG entry carries the current address
                    logger.info("gossip: adding server %s to raft", member.name)
                    self.raft.add_voter(member.name, raft_addr)
            elif event in ("dead", "leave", "reap"):
                # intentional leaves always deregister; crash-failures are
                # reaped only when autopilot dead-server cleanup is on
                # (ref autopilot.go pruneDeadServers)
                if event == "dead" and not self.autopilot_config().get(
                    "cleanup_dead_servers", True
                ):
                    return
                if member.name not in self.raft.voters:
                    return
                if event == "leave":
                    # a leave is the member's own statement — no stale-
                    # record race to absorb, remove immediately
                    logger.info(
                        "gossip: removing server %s from raft", member.name
                    )
                    self.raft.remove_voter(member.name)
                else:
                    self._remove_dead_server_after_grace(member.name)
        except NotLeaderError:
            pass
        except Exception:
            logger.exception("gossip membership change failed")

    # ------------------------------------------------------------------
    # Autopilot + operator membership surface (ref nomad/autopilot.go,
    # nomad/operator_endpoint.go, command/agent/agent_endpoint.go)
    # ------------------------------------------------------------------
    DEFAULT_AUTOPILOT = {
        "cleanup_dead_servers": True,
        "last_contact_threshold_s": 0.2,
        "max_trailing_logs": 250,
        "server_stabilization_time_s": 10.0,
        #: seconds a dead/reaped member must STAY dead before its voter
        #: record is removed (ref autopilot.go pruneDeadServers running
        #: on an interval, never instantly on the serf event). The grace
        #: absorbs stale death records: after a WAN partition heals, the
        #: far side's DEAD record for a live local server can arrive
        #: moments before that server's refutation — instant removal
        #: then splits the voter map and starts an election war.
        "dead_server_grace_s": 3.0,
    }

    def autopilot_config(self) -> dict:
        cfg = dict(self.DEFAULT_AUTOPILOT)
        cfg.update(self.state.autopilot_config() or {})
        return cfg

    def set_autopilot_config(self, config: dict):
        """Validate and persist the autopilot overrides. Only known keys
        with the right types are stored (a stray string duration would
        otherwise 500 every future health check), and defaults are NOT
        folded in — future default changes must still apply."""
        cleaned = {}
        for key, value in (config or {}).items():
            if key not in self.DEFAULT_AUTOPILOT:
                raise ValueError(f"unknown autopilot setting: {key}")
            default = self.DEFAULT_AUTOPILOT[key]
            if isinstance(default, bool):
                if not isinstance(value, bool):
                    raise ValueError(f"autopilot setting {key} must be a bool")
            elif isinstance(default, (int, float)):
                if isinstance(value, bool) or not isinstance(
                    value, (int, float)
                ):
                    raise ValueError(
                        f"autopilot setting {key} must be a number"
                    )
                value = float(value)
            cleaned[key] = value
        self._apply(fsm_mod.AUTOPILOT_CONFIG, {"config": cleaned})

    def members(self) -> list[dict]:
        """Gossip membership view (ref agent_endpoint.go AgentMembersRequest).
        Without gossip (dev/static clusters) synthesizes records from the
        raft voter map."""
        if self.gossip is not None:
            with self.gossip._lock:
                rows = [
                    {
                        "Name": m.name,
                        "Addr": m.host,
                        "Port": m.port,
                        "Status": m.status,
                        "Tags": dict(m.tags),
                    }
                    for m in self.gossip.members.values()
                ]
            return sorted(rows, key=lambda r: r["Name"])
        return [
            {
                "Name": node_id,
                "Addr": addr,
                "Port": 0,
                "Status": "alive",
                "Tags": {"raft": addr, "role": "server", "region": self.region},
            }
            for node_id, addr in sorted(self.raft.voters_snapshot().items())
        ]

    def gossip_join(self, addresses: list) -> int:
        """Join one or more gossip seeds; returns how many succeeded
        (ref agent.go Join)."""
        if self.gossip is None:
            raise RuntimeError("gossip is not enabled on this server")
        joined = 0
        for addr in addresses:
            host, _, port = str(addr).rpartition(":")
            if self.gossip.join((host or "127.0.0.1", int(port)), timeout=3.0):
                joined += 1
        return joined

    def gossip_force_leave(self, name: str) -> bool:
        """Force a failed member out of gossip (and, via the leave event,
        out of raft); ref agent.go ForceLeave → serf RemoveFailedNode."""
        if self.gossip is None:
            raise RuntimeError("gossip is not enabled on this server")
        return self.gossip.force_leave(name)

    def raft_configuration(self) -> dict:
        """ref operator_endpoint.go RaftGetConfiguration"""
        leader_id = getattr(self.raft, "leader_id", None)
        servers = []
        for node_id, addr in sorted(self.raft.voters_snapshot().items()):
            servers.append(
                {
                    "ID": node_id,
                    "Node": node_id,
                    "Address": addr,
                    "Leader": self.raft.is_leader()
                    and node_id == self.raft.node_id
                    or node_id == leader_id,
                    "Voter": True,
                }
            )
        return {"Servers": servers, "Index": self.state.latest_index()}

    def raft_remove_peer(self, node_id: str):
        """ref operator_endpoint.go RaftRemovePeerByID"""
        self._check_leader()
        if node_id not in self.raft.voters_snapshot():
            raise KeyError(f"no raft peer with id {node_id}")
        self.raft.remove_voter(node_id)

    def autopilot_health(self) -> dict:
        """Per-server health from leader replication progress + gossip
        status (ref autopilot ServerHealth/OperatorServerHealth)."""
        cfg = self.autopilot_config()
        progress = self.raft.peer_progress() if self.raft.is_leader() else {}
        gossip_status = {}
        if self.gossip is not None:
            with self.gossip._lock:
                gossip_status = {
                    m.name: m.status for m in self.gossip.members.values()
                }
        leader_last, _ = (
            self.raft._last_log() if self.raft.is_leader() else (0, 0)
        )
        servers = []
        healthy_all = True
        for node_id, addr in sorted(self.raft.voters_snapshot().items()):
            prog = progress.get(node_id, {})
            contact = prog.get("last_contact_s")
            trailing = (
                leader_last - prog.get("match_index", 0)
                if prog
                else None
            )
            alive = gossip_status.get(node_id, "alive") == "alive"
            healthy = alive and (
                node_id == self.raft.node_id
                or not self.raft.is_leader()
                or (
                    contact is not None
                    and contact <= cfg["last_contact_threshold_s"]
                    and trailing is not None
                    and trailing <= cfg["max_trailing_logs"]
                )
            )
            healthy_all = healthy_all and healthy
            servers.append(
                {
                    "ID": node_id,
                    "Name": node_id,
                    "Address": addr,
                    "SerfStatus": gossip_status.get(node_id, "alive"),
                    "LastContact": contact,
                    "TrailingLogs": trailing,
                    "Leader": prog.get("leader", False),
                    "Healthy": healthy,
                    "Voter": True,
                }
            )
        failure_tolerance = max(0, (len(servers) - 1) // 2) if servers else 0
        return {
            "Healthy": healthy_all,
            "FailureTolerance": failure_tolerance,
            "Servers": servers,
        }

    # ------------------------------------------------------------------
    # Regions (ref nomad/regions_endpoint.go + rpc.go region forwarding)
    # ------------------------------------------------------------------
    def regions(self) -> list[str]:
        """All regions known through gossip, self included."""
        out = {self.region}
        if self.gossip is not None:
            for member in self.gossip.alive_members():
                region = member.tags.get("region")
                if region:
                    out.add(region)
        return sorted(out)

    def region_http_servers(self, region: str) -> list[str]:
        """HTTP addresses of alive servers in ``region`` (from gossip
        tags) — the region-forwarding table."""
        if self.gossip is None:
            return []
        out = []
        for member in self.gossip.alive_members():
            if member.tags.get("region") == region and member.tags.get("http"):
                out.append(member.tags["http"])
        return out

    def advertise_http(self, address: str):
        """Publish this server's HTTP address: always recorded locally (the
        Status.HTTPAddr RPC serves it to peers, so leader forwarding works
        in voters-only topologies) and additionally into gossip tags so
        other regions can forward to it."""
        self.http_advertise_addr = address
        if self.gossip is None:
            return
        self.gossip.set_tags({"http": address})

    def _conn_pool(self):
        """The server's outbound RPC pool (client-fs forwarding, exec
        bridging, peer Status lookups), created on first use so the mTLS
        client context attached during agent wiring is picked up."""
        pool = getattr(self, "_outbound_pool", None)
        if pool is None:
            from ..rpc import ConnPool

            pool = self._outbound_pool = ConnPool(
                tls_context=getattr(self, "tls_client_context", None)
            )
        return pool

    def resolve_server_http_addr(
        self, server_id: Optional[str], rpc_addr: Optional[str]
    ) -> Optional[str]:
        """HTTP address of the peer server ``server_id``/``rpc_addr``, for
        follower→leader request forwarding (ref nomad/rpc.go:280-340
        forward(): the reference forwards over its server RPC connections
        and never needs an HTTP address map — here the HTTP proxy layer
        asks the peer for its HTTP address over that same RPC tier).

        Resolution order: gossip tags and the static ``server_http_addrs``
        config (both free, possibly absent), then a Status.HTTPAddr RPC to
        the peer's raft/RPC address — which every server always knows from
        its voter map, so this works with no gossip configured. RPC
        answers are cached per rpc_addr. A failed proxy reports back via
        ``forget_server_http_addr``, which quarantines the bad address for
        a few seconds so a stale gossip tag / static entry / cached answer
        can't shadow the live sources forever (a peer restarted onto a new
        HTTP port)."""

        def ok(addr):
            if not addr:
                return False
            with self._http_addr_lock:
                bad_at = self._bad_http_addrs.get(addr)
                if (
                    bad_at is not None
                    and time.monotonic() - bad_at > HTTP_ADDR_QUARANTINE
                ):
                    # quarantine served its term; stop tracking the addr
                    del self._bad_http_addrs[addr]
                    bad_at = None
            return bad_at is None

        if server_id:
            if self.gossip is not None:
                with self.gossip._lock:
                    member = self.gossip.members.get(server_id)
                if member is not None and ok(member.tags.get("http")):
                    return member.tags["http"]
            static = (self.config.get("server_http_addrs") or {}).get(
                server_id
            )
            if ok(static):
                return static
        if not rpc_addr:
            return None
        with self._http_addr_lock:
            cached = self._peer_http_addrs.get(rpc_addr)
        if ok(cached):
            return cached
        try:
            resp = self._conn_pool().call(
                rpc_addr, "Status.HTTPAddr", {}, timeout=5.0
            )
        except Exception:
            return None
        addr = (resp or {}).get("http_addr")
        if addr:
            with self._http_addr_lock:
                self._peer_http_addrs[rpc_addr] = addr
                self._bad_http_addrs.pop(addr, None)
        return addr

    def forget_server_http_addr(
        self, rpc_addr: Optional[str], http_addr: Optional[str] = None
    ):
        """Record a failed proxy target: drops the RPC-learned cache entry
        and quarantines ``http_addr`` so gossip/static sources holding the
        same stale value are skipped on the next resolution."""
        now = time.monotonic()
        with self._http_addr_lock:
            self._peer_http_addrs.pop(rpc_addr, None)
            if http_addr:
                self._bad_http_addrs[http_addr] = now
            # sweep quarantine entries past their term: failed addrs must
            # not accumulate forever (ADVICE r5 low)
            expired = [
                a
                for a, t0 in self._bad_http_addrs.items()
                if now - t0 > HTTP_ADDR_QUARANTINE
            ]
            for a in expired:
                del self._bad_http_addrs[a]

    def _reconcile_gossip_members(self):
        """On leadership: fold the current gossip view into raft membership
        both ways — joins a previous leader never applied AND removals it
        never committed (a follower drops dead/reap events at the leader
        guard, and swim reaps the record entirely, so without this sweep a
        dead server would stay a quorum-counted voter forever)."""
        if self.gossip is None:
            return
        alive = {m.name: m for m in self.gossip.alive_members()}
        for member in alive.values():
            if member.name == self.raft.node_id:
                continue
            self._gossip_event("join", member)
        for voter in self.raft.voters_snapshot():
            if voter == self.raft.node_id or voter in alive:
                continue
            with_status = self.gossip.members.get(voter)
            if with_status is not None and with_status.status == "suspect":
                continue  # possibly flapping; the dead event will decide
            # same grace as the dead event: a leadership change right
            # after a partition heal sees the far side's stale DEAD
            # records before the refutations arrive — removing on that
            # snapshot splits the voter map
            self._remove_dead_server_after_grace(voter)

    def _remove_dead_server_after_grace(self, name: str):
        """Schedule a voter removal that only fires if ``name`` is STILL
        not alive after ``autopilot.dead_server_grace_s`` (one pending
        recheck per member). Ref autopilot.go pruneDeadServers: cleanup
        is periodic, never instant on a serf event, exactly so a stale
        death record can be refuted before it costs a voter."""
        grace = float(
            self.autopilot_config().get("dead_server_grace_s", 3.0)
        )
        with self._dead_server_lock:
            if name in self._dead_server_pending:
                return
            self._dead_server_pending.add(name)

        def recheck():
            with self._dead_server_lock:
                self._dead_server_pending.discard(name)
            if not self._running or not self._leader:
                return
            member = (
                self.gossip.members.get(name)
                if self.gossip is not None
                else None
            )
            if member is not None and member.status == "alive":
                return  # refuted within the grace — a live server keeps its seat
            if name not in self.raft.voters:
                return
            try:
                logger.info(
                    "gossip: removing dead server %s from raft", name
                )
                self.raft.remove_voter(name)
            except NotLeaderError:
                pass
            except Exception:
                logger.exception("dead-server removal failed")

        def recheck_async():
            # remove_voter blocks on the CONFIG commit (up to its 5s
            # timeout when quorum is strained) — never on the shared
            # timer wheel's thread, where it would stall every broker
            # nack/heartbeat timer behind it
            threading.Thread(
                target=recheck, daemon=True, name=f"dead-server-rm-{name}"
            ).start()

        if grace <= 0:
            recheck_async()
        else:
            shared_timer_wheel().arm(grace, recheck_async, ())

    def _apply(self, msg_type: str, payload: dict):
        """Propose a write through consensus (ref nomad/rpc.go raftApply).
        Raises NotLeaderError with a leader hint; the RPC layer forwards."""
        return self.raft.apply(msg_type, payload)

    def _check_leader(self):
        """Forward-first semantics: leader-only endpoints reject on
        followers BEFORE reading local (possibly stale) state, so the RPC
        layer retries at the leader (ref nomad/rpc.go forward(), called at
        the top of every endpoint)."""
        if not self.raft.is_leader():
            raise NotLeaderError(
                self.raft.leader_address(), self.raft.leader_id
            )

    def attach_periodic(self, dispatcher):
        """Attach the leader's periodic dispatcher; the FSM tracks periodic
        jobs as registrations apply (ref fsm.go periodicDispatcher field)."""
        self.periodic = dispatcher
        self.fsm.periodic_dispatcher = dispatcher
        if self._leader:
            dispatcher.set_enabled(True)
            dispatcher.restore(self.state)

    def _commit_plan(self, plan, result, preemption_evals):
        """Replicate one verified plan result via consensus."""
        return self._apply(
            fsm_mod.APPLY_PLAN_RESULTS,
            self._plan_payload(plan, result, preemption_evals),
        )

    def _plan_commit_barrier(self, exc):
        """Resolve an INDETERMINATE plan commit (raft apply timeout): a
        barrier committed behind the timed-out entry applying in the same
        leadership proves — by log matching — that the entry applied too.
        Same leadership must be PROVEN, not assumed: if the term moved at
        any point since the entry was proposed (terms are monotonic, so a
        changed current term is conclusive), an intervening leader may
        have truncated the entry — the resolution fails and the applier
        falls back to flooring its snapshots past the entry. Generous
        timeout: under storm backlog the barrier waits out the same apply
        queue that made the commit slow in the first place."""
        self.raft.barrier(timeout=120.0)
        term = getattr(exc, "raft_term", 0)
        if term and self.raft.current_term != term:
            raise RuntimeError(
                f"plan commit entry {exc.raft_index} unresolvable: term "
                f"moved {term} -> {self.raft.current_term} during the wait"
            )

    def _commit_plan_batch(self, items):
        """Replicate several independently-verified plan results in ONE
        raft entry (one fsync + round-trip for the whole batch; the FSM
        applies them sequentially). ``items`` =
        [(plan, result, preemption_evals), ...] in verify order."""
        if len(items) == 1:
            return self._commit_plan(*items[0])
        return self._apply(
            fsm_mod.APPLY_PLAN_RESULTS_BATCH,
            {"plans": [self._plan_payload(*item) for item in items]},
        )

    def _plan_payload(self, plan, result, preemption_evals) -> dict:
        """The raft payload for a verified plan result — NORMALIZED (the
        reference's plan normalization for raft-log size, structs.go
        Plan.NormalizeAllocations):
        the plan ships without its alloc maps (the result carries the
        verified subset), and stopped/preempted allocs ship as id+field
        diffs the FSM rehydrates from each replica's own state, since the
        full documents are already replicated there. Only fresh placements
        travel whole."""
        import dataclasses

        slim_plan = dataclasses.replace(
            plan, node_update={}, node_allocation={}, node_preemptions={},
            annotations=None,
        )

        def diffs(alloc_map):
            return {
                node_id: [
                    {
                        "id": a.id,
                        "desired_status": a.desired_status,
                        "desired_description": a.desired_description,
                        "client_status": a.client_status,
                        "preempted_by_allocation": a.preempted_by_allocation,
                    }
                    for a in allocs
                ]
                for node_id, allocs in alloc_map.items()
            }

        # placements travel whole, but the (shared) Job document ships
        # exactly once per distinct job version, not once per alloc —
        # serializing 10K copies of the same job dominated commit time
        jobs_doc: dict[str, dict] = {}

        def placement_doc(a):
            job = a.job
            if job is None:
                return a.to_dict()
            jkey = f"{job.namespace}\x00{job.id}\x00{job.version}\x00{job.modify_index}"
            if jkey not in jobs_doc:
                jobs_doc[jkey] = job.to_dict()
            c = fast_alloc_clone(a)
            c.job = None
            d = c.to_dict()
            d["job_ref"] = jkey
            return d

        result_doc = {
            "node_update": diffs(result.node_update),
            "node_preemptions": diffs(result.node_preemptions),
            "node_allocation": {
                node_id: [placement_doc(a) for a in allocs]
                for node_id, allocs in result.node_allocation.items()
            },
            "jobs": jobs_doc,
            "deployment": (
                result.deployment.to_dict() if result.deployment else None
            ),
            "deployment_updates": [
                u.to_dict() for u in result.deployment_updates
            ],
            "refresh_index": result.refresh_index,
        }
        from ..trace import tracer as _tracer

        return {
            "plan": slim_plan.to_dict(),
            "result": result_doc,
            "normalized": True,
            "preemption_evals": [e.to_dict() for e in preemption_evals],
            # raft-entry trace annotation: the FSM pops it to span its
            # apply (leader AND followers) and to link the committed
            # index to the eval's trace for the mirror's patch spans.
            # It never enters state-store objects, so traced and
            # untraced runs commit byte-identical STATE
            "trace": _tracer.annotation_for_eval(plan.eval_id),
        }

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self, num_workers: int = 2, wait_for_leader: Optional[float] = None):
        self._running = True
        if self._flight_enabled:
            self.flight_recorder.start()
        if self.config.get("shard_devices"):
            # mesh-shard the planner node axis across the configured
            # device count (tpu/shard.py; env NOMAD_TPU_SHARD covers the
            # no-config path) — built before prewarm so the warmed
            # programs carry the sharded layouts
            from ..tpu import shard as _shard

            _shard.configure(int(self.config["shard_devices"]))
        if self.config.get("wavefront"):
            # wavefront placement plane (tpu/wavefront.py): route the
            # exact-scan dispatch through conflict-free batched commits.
            # Applied before prewarm so the warmed ladder includes the
            # wavefront programs when the stanza enables them.
            from ..tpu import wavefront as _wavefront

            wf = dict(self.config["wavefront"])
            _wavefront.configure(
                enabled=wf.get("enabled", True),
                max_round=wf.get("max_round"),
                contention_top_m=wf.get("contention_top_m"),
            )
        if self.config.get("paging"):
            # paged node axis (tpu/paging.py): stream over-budget node
            # planes through device memory in tiles. Applied before
            # prewarm so the warmed ladder includes the tile shapes,
            # and before first commit so the committed planes stamp
            # dirtiness at the configured tile granularity.
            from ..tpu import paging as _paging

            pg = dict(self.config["paging"])
            _paging.configure(
                enabled=pg.get("enabled", True),
                device_node_budget_mb=pg.get("device_node_budget_mb"),
                tile_nodes=pg.get("tile_nodes"),
            )
        if self.config.get("prewarm_kernels"):
            # compile the planner shape ladder in the background so the
            # first real eval doesn't eat the cold-compile latency
            # (tpu/warmup.py; persists via the on-disk compilation cache).
            # With batch_drain + an expected cluster size, the fused
            # drain-batch shapes prewarm too — mesh-sharded when a mesh
            # is active, so the sharded headline never recompiles.
            from ..tpu import shard as _shard
            from ..tpu.warmup import prewarm_async

            drain_shape = None
            drain_cfg = int(self.config.get("batch_drain", 0))
            nodes_hint = int(self.config.get("prewarm_drain_nodes", 0))
            if drain_cfg > 1 and nodes_hint > 0:
                drain_shape = (nodes_hint, drain_cfg)
            self._prewarm_thread = prewarm_async(
                drain=drain_shape, mesh=_shard.active_mesh()
            )
        self.raft.start()
        if self.gossip is not None:
            self.gossip.start()
            seeds = self.config.get("gossip", {}).get("join", [])
            if seeds:
                # retry-join in the background until a seed answers
                # (ref agent retry_join): a seed binding late must not
                # strand a non-bootstrap server (it has no voters and
                # never self-elects, so a silent give-up is a hang)
                def _join():
                    delay = 0.5
                    while self._running:
                        for seed in seeds:
                            if self.gossip.join(tuple(seed)):
                                return
                        logger.warning(
                            "gossip: no seed answered (%s); retrying in %.1fs",
                            seeds, delay,
                        )
                        time.sleep(delay)
                        delay = min(delay * 2, 10.0)

                threading.Thread(
                    target=_join, daemon=True, name="gossip-retry-join"
                ).start()
        self.start_workers(num_workers)
        if wait_for_leader is None:
            # single-voter servers are their own leader; block briefly so
            # callers can write immediately (dev-mode ergonomics)
            wait_for_leader = 5.0 if len(self.raft.voters) == 1 else 0.0
        if wait_for_leader:
            self.wait_for_leader(wait_for_leader)

    def start_workers(self, num_workers: int):
        """Spawn scheduler workers (split from start() so a harness can
        bring the server up with zero workers, load the broker, and only
        then open the drain — the deterministic way to exercise fused
        multi-eval batches: with workers racing registration, whether two
        evals are ever simultaneously ready is a scheduling accident)."""
        drain_n = int(self.config.get("batch_drain", 0))
        for i in range(num_workers):
            if drain_n > 1:
                # north-star bridge: drain N evals per cycle into one fused
                # kernel batch (worker.go:105 + SURVEY §2.3 broker drain)
                from .worker import BatchDrainWorker

                w = BatchDrainWorker(
                    self, seed=self.config.get("seed"), batch_size=drain_n
                )
            else:
                w = Worker(self, seed=self.config.get("seed"))
            self.workers.append(w)
            w.start()

    # ------------------------------------------------------------------
    # overload plane (core/overload.py)
    # ------------------------------------------------------------------
    def _overload_load(self) -> float:
        """Cheap cached load signal in [0, ~∞): max of broker backlog
        against its depth limit and the plan queue-wait p99 against its
        budget. Deliberately two in-process taps — the admission check
        sits on every mutating request and must never itself become the
        bottleneck (AdmissionController caches the value for 0.5s)."""
        cfg = self.config.get("overload") or {}
        depth_limit = float(cfg.get("depth_limit", 4096))
        qw_budget_s = float(cfg.get("queue_wait_budget_ms", 500.0)) / 1e3
        st = self.eval_broker.stats()
        depth = st["total_ready"] + st["total_unacked"]
        load = depth / max(1.0, depth_limit)
        p99 = metrics.percentile("plan.queue_wait", 0.99)
        if p99:
            load = max(load, float(p99) / max(1e-9, qw_budget_s))
        return load

    def _brownout_actions(self) -> list:
        """The brownout ladder, in degradation order (ISSUE round 18):
        wavefront→exact-scan dispatch, trace sampling→0, devprof census
        off, snapshot-on-subscribe off. Every degrade captures the prior
        value so restore puts the PROCESS-WIDE knob back exactly — a
        brownout that outlives the storm would leak into the next test's
        baseline."""
        from ..debug import devprof as _devprof
        from ..tpu import wavefront as _wavefront
        from ..trace import tracer as _tracer

        prior: dict = {}

        def wf_degrade():
            prior["wavefront"] = _wavefront.enabled()
            _wavefront.configure(enabled=False)

        def wf_restore():
            _wavefront.configure(enabled=prior.pop("wavefront", True))

        def trace_degrade():
            prior["sample_rate"] = _tracer.sample_rate
            _tracer.sample_rate = 0.0

        def trace_restore():
            _tracer.sample_rate = prior.pop("sample_rate", 1.0)

        def devprof_degrade():
            prior["devprof"] = _devprof.enable(False)

        def devprof_restore():
            _devprof.enable(prior.pop("devprof", True))

        def snap_degrade():
            eb = self.event_broker
            if eb is not None:
                prior["snapshot_on_subscribe"] = eb.snapshot_on_subscribe
                eb.snapshot_on_subscribe = False

        def snap_restore():
            eb = self.event_broker
            if eb is not None:
                eb.snapshot_on_subscribe = prior.pop(
                    "snapshot_on_subscribe", True
                )

        def shed_batch_degrade():
            self._shed_stream_class(overload_mod.CLASS_BATCH, True)

        def shed_batch_restore():
            self._shed_stream_class(overload_mod.CLASS_BATCH, False)

        def shed_service_degrade():
            self._shed_stream_class(overload_mod.CLASS_SERVICE, True)

        def shed_service_restore():
            self._shed_stream_class(overload_mod.CLASS_SERVICE, False)

        return [
            ("wavefront", wf_degrade, wf_restore),
            ("trace_sampling", trace_degrade, trace_restore),
            ("devprof_census", devprof_degrade, devprof_restore),
            ("snapshot_on_subscribe", snap_degrade, snap_restore),
            # stream shedding rungs, most-sheddable class first; there is
            # deliberately NO rung for system streams — deployment
            # watchers and operator consoles ride out any brownout
            ("stream_shed_batch", shed_batch_degrade, shed_batch_restore),
            (
                "stream_shed_service",
                shed_service_degrade,
                shed_service_restore,
            ),
        ]

    def add_stream_shed_hook(self, fn) -> None:
        """Register ``fn(admission_class, shed)`` to receive stream-shed
        transitions from the brownout ladder. A mux created while a
        stream rung is already degraded gets the current state replayed
        at registration, so mid-brownout adoptions shed too."""
        self._stream_shed_hooks.append(fn)
        for cls in sorted(self._stream_shed_on):
            try:
                fn(cls, True)
            except Exception:
                logger.exception("stream shed hook failed (%s)", cls)

    def _shed_stream_class(self, admission_class: str, shed: bool) -> None:
        if shed:
            self._stream_shed_on.add(admission_class)
        else:
            self._stream_shed_on.discard(admission_class)
        for fn in list(self._stream_shed_hooks):
            try:
                fn(admission_class, shed)
            except Exception:
                logger.exception(
                    "stream shed hook failed (%s)", admission_class
                )

    def eval_deadline_exceeded(self, ev: Evaluation, where: str):
        """Terminal deadline_exceeded outcome for ``ev``: one raft-applied
        failed-eval update carrying the refusing stage, plus the overload
        ledger. Called by the broker's refuse-at-dequeue callback and the
        worker's refuse-to-evaluate path (core/worker.py) — the refusing
        stage increments its own ``overload.deadline_exceeded.<stage>``
        metric at the refusal point, so this never double-counts."""
        if self.overload is not None:
            self.overload.note_deadline_exceeded(where)
        updated = ev.copy()
        updated.status = "failed"
        updated.status_description = f"deadline_exceeded ({where})"
        updated.modify_time = now_ns()
        try:
            self._apply(fsm_mod.EVAL_UPDATE, {"evals": [updated.to_dict()]})
        except NotLeaderError:
            # leadership moved mid-refusal: the new leader's broker will
            # refuse the same expired eval and apply the update itself
            pass

    def stop(self, hard: bool = False):
        """``hard=True`` is a simulated crash (the chaos harness's
        leader kill): no gossip leave broadcast, so peers discover the
        death through the SWIM failure detector exactly as they would a
        kill -9 — intentional departures stay distinguishable from
        failures (serf leave vs. failed)."""
        self._running = False
        self.flight_recorder.stop()
        if self.overload is not None:
            # restore every browned-out PROCESS-WIDE knob (wavefront,
            # trace sampling, devprof, snapshot-on-subscribe) so a storm
            # that ended mid-brownout can't leak into the next run
            self.overload.stop()
        if self.watchdog is not None:
            # a bundle capture racing teardown reads dying subsystems;
            # bounded wait, capture errors are already swallowed
            self.watchdog.wait_idle(timeout=5.0)
        self._hb_expire_q.put(None)  # unpark the expiry drainer, if any
        if self.gossip is not None:
            if not hard:
                try:
                    self.gossip.leave()
                except Exception:
                    pass
            self.gossip.stop()
        for w in self.workers:
            w.stop()
        self.workers = []
        self._revoke_leadership()
        self.raft.shutdown()
        if self.columnar_mirror is not None:
            self.columnar_mirror.close()
        if self.event_broker is not None:
            self.event_broker.shutdown()
        pool = getattr(self, "_outbound_pool", None)
        if pool is not None:
            pool.close()

    def is_leader(self) -> bool:
        return self.raft.is_leader()

    def leader_address(self) -> Optional[str]:
        return self.raft.leader_address()

    def wait_for_leader(self, timeout: float = 5.0) -> bool:
        """Wait until this server becomes the leader."""
        with self._leader_cond:
            return self._leader_cond.wait_for(lambda: self._leader, timeout)

    def _leadership_changed(self, leader: bool):
        if leader:
            self._establish_leadership()
        else:
            self._revoke_leadership()

    def _leadership_barrier(self) -> bool:
        """True once the FSM provably covers every entry committed by
        prior leaders. Rides the term-start noop raft already appended
        at election — commit of a current-term entry proves (by Log
        Matching) every prior committed entry is in this log, and its
        APPLY means the FSM replayed them all — so the barrier proposes
        nothing and adds no load; it just waits out the apply loop.
        Aborts only when leadership moves (the follower transition
        callback cleans up); it never gives up while still leader, which
        would leave a raft leader whose server never enables its
        planner — every write then fails not_leader forever."""
        target = self.raft.term_start_index
        while self._running and self.raft.is_leader():
            if self.raft.last_applied >= target:
                return True
            time.sleep(0.002)
        return False

    def _establish_leadership(self):
        """ref leader.go:180 establishLeadership"""
        if not self._running:
            return
        # barrier FIRST (ref leader.go: s.raft.Barrier()): commit + apply
        # a current-term noop so the FSM covers every entry committed by
        # prior leaders before ANY leader subsystem reads state. Without
        # it, _restore_evals re-enqueues evals whose ack is still in the
        # un-applied log suffix and the planner verifies plans against
        # snapshots missing the old leader's committed placements — the
        # "alloc placed twice after failover" class the federated storm
        # surfaced. Runs on the raft-lead-* callback thread, so blocking
        # here stalls no raft progress.
        if not self._leadership_barrier():
            return
        self.eval_broker.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.planner.start()
        self._restore_evals()
        self._initialize_heartbeat_timers()
        if self.periodic is not None:
            self.periodic.set_enabled(True)
            self.periodic.restore(self.state)
        if self.deployment_watcher is not None:
            self.deployment_watcher.set_enabled(True)
        if self.drainer is not None:
            self.drainer.set_enabled(True)
        # the flag must be up before the leader loops launch — they check it
        # as their run condition and would otherwise race a one-iteration exit
        with self._leader_cond:
            self._leader = True
            self._leader_cond.notify_all()
        self._reaper = threading.Thread(
            target=self._reap_failed_evals, daemon=True,
            name="eval-failed-reaper",
        )
        self._reaper.start()
        threading.Thread(
            target=self._reap_dup_blocked_evals, daemon=True,
            name="blocked-dup-reaper",
        ).start()
        self._gc_scheduler = threading.Thread(
            target=self._schedule_core_gc, daemon=True,
            name="core-gc-scheduler",
        )
        self._gc_scheduler.start()
        if self._acl_replication_target():
            t = threading.Thread(
                target=self._acl_replication_loop, daemon=True,
                name="acl-replication",
            )
            t.start()
        self._reconcile_gossip_members()
        logger.info("server %s: leadership established", self.raft.node_id)

    def _revoke_leadership(self):
        with self._leader_cond:
            self._leader = False
        self.planner.stop()
        self.eval_broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        if self.periodic is not None:
            self.periodic.set_enabled(False)
        if self.deployment_watcher is not None:
            self.deployment_watcher.set_enabled(False)
        if self.drainer is not None:
            self.drainer.set_enabled(False)
        with self._lock:
            for t in self._heartbeat_timers.values():
                t.cancel()
            self._heartbeat_timers.clear()

    def _restore_evals(self):
        """Re-populate the broker from replicated state on leadership
        (ref leader.go:295 restoreEvals)."""
        for ev in list(self.state.evals()):
            if ev.should_enqueue():
                self.eval_broker.enqueue(ev)
            elif ev.should_block():
                self.blocked_evals.block(ev)

    def _initialize_heartbeat_timers(self):
        """ref heartbeat.go:21 initializeHeartbeatTimers"""
        for node in list(self.state.nodes()):
            if node.status != NODE_STATUS_DOWN:
                self._reset_heartbeat(node.id)

    def _reap_failed_evals(self):
        """Drain the _failed queue: mark evals failed and schedule a delayed
        follow-up retry (ref leader.go:505 reapFailedEvaluations)."""
        from .broker import FAILED_QUEUE

        follow_up_wait = self.config.get("failed_eval_followup_wait", 60.0)
        unblock_interval = self.config.get("failed_eval_unblock_interval", 60.0)
        last_unblock = time.monotonic()
        while self._running and self._leader:
            if time.monotonic() - last_unblock >= unblock_interval:
                last_unblock = time.monotonic()
                self.blocked_evals.unblock_failed()
            ev, token = self.eval_broker.dequeue([FAILED_QUEUE], timeout=0.5)
            if ev is None:
                continue
            try:
                failed = ev.copy()
                failed.status = "failed"
                failed.status_description = "evaluation reached delivery limit"
                follow_up = failed.create_failed_follow_up_eval(
                    int(follow_up_wait * 1e9)
                )
                self._apply(
                    fsm_mod.EVAL_UPDATE,
                    {"evals": [failed.to_dict(), follow_up.to_dict()]},
                )
                self.eval_broker.ack(ev.id, token)
            except NotLeaderError:
                return
            except Exception:
                logger.exception("failed-eval reaping error for %s", ev.id)

    def _reap_dup_blocked_evals(self):
        """Cancel blocked evals superseded by a newer one for the same job
        (ref leader.go:524 reapDupBlockedEvaluations): BlockedEvals dedup
        keeps one eval per job; the losers must not sit 'blocked' in raft
        state forever."""
        while self._running and self._leader:
            dups = self.blocked_evals.get_duplicates(timeout=0.5)
            if not dups:
                continue
            try:
                cancelled = []
                for ev in dups:
                    c = ev.copy()
                    c.status = EVAL_STATUS_CANCELLED
                    c.status_description = (
                        "existing blocked evaluation exists for this job"
                    )
                    cancelled.append(c.to_dict())
                self._apply(fsm_mod.EVAL_UPDATE, {"evals": cancelled})
            except NotLeaderError:
                return
            except Exception:
                logger.exception("duplicate blocked eval reaping error")

    def _schedule_core_gc(self):
        """Leader cron enqueuing GC core-job evals on their intervals
        (ref leader.go:440-486 schedulePeriodic). Core evals live only in
        the leader's broker — they are never raft-persisted."""
        from .core_sched import (
            CORE_JOB_DEPLOYMENT_GC,
            CORE_JOB_EVAL_GC,
            CORE_JOB_JOB_GC,
            CORE_JOB_NODE_GC,
            core_job_eval,
        )

        this_thread = threading.current_thread()
        intervals = {
            CORE_JOB_EVAL_GC: float(self.config.get("eval_gc_interval", 300.0)),
            CORE_JOB_NODE_GC: float(self.config.get("node_gc_interval", 300.0)),
            CORE_JOB_JOB_GC: float(self.config.get("job_gc_interval", 300.0)),
            CORE_JOB_DEPLOYMENT_GC: float(
                self.config.get("deployment_gc_interval", 300.0)
            ),
        }
        next_fire = {job: time.monotonic() + iv for job, iv in intervals.items()}
        while (
            self._running and self._leader and self._gc_scheduler is this_thread
        ):
            # keep witnessing the head index as wall time passes; apply-time
            # witnesses alone never age the newest writes on an idle cluster
            self.time_table.witness(self.state.latest_index())
            now = time.monotonic()
            for job, fire_at in next_fire.items():
                if now >= fire_at:
                    next_fire[job] = now + intervals[job]
                    self.eval_broker.enqueue(
                        core_job_eval(job, self.state.latest_index())
                    )
            time.sleep(min(1.0, min(iv for iv in intervals.values())))

    # ------------------------------------------------------------------
    # ACL endpoints (ref nomad/acl_endpoint.go + nomad/acl.go)
    # ------------------------------------------------------------------
    def acl_enabled(self) -> bool:
        return bool(self.config.get("acl", {}).get("enabled"))

    def resolve_token(self, secret: str):
        """secret → compiled ACL (ref acl.go ResolveToken, with the
        reference's resolution cache). With ACLs off, everything is allowed;
        an empty secret is the anonymous ACL; an unknown secret is rejected.
        Resolutions cache on (secret, token-table index, policy-table
        index) so the hot path skips the token scan + policy parse until an
        ACL write invalidates it."""
        from ..acl import ACL_ANONYMOUS, ACL_MANAGEMENT, compile_acl, parse_policy
        from ..structs.model import ACL_TOKEN_TYPE_MANAGEMENT

        if not self.acl_enabled():
            return ACL_MANAGEMENT
        if not secret:
            return ACL_ANONYMOUS
        key = (
            secret,
            self.state.table_index("acl_token"),
            self.state.table_index("acl_policy"),
        )
        cached = self._acl_cache.get(key)
        if cached is not None:
            return cached
        token = self.state.acl_token_by_secret(secret)
        if token is None:
            if not self.raft.is_leader():
                # a follower's table may simply LAG — a freshly restarted
                # server serves HTTP before its FSM catches up to the
                # commit index, and a replica region's follower may not
                # have replicated a new global token yet. Only the
                # leader's miss is authoritative (ref acl.go: resolution
                # falls through to the authoritative source on a local
                # miss); the RPC/HTTP layers forward on this error.
                raise NotLeaderError(
                    self.raft.leader_address(), self.raft.leader_id
                )
            raise PermissionError("ACL token not found")
        if token.type == ACL_TOKEN_TYPE_MANAGEMENT:
            acl = ACL_MANAGEMENT
        else:
            parsed = []
            for name in token.policies:
                policy = self.state.acl_policy_by_name(name)
                if policy is not None:
                    parsed.append(parse_policy(policy.rules))
            acl = compile_acl(parsed)
        if len(self._acl_cache) > 512:
            self._acl_cache.clear()
        self._acl_cache[key] = acl
        return acl

    # ------------------------------------------------------------------
    # ACL replication (ref leader.go:277 replicateACLPolicies/Tokens:
    # non-authoritative region leaders mirror policies and global tokens
    # from the authoritative region over its HTTP surface)
    # ------------------------------------------------------------------
    def _acl_replication_target(self) -> Optional[str]:
        acl_cfg = self.config.get("acl", {})
        auth = acl_cfg.get("authoritative_region")
        if not acl_cfg.get("enabled") or not auth or auth == self.region:
            return None
        return auth

    def _acl_replication_loop(self):
        interval = float(
            self.config.get("acl", {}).get("replication_interval", 1.0)
        )
        # WHY: one replication round per interval per follower region —
        # fixed cadence, not per-request; budget-severing would stall
        # ACL convergence (staleness already surfaced as replication lag)
        while self._leader and self._running:  # nta: ignore[retry-without-budget]
            try:
                self.replicate_acl_once()
            except Exception as e:
                st = self.acl_replication_status
                st["failures"] = st.get("failures", 0) + 1
                st["last_error"] = f"{type(e).__name__}: {e}"
                logger.exception("acl replication round failed")
            time.sleep(interval)

    def acl_replication_lag_s(self) -> Optional[float]:
        """Seconds since the last successful replication round (None
        when this server doesn't replicate — authoritative regions and
        ACL-less clusters). A server that has NEVER succeeded reports
        lag since its first attempt, so a region that came up
        partitioned is visibly behind from the start."""
        st = self.acl_replication_status
        if not st.get("configured"):
            return None
        anchor = st.get("last_success_wall") or st.get("started_wall")
        if anchor is None:
            return None
        return max(0.0, time.time() - anchor)

    def replicate_acl_once(self) -> dict:
        """One replication round; returns {policies_upserted, policies_
        deleted, tokens_upserted, tokens_deleted} (exposed for tests and
        operator debugging)."""
        stats = {
            "policies_upserted": 0,
            "policies_deleted": 0,
            "tokens_upserted": 0,
            "tokens_deleted": 0,
        }
        auth = self._acl_replication_target()
        if auth is None:
            return stats
        st = self.acl_replication_status
        st["configured"] = True
        st["authoritative_region"] = auth
        st.setdefault("started_wall", time.time())
        st.setdefault("rounds", 0)
        st.setdefault("failures", 0)
        # inter-region fault seam: a partitioned WAN stalls replication
        # here exactly like an unreachable authoritative region — the
        # stall is counted so the acl_replication_lag watchdog sees it
        if _faults.region_link(self.region, auth, "acl.replication") in (
            "drop", "sever",
        ):
            st["failures"] += 1
            st["last_error"] = (
                f"region link {self.region}->{auth} severed"
            )
            return stats
        peers = self.region_http_servers(auth)
        if not peers:
            st["failures"] += 1
            st["last_error"] = f"no path to authoritative region {auth!r}"
            return stats
        from ..api.client import ApiClient
        from ..structs.model import AclPolicy, AclToken

        api = ApiClient(
            address=peers[0],
            token=self.config.get("acl", {}).get("replication_token", ""),
        )

        # policies: authoritative region owns the namespace wholesale
        remote_names = {p["Name"] for p in api.acl_policies()}
        upserts = []
        for name in remote_names:
            doc = api.acl_policy(name)
            local = self.state.acl_policy_by_name(name)
            if local is None or local.rules != doc["Rules"]:
                upserts.append(
                    AclPolicy(
                        name=name,
                        description=doc.get("Description", ""),
                        rules=doc["Rules"],
                    )
                )
        if upserts:
            self.acl_upsert_policies(upserts)
            stats["policies_upserted"] = len(upserts)
        stale = [
            p.name
            for p in self.state.acl_policies()
            if p.name not in remote_names
        ]
        if stale:
            self.acl_delete_policies(stale)
            stats["policies_deleted"] = len(stale)

        # tokens: only global ones replicate (ref leader.go
        # replicateACLTokens; local tokens stay region-scoped)
        remote_tokens = {
            t["AccessorID"]: t for t in api.acl_tokens() if t.get("Global")
        }
        token_upserts = []
        for accessor, row in remote_tokens.items():
            local = self.state.acl_token_by_accessor(accessor)
            if local is not None and local.policies == row.get("Policies"):
                continue
            doc = api.acl_token(accessor)  # full doc incl. the secret
            token_upserts.append(
                AclToken(
                    accessor_id=doc["AccessorID"],
                    secret_id=doc["SecretID"],
                    name=doc.get("Name", ""),
                    type=doc.get("Type", "client"),
                    policies=list(doc.get("Policies", [])),
                    global_token=True,
                )
            )
        if token_upserts:
            self._apply(
                fsm_mod.ACL_TOKEN_UPSERT,
                {"tokens": [t.to_dict() for t in token_upserts]},
            )
            stats["tokens_upserted"] = len(token_upserts)
        stale_tokens = [
            t.accessor_id
            for t in self.state.acl_tokens()
            if t.global_token and t.accessor_id not in remote_tokens
        ]
        if stale_tokens:
            self.acl_delete_tokens(stale_tokens)
            stats["tokens_deleted"] = len(stale_tokens)
        st["rounds"] += 1
        st["last_success_wall"] = time.time()
        st.pop("last_error", None)
        return stats

    def acl_bootstrap(self):
        """One-shot creation of the initial management token
        (ref acl_endpoint.go Bootstrap). Done-ness is a persisted index
        marker, NOT the existence of a management token — deleting all
        management tokens must not silently re-open anonymous bootstrap."""
        from ..structs.model import ACL_TOKEN_TYPE_MANAGEMENT, AclToken

        self._check_leader()
        if self.state.table_index("acl_bootstrap"):
            raise PermissionError("ACL bootstrap already done")
        token = AclToken(
            accessor_id=generate_uuid(),
            secret_id=generate_uuid(),
            name="Bootstrap Token",
            type=ACL_TOKEN_TYPE_MANAGEMENT,
            global_token=True,
            create_time=now_ns(),
        )
        self._apply(
            fsm_mod.ACL_TOKEN_UPSERT,
            {"tokens": [token.to_dict()], "bootstrap": True},
        )
        return token

    def acl_upsert_policies(self, policies: list):
        from ..acl import parse_policy

        self._check_leader()
        for p in policies:
            if not p.name:
                raise ValueError("policy requires a name")
            parse_policy(p.rules)  # validate before replicating
        self._apply(
            fsm_mod.ACL_POLICY_UPSERT,
            {"policies": [p.to_dict() for p in policies]},
        )

    def acl_delete_policies(self, names: list[str]):
        self._check_leader()
        self._apply(fsm_mod.ACL_POLICY_DELETE, {"names": list(names)})

    def acl_create_token(self, token):
        from ..structs.model import ACL_TOKEN_TYPE_CLIENT, ACL_TOKEN_TYPE_MANAGEMENT

        self._check_leader()
        if token.type not in (ACL_TOKEN_TYPE_CLIENT, ACL_TOKEN_TYPE_MANAGEMENT):
            raise ValueError(f"invalid token type {token.type!r}")
        if token.type == ACL_TOKEN_TYPE_CLIENT and not token.policies:
            raise ValueError("client token requires policies")
        token.accessor_id = token.accessor_id or generate_uuid()
        token.secret_id = token.secret_id or generate_uuid()
        token.create_time = token.create_time or now_ns()
        self._apply(fsm_mod.ACL_TOKEN_UPSERT, {"tokens": [token.to_dict()]})
        return token

    def acl_delete_tokens(self, accessors: list[str]):
        self._check_leader()
        self._apply(fsm_mod.ACL_TOKEN_DELETE, {"accessors": list(accessors)})

    # ------------------------------------------------------------------
    # Search (ref nomad/search_endpoint.go: prefix matches across tables,
    # truncated at 20 per context)
    # ------------------------------------------------------------------
    def search(
        self,
        prefix: str,
        context: str = "all",
        namespace: str = "default",
        include_nodes: bool = True,
    ) -> dict:
        """Results are scoped to the request namespace (jobs/evals/allocs/
        deployments), and nodes only appear for callers holding node:read —
        matching the per-context ACL filtering of search_endpoint.go."""
        snap = self.state.snapshot()
        limit = 20
        contexts: dict[str, list[str]] = {}
        truncations: dict[str, bool] = {}

        def collect(name: str, ids):
            if context not in ("all", name):
                return
            matches = sorted(i for i in ids if i.startswith(prefix))
            truncations[name] = len(matches) > limit
            contexts[name] = matches[:limit]

        collect("jobs", (j.id for j in snap.jobs() if j.namespace == namespace))
        collect(
            "evals", (e.id for e in snap.evals() if e.namespace == namespace)
        )
        collect(
            "allocs", (a.id for a in snap.allocs() if a.namespace == namespace)
        )
        if include_nodes:
            collect("nodes", (n.id for n in snap.nodes()))
        collect(
            "deployments",
            (d.id for d in snap.deployments() if d.namespace == namespace),
        )
        return {"matches": contexts, "truncations": truncations}

    def catalog_service(self, name: str) -> list[dict]:
        """Service catalog lookup (the Consul-catalog role for Connect
        upstream resolution): plain service instances by name, plus
        client-published sidecar listeners under ``<svc>-sidecar-proxy``
        (ref Consul sidecar service registrations)."""
        snap = self.state.snapshot()
        out = []
        for alloc in snap.allocs():
            if alloc.terminal_status():
                continue
            for svc_name, ep in (alloc.connect_proxies or {}).items():
                if f"{svc_name}-sidecar-proxy" != name:
                    continue
                out.append(
                    {
                        "ServiceName": name,
                        "AllocID": alloc.id,
                        "NodeID": alloc.node_id,
                        "Address": ep.get("ip", ""),
                        "Port": int(ep.get("port", 0)),
                        "Status": "passing",
                    }
                )
            job = alloc.job
            tg = job.lookup_task_group(alloc.task_group) if job else None
            if tg is None:
                continue
            for task in tg.tasks:
                state = alloc.task_states.get(task.name)
                healthy = state is not None and state.state == "running"
                if healthy and any(
                    v != "passing" for v in state.check_status.values()
                ):
                    healthy = False
                for svc in task.services:
                    if svc.name != name:
                        continue
                    address, port = "", 0
                    resources = alloc.allocated_resources
                    tr = (
                        resources.tasks.get(task.name)
                        if resources is not None
                        else None
                    )
                    if tr is not None and svc.port_label:
                        for net in tr.networks:
                            for p in list(net.reserved_ports) + list(
                                net.dynamic_ports
                            ):
                                if p.label == svc.port_label:
                                    address, port = net.ip, p.value
                    out.append(
                        {
                            "ServiceName": svc.name,
                            "AllocID": alloc.id,
                            "NodeID": alloc.node_id,
                            "Address": address,
                            "Port": port,
                            "Status": "passing" if healthy else "critical",
                        }
                    )
        return out

    def _plan_token_live(self, plan) -> bool:
        """Dequeue-time re-validation of a plan's eval token (plans without
        tokens — direct planner users — pass)."""
        if not plan.eval_token:
            return True
        token, ok = self.eval_broker.outstanding(plan.eval_id)
        return ok and token == plan.eval_token

    def plan_submit(self, plan):
        """Plan submission with the EvalToken split-brain guard
        (ref plan_endpoint.go:19-52): the broker must still hold this eval
        outstanding under this token, else the worker is stale (its eval was
        nacked and re-dequeued elsewhere) and the plan is rejected before it
        can clobber the newer worker's. The nack timer pauses while the plan
        queues — it is making progress — and resumes when the result lands."""
        from .broker import BrokerError

        eval_id = plan.eval_id
        token = plan.eval_token
        self.eval_broker.pause_nack_timeout(eval_id, token)
        try:
            pending = self.planner.queue.enqueue(plan)
            return pending.wait(timeout=30.0)
        finally:
            try:
                self.eval_broker.resume_nack_timeout(eval_id, token)
            except BrokerError:
                pass  # acked/nacked while the plan was in flight

    def derive_vault_token(self, alloc_id: str, task_name: str) -> str:
        """ref node_endpoint.go DeriveVaultToken"""
        self._check_leader()
        return self.vault.derive_token(alloc_id, task_name)

    def upsert_node_events(self, events_by_node: dict[str, list]) -> int:
        """Replicate operational node events (ref node_endpoint.go
        EmitEvents → raft NodeEventsUpsertRequestType). Leader-only; event
        docs carry their own timestamps so replicas apply identically."""
        self._check_leader()
        return self._apply(
            fsm_mod.NODE_EVENTS_UPSERT, {"events": events_by_node}
        )

    #: node-event fanout cap for a single kernel fault: the witness needs
    #: a few TPU-plane nodes, not a raft write touching every device host
    MAX_KERNEL_FAULT_EVENT_NODES = 8

    def note_kernel_fault(self, ev: Optional[Evaluation], reason: str):
        """Witness a device-tier scheduler fault (TPU placement kernel
        error/NaN) that the scheduler degraded around: a metric for the
        telemetry surface plus a node event on the TPU device plane so
        operators see WHERE the accelerator tier is unhealthy — the eval
        itself completed on the exact-np host oracle."""
        metrics.incr("tpu.kernel_fault")
        targets = []
        for node in self.state.nodes():
            devices = getattr(node.node_resources, "devices", None) or []
            if any(getattr(d, "type", "") == "tpu" for d in devices):
                targets.append(node.id)
                if len(targets) >= self.MAX_KERNEL_FAULT_EVENT_NODES:
                    break
        if not targets:
            return
        event = {
            "timestamp": now_ns(),
            "subsystem": "TPU",
            "message": f"placement kernel fault: {reason}; "
            "degraded to exact-np planner",
            "details": {"eval_id": ev.id if ev is not None else ""},
        }
        self.upsert_node_events({node_id: [event] for node_id in targets})

    def system_gc(self):
        """Force-GC everything eligible (ref system_endpoint.go GarbageCollect
        → CoreJobForceGC). Leader-only."""
        from .core_sched import CORE_JOB_FORCE_GC, core_job_eval

        self._check_leader()
        self.eval_broker.enqueue(
            core_job_eval(CORE_JOB_FORCE_GC, self.state.latest_index())
        )

    @staticmethod
    def _adopt_eval_trace(ev: Evaluation):
        """Link the eval about to be created to the caller's trace
        context (HTTP/CLI submit span, RPC server span): the broker's
        root span — opened later on the raft apply thread — parents
        under it, so submit→device→ack is ONE tree."""
        from ..trace import tracer as _tracer

        _tracer.adopt_eval(ev.id)

    # ------------------------------------------------------------------
    # Job endpoints (ref nomad/job_endpoint.go:80 Register)
    # ------------------------------------------------------------------
    def job_register(self, job: Job) -> str:
        """Returns the eval id created (empty for periodic/parameterized)."""
        self._check_leader()
        self._validate_job(job)
        # stamp submission time before replication (ref job_endpoint.go
        # Register → job.SubmitTime = time.Now()); the FSM seeds the
        # periodic-launch checkpoint from it, so 0 would mean epoch-0 and
        # fire a spurious catch-up on the next leadership establishment
        job.submit_time = now_ns()
        self._apply(fsm_mod.JOB_REGISTER, {"job": job.to_dict()})
        stored = self.state.job_by_id(job.namespace, job.id)

        if stored.is_periodic() or stored.is_parameterized():
            return ""

        # direct-RPC submissions never pass the HTTP mint; when the
        # overload stanza sets default_deadline_s, stamp it here so the
        # whole pipeline stays bounded regardless of entry surface
        deadline_ns = current_deadline()
        if (
            not deadline_ns
            and self.overload is not None
            and self.overload.default_deadline_s > 0
        ):
            from .overload import mint_deadline

            deadline_ns = mint_deadline(self.overload.default_deadline_s)
        ev = Evaluation(
            id=generate_uuid(),
            namespace=job.namespace,
            priority=stored.priority,
            type=stored.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
            job_id=stored.id,
            job_modify_index=stored.modify_index,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
            # deadline propagation (core/overload.py): the HTTP/RPC edge
            # activated the caller's deadline scope; the eval carries it
            # so broker/worker/applier/drain can refuse expired work.
            # Server-initiated follow-ups deliberately do NOT inherit it.
            deadline=deadline_ns,
        )
        self._adopt_eval_trace(ev)
        self._apply(fsm_mod.EVAL_UPDATE, {"evals": [ev.to_dict()]})
        return ev.id

    def job_plan(self, job: Job, diff: bool = True) -> dict:
        """Dry-run the job against a scratch copy of current state and
        return the annotated plan + structural diff without mutating
        anything (ref job_endpoint.go Plan: snapshot + UpsertJob into the
        snapshot, scheduler.Harness dry-run with annotate, structs diff)."""
        from ..scheduler import Harness
        from ..structs.diff import job_diff

        self._validate_job(job)
        old_job = self.state.job_by_id(job.namespace, job.id)

        # scratch world adopting the immutable generation; never published
        scratch = StateStore()
        scratch._gen = self.state.snapshot()._gen
        planned = job.copy()
        planned.submit_time = now_ns()
        scratch.upsert_job(None, planned)

        harness = Harness(state=scratch, seed=self.config.get("seed"))
        # nta: ignore[raft-index-arith] — scratch dry-run world: this
        # index seeds the harness's private overlay and is never
        # published, compared, or waited on against a real store
        harness._next_index = scratch.latest_index() + 1
        ev = Evaluation(
            id=generate_uuid(),
            namespace=job.namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
            job_id=job.id,
            status=EVAL_STATUS_PENDING,
            annotate_plan=True,
        )
        sched = harness.process(job.type, ev)

        plan = harness.plans[-1] if harness.plans else None
        annotations = None
        if plan is not None and plan.annotations is not None:
            annotations = plan.annotations.to_dict()
        failed = {
            name: metric.to_dict()
            for name, metric in (getattr(sched, "failed_tg_allocs", None) or {}).items()
        }
        return {
            "annotations": annotations,
            "failed_tg_allocs": failed,
            "diff": job_diff(old_job, job) if diff else None,
            "job_modify_index": old_job.modify_index if old_job is not None else 0,
        }

    def job_deregister(self, namespace: str, job_id: str, purge: bool = False) -> str:
        """ref job_endpoint.go Deregister"""
        self._check_leader()
        job = self.state.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(f"job not found: {job_id}")
        self._apply(
            fsm_mod.JOB_DEREGISTER,
            {"namespace": namespace, "job_id": job_id, "purge": purge},
        )
        ev = Evaluation(
            id=generate_uuid(),
            namespace=namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_DEREGISTER,
            job_id=job_id,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
        self._apply(fsm_mod.EVAL_UPDATE, {"evals": [ev.to_dict()]})
        return ev.id

    def job_dispatch(
        self,
        namespace: str,
        job_id: str,
        payload: str = "",
        meta: Optional[dict] = None,
    ) -> dict:
        """Instantiate a parameterized job (ref job_endpoint.go:1523
        Dispatch): validates payload/meta against the job's parameterized
        config, registers a derived child, and evaluates it."""
        self._check_leader()
        parent = self.state.job_by_id(namespace, job_id)
        if parent is None:
            raise KeyError(f"job not found: {job_id}")
        if not parent.is_parameterized():
            raise ValueError(f"job {job_id} is not parameterized")
        if parent.stopped():
            raise ValueError(f"job {job_id} is stopped")

        cfg = parent.parameterized_job
        meta = dict(meta or {})
        if cfg.payload == "required" and not payload:
            raise ValueError("payload is required by the job")
        if cfg.payload == "forbidden" and payload:
            raise ValueError("payload is forbidden by the job")
        if len(payload) > 16 * 1024:
            raise ValueError("payload exceeds maximum size (16KiB)")
        missing = [k for k in cfg.meta_required if k not in meta]
        if missing:
            raise ValueError(f"missing required dispatch meta: {missing}")
        allowed = set(cfg.meta_required) | set(cfg.meta_optional)
        unknown = [k for k in meta if k not in allowed]
        if unknown:
            raise ValueError(f"dispatch meta not allowed by job: {unknown}")

        child = derive_dispatch_job(parent, payload, meta)
        self._apply(fsm_mod.JOB_REGISTER, {"job": child.to_dict()})
        stored = self.state.job_by_id(namespace, child.id)
        ev = Evaluation(
            id=generate_uuid(),
            namespace=namespace,
            priority=stored.priority,
            type=stored.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
            job_id=stored.id,
            job_modify_index=stored.modify_index,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
        self._adopt_eval_trace(ev)
        self._apply(fsm_mod.EVAL_UPDATE, {"evals": [ev.to_dict()]})
        return {"DispatchedJobID": child.id, "EvalID": ev.id}

    def job_evaluate(
        self, namespace: str, job_id: str, force_reschedule: bool = False
    ) -> str:
        """Force a fresh evaluation of a job (ref job_endpoint.go Evaluate):
        used by `job eval` to re-drive placement after manual fixes. With
        force_reschedule, failed allocs get desired-transition
        ForceReschedule so the reconciler replaces them immediately."""
        self._check_leader()
        job = self.state.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(f"job not found: {job_id}")
        if job.is_periodic():
            raise ValueError("can't evaluate a periodic job directly")
        ev = Evaluation(
            id=generate_uuid(),
            namespace=namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
            job_id=job_id,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
        self._adopt_eval_trace(ev)
        if force_reschedule:
            failed = {
                a.id: {"force_reschedule": True}
                for a in self.state.allocs_by_job(namespace, job_id)
                if a.client_status == "failed" and not a.next_allocation
            }
            self._apply(
                fsm_mod.ALLOC_DESIRED_TRANSITION,
                {"allocs": failed, "evals": [ev.to_dict()]},
            )
        else:
            self._apply(fsm_mod.EVAL_UPDATE, {"evals": [ev.to_dict()]})
        return ev.id

    def periodic_force(self, namespace: str, job_id: str) -> str:
        """ref periodic_endpoint.go Force"""
        self._check_leader()
        if self.periodic is None:
            raise ValueError("periodic dispatcher not available")
        return self.periodic.force_launch(namespace, job_id)

    @staticmethod
    def _validate_job(job: Job):
        """Minimal admission checks (ref job_endpoint.go validateJob)."""
        if not job.id:
            raise ValueError("missing job ID")
        if not job.task_groups and not job.stop:
            raise ValueError("job requires at least one task group")
        if job.type == JOB_TYPE_CORE:
            raise ValueError("job type cannot be core")
        if not (JOB_MIN_PRIORITY <= job.priority <= JOB_MAX_PRIORITY):
            # priority drives eval ordering AND overload admission
            # classes; out-of-band values would make a user job outrank
            # core GC or dodge shedding (ref structs.go Job.Validate)
            raise ValueError(
                f"job priority must be between {JOB_MIN_PRIORITY} "
                f"and {JOB_MAX_PRIORITY}, got {job.priority}"
            )
        if job.periodic is not None and job.periodic.enabled:
            if job.type != JOB_TYPE_BATCH:
                # the dispatcher stamps child copies per tick; a periodic
                # service would accrete immortal children (ref structs.go:
                # periodic is batch-only)
                raise ValueError(
                    "periodic can only be used with batch jobs, got "
                    f"type {job.type!r}"
                )
            if job.parameterized_job is not None:
                # both are job factories; composing them is ambiguous
                # (does the cron tick dispatch, or template a dispatch?)
                raise ValueError(
                    "a periodic job cannot also be parameterized"
                )
        if job.is_periodic():
            # reject bad cron specs at admission: the dispatcher would
            # otherwise silently never launch (ref structs.go
            # PeriodicConfig.Validate)
            from .periodic import CronSpec

            if job.periodic.spec_type != "cron":
                raise ValueError(
                    f"unknown periodic spec type {job.periodic.spec_type!r}"
                )
            CronSpec(job.periodic.spec)
        for tg in job.task_groups:
            if tg.count < 0:
                raise ValueError(f"task group {tg.name} count must be >= 0")
            if not tg.tasks:
                raise ValueError(f"task group {tg.name} requires at least one task")

    # ------------------------------------------------------------------
    # Node endpoints (ref nomad/node_endpoint.go:79 Register, :362
    # UpdateStatus, :894 GetClientAllocs)
    # ------------------------------------------------------------------
    def node_register(self, node: Node) -> dict:
        self._check_leader()
        if not node.computed_class:
            compute_class(node)
        existed = self.state.node_by_id(node.id) is not None
        if not node.status:
            node.status = NODE_STATUS_READY
        # stamp before replication: event timestamps must be identical on
        # every replica and across log replays (like job.submit_time)
        node.status_updated_at = now_ns()
        self._apply(fsm_mod.NODE_REGISTER, {"node": node.to_dict()})
        self._reset_heartbeat(node.id)

        if not existed or node.status == NODE_STATUS_READY:
            self._create_node_evals(node.id)
        return {"heartbeat_ttl": self.heartbeat_ttl}

    def node_deregister(self, node_id: str):
        self._check_leader()
        self._apply(fsm_mod.NODE_DEREGISTER, {"node_id": node_id})
        with self._lock:
            t = self._heartbeat_timers.pop(node_id, None)
            if t is not None:
                t.cancel()

    def node_purge(self, node_id: str) -> list[str]:
        """Force-remove a node and create evals so its allocations are
        rescheduled (ref node_endpoint.go Deregister: the raft deregister
        applies FIRST, then createNodeEvals — evals created before the
        deregister commits would schedule against a state where the node
        still looks healthy and no-op, stranding its allocs)."""
        self._check_leader()
        node_id = self._node_id_by_prefix(node_id)
        self.node_deregister(node_id)
        return self._create_node_evals(node_id) or []

    def alloc_stop(self, alloc_id: str) -> str:
        """Stop one allocation: desired-transition migrate=true plus an
        alloc-stop eval in a single raft apply (ref alloc_endpoint.go:211
        Stop). The scheduler reconciles the stop and replaces the alloc."""
        from ..structs.model import EVAL_TRIGGER_ALLOC_STOP

        self._check_leader()
        alloc = self.state.alloc_by_id(alloc_id)
        if alloc is None:
            matches = [
                a for a in self.state.allocs() if a.id.startswith(alloc_id)
            ]
            if len(matches) == 1:
                alloc = matches[0]
        if alloc is None:
            raise KeyError(f"alloc not found: {alloc_id}")
        job = alloc.job or self.state.job_by_id(alloc.namespace, alloc.job_id)
        ev = Evaluation(
            id=generate_uuid(),
            namespace=alloc.namespace,
            priority=job.priority if job is not None else 50,
            type=job.type if job is not None else JOB_TYPE_SERVICE,
            triggered_by=EVAL_TRIGGER_ALLOC_STOP,
            job_id=alloc.job_id,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
        self._apply(
            fsm_mod.ALLOC_DESIRED_TRANSITION,
            {
                "allocs": {alloc.id: {"migrate": True}},
                "evals": [ev.to_dict()],
            },
        )
        return ev.id

    def alloc_get(self, alloc_id: str) -> Optional[dict]:
        """Alloc document by id (ref alloc_endpoint.go GetAlloc); used by
        clients awaiting a previous allocation during disk migration."""
        alloc = self.state.alloc_by_id(alloc_id)
        return None if alloc is None else alloc.to_dict()

    def forward_client_fs(self, alloc_id: str, method: str, params: dict):
        """Server-side hop of the client→server→client fs path
        (ref client_fs_endpoint.go): resolve the alloc's node and forward
        to its client RPC listener with the node secret. This is how a
        replacement alloc migrates ephemeral disk off another node without
        ever holding that node's secret itself."""
        alloc = self.state.alloc_by_id(alloc_id)
        if alloc is None:
            raise KeyError(f"alloc not found: {alloc_id}")
        node = self.state.node_by_id(alloc.node_id)
        addr = (
            node.attributes.get("unique.advertise.client_rpc")
            if node is not None
            else None
        )
        if not addr:
            raise KeyError(
                f"alloc {alloc_id} is on a node without a client RPC address"
            )
        payload = dict(
            params or {}, alloc_id=alloc_id, secret=node.secret_id
        )
        return self._conn_pool().call(
            addr, f"ClientFS.{method}", payload, timeout=30.0
        )

    def _client_rpc_target(self, alloc_id: str):
        """(client rpc addr, node secret) for the node hosting an alloc."""
        alloc = self.state.alloc_by_id(alloc_id)
        if alloc is None:
            raise KeyError(f"alloc not found: {alloc_id}")
        node = self.state.node_by_id(alloc.node_id)
        addr = (
            node.attributes.get("unique.advertise.client_rpc")
            if node is not None
            else None
        )
        if not addr:
            raise KeyError(
                f"alloc {alloc_id} is on a node without a client RPC address"
            )
        return addr, node.secret_id

    def open_client_exec(self, alloc_id: str, params: dict):
        """Dial the hosting node and open the duplex exec stream (the
        server hop of agent→server→client exec forwarding — the path the
        reference serves via client_alloc_endpoint.go exec streaming).
        Returns the live client-side stream for the caller to bridge."""
        addr, secret = self._client_rpc_target(alloc_id)
        payload = dict(params or {}, alloc_id=alloc_id, secret=secret)
        return self._conn_pool().call_duplex(
            addr, "ClientAllocations.Exec", payload
        )

    def reconcile_summaries(self):
        """Rebuild job summaries from the alloc table through raft
        (ref system_endpoint.go ReconcileJobSummaries)."""
        self._check_leader()
        self._apply(fsm_mod.RECONCILE_SUMMARIES, {})

    def node_update_status(self, node_id: str, status: str) -> dict:
        self._check_leader()
        node = self.state.node_by_id(node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        if node.status != status:
            self._apply(
                fsm_mod.NODE_STATUS_UPDATE,
                {"node_id": node_id, "status": status, "updated_at": now_ns()},
            )
            self._create_node_evals(node_id)
        if status != NODE_STATUS_DOWN:
            self._reset_heartbeat(node_id)
        return {"heartbeat_ttl": self.heartbeat_ttl}

    def node_heartbeat(self, node_id: str) -> dict:
        """ref node_endpoint.go UpdateStatus heartbeat path + heartbeat.go"""
        self._check_leader()
        node = self.state.node_by_id(node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        if node.status == NODE_STATUS_DOWN:
            # heartbeat revives a down node
            return self.node_update_status(node_id, NODE_STATUS_READY)
        self._reset_heartbeat(node_id)
        return {"heartbeat_ttl": self.heartbeat_ttl}

    def node_drain(
        self,
        node_id: str,
        drain: bool,
        deadline_ns: int = 0,
        ignore_system_jobs: bool = False,
        mark_eligible: Optional[bool] = None,
    ):
        """ref node_endpoint.go UpdateDrain: the drainer subsystem paces the
        actual migrations; a deadline forces whatever remains."""
        self._check_leader()
        node_id = self._node_id_by_prefix(node_id)
        payload = {"node_id": node_id, "drain": drain, "updated_at": now_ns()}
        if drain:
            payload["drain_strategy"] = {
                "deadline": deadline_ns,
                "force_deadline": (now_ns() + deadline_ns) if deadline_ns > 0 else 0,
                "ignore_system_jobs": ignore_system_jobs,
            }
        else:
            # cancelling a drain re-marks eligible unless told otherwise
            payload["mark_eligible"] = (
                True if mark_eligible is None else mark_eligible
            )
        self._apply(fsm_mod.NODE_DRAIN_UPDATE, payload)
        if drain and self.drainer is not None:
            self.drainer.notify()
        self._create_node_evals(node_id)

    def node_update_eligibility(self, node_id: str, eligibility: str):
        self._check_leader()
        self._apply(
            fsm_mod.NODE_ELIGIBILITY_UPDATE,
            {
                "node_id": self._node_id_by_prefix(node_id),
                "eligibility": eligibility,
                "updated_at": now_ns(),
            },
        )

    def _node_id_by_prefix(self, node_id: str) -> str:
        """Resolve a short node ID to the full ID (the CLI prints 8-char
        prefixes, matching the reference's prefix-tolerant lookups)."""
        if self.state.node_by_id(node_id) is not None:
            return node_id
        matches = self.state.node_by_prefix(node_id)
        if len(matches) > 1:
            raise ValueError(
                f"ambiguous node prefix {node_id!r} ({len(matches)} matches)"
            )
        if not matches:
            raise KeyError(f"node not found: {node_id}")
        return matches[0].id

    def _reset_heartbeat(self, node_id: str):
        """ref heartbeat.go:33-212 resetHeartbeatTimer (leader-only)"""
        if not self._running or not self._leader:
            return
        with self._lock:
            old = self._heartbeat_timers.pop(node_id, None)
            if old is not None:
                old.cancel()
            handle_box: list = []
            handle = shared_timer_wheel().arm(
                self.heartbeat_ttl,
                self._enqueue_heartbeat_expiry,
                (node_id, handle_box),
            )
            # the callback identity-checks against the map under this
            # same lock, so it can't observe the box empty
            handle_box.append(handle)
            self._heartbeat_timers[node_id] = handle

    def _enqueue_heartbeat_expiry(self, node_id: str, handle_box: list):
        """Wheel callback: never do raft work on the wheel thread — a
        mass expiry would serialize there and freeze every other timer
        in the process (nack timeouts, other in-process servers). A
        queued expiry can't be retracted the way a timer cancel() could,
        so the map entry is claimed HERE, under the lock, only if this
        firing's handle is still the node's current one — and the
        drainer re-checks before acting."""
        with self._lock:
            if not self._running:
                return
            if self._heartbeat_timers.get(node_id) is not handle_box[0]:
                return  # stale fire: a heartbeat re-armed this node
            del self._heartbeat_timers[node_id]
            t = self._hb_expire_thread
            if t is None or not t.is_alive():
                t = threading.Thread(
                    target=self._drain_heartbeat_expirations,
                    name="heartbeat-expiry",
                    daemon=True,
                )
                self._hb_expire_thread = t
                t.start()
        self._hb_expire_q.put(node_id)

    def _drain_heartbeat_expirations(self):
        while True:
            node_id = self._hb_expire_q.get()
            if node_id is None:
                # stop() sentinel. A server can stop()+start() again,
                # and stop() enqueues unconditionally — a sentinel from
                # a PREVIOUS life must not kill the new life's drainer
                # (stranding that batch's expirations behind it)
                if not self._running:
                    return
                continue
            self._invalidate_heartbeat(node_id)

    def _invalidate_heartbeat(self, node_id: str):
        """Heartbeat missed → node down → node evals (ref heartbeat.go:150)."""
        with self._lock:
            if node_id in self._heartbeat_timers:
                # the node heartbeated between the expiry firing and this
                # drain — it is alive and freshly armed; downing it now
                # would flap a healthy node
                return
        try:
            node = self.state.node_by_id(node_id)
            if node is not None and node.status != NODE_STATUS_DOWN:
                logger.warning("node %s missed heartbeat; marking down", node_id[:8])
                self.node_update_status(node_id, NODE_STATUS_DOWN)
        except NotLeaderError:
            pass
        except Exception:
            logger.exception("heartbeat invalidation failed for %s", node_id)

    def _create_node_evals(self, node_id: str):
        """Create evals for all jobs with allocs on the node + system jobs
        (ref node_endpoint.go:1056 createNodeEvals)."""
        node = self.state.node_by_id(node_id)
        jobs: dict[tuple[str, str], Job] = {}
        for alloc in self.state.allocs_by_node(node_id):
            if alloc.job is not None and not alloc.terminal_status():
                jobs[(alloc.namespace, alloc.job_id)] = alloc.job
        for job in self.state.jobs_by_scheduler(JOB_TYPE_SYSTEM):
            if node is not None and node.datacenter in job.datacenters:
                jobs[(job.namespace, job.id)] = job

        evals = []
        for (ns, job_id), job in jobs.items():
            evals.append(
                Evaluation(
                    id=generate_uuid(),
                    namespace=ns,
                    priority=job.priority,
                    type=job.type,
                    triggered_by=EVAL_TRIGGER_NODE_UPDATE,
                    job_id=job_id,
                    node_id=node_id,
                    status=EVAL_STATUS_PENDING,
                    create_time=now_ns(),
                    modify_time=now_ns(),
                )
            )
        if evals:
            self._apply(
                fsm_mod.EVAL_UPDATE, {"evals": [e.to_dict() for e in evals]}
            )
        return [e.id for e in evals]

    # ------------------------------------------------------------------
    # Client alloc sync (ref node_endpoint.go:894 GetClientAllocs, :362
    # UpdateAlloc)
    # ------------------------------------------------------------------
    def get_client_allocs(
        self, node_id: str, min_index: int = 0, timeout: float = 30.0
    ) -> tuple[list[Allocation], int]:
        """Blocking query the client long-polls for its allocs."""
        def query(snap):
            return snap.allocs_by_node(node_id)

        return self.state.blocking_query(query, min_index=min_index, timeout=timeout)

    def update_allocs(self, allocs: list[Allocation]):
        """Client-reported alloc status; failed allocs trigger new evals in
        the same log entry (ref node_endpoint.go UpdateAlloc:1006-1053)."""
        self._check_leader()
        evals = []
        seen = set()
        for update in allocs:
            stored = self.state.alloc_by_id(update.id)
            job = stored.job if stored is not None else None
            if job is None:
                continue
            if update.client_terminal_status() and not stored.server_terminal_status():
                key = (stored.namespace, stored.job_id)
                if key in seen:
                    continue
                seen.add(key)
                evals.append(
                    Evaluation(
                        id=generate_uuid(),
                        namespace=stored.namespace,
                        priority=job.priority,
                        type=job.type,
                        triggered_by=EVAL_TRIGGER_RETRY_FAILED_ALLOC,
                        job_id=stored.job_id,
                        status=EVAL_STATUS_PENDING,
                        create_time=now_ns(),
                        modify_time=now_ns(),
                    )
                )
        self._apply(
            fsm_mod.ALLOC_CLIENT_UPDATE,
            {
                "allocs": [a.to_dict() for a in allocs],
                "evals": [e.to_dict() for e in evals],
            },
        )
        if self.vault.enabled():
            terminal = [a.id for a in allocs if a.client_terminal_status()]
            if terminal:
                # alloc done → its vault tokens die with it (vault.go
                # RevokeTokens on terminal allocations)
                self.vault.revoke_for_allocs(terminal)

    # ------------------------------------------------------------------
    # Eval endpoints (ref nomad/eval_endpoint.go)
    # ------------------------------------------------------------------
    def eval_dequeue(self, schedulers: list[str], timeout: float = 1.0):
        self._check_leader()
        return self.eval_broker.dequeue(schedulers, timeout)

    def eval_ack(self, eval_id: str, token: str):
        self._check_leader()
        self.eval_broker.ack(eval_id, token)

    def eval_nack(self, eval_id: str, token: str):
        self._check_leader()
        self.eval_broker.nack(eval_id, token)

    def update_evals(self, evals: list[Evaluation]):
        """Worker-side eval status writes (ref eval_endpoint.go Update)."""
        self._apply(
            fsm_mod.EVAL_UPDATE, {"evals": [e.to_dict() for e in evals]}
        )

    # ------------------------------------------------------------------
    def _make_preemption_evals(self, result) -> list[Evaluation]:
        """Follow-up evals for jobs whose allocs were preempted
        (ref plan_apply.go preemption eval creation)."""
        jobs = {}
        for allocs in result.node_preemptions.values():
            for alloc in allocs:
                stored = self.state.alloc_by_id(alloc.id)
                job = stored.job if stored is not None else None
                if job is not None:
                    jobs[(alloc.namespace, alloc.job_id)] = job
        evals = []
        for (ns, job_id), job in jobs.items():
            evals.append(
                Evaluation(
                    id=generate_uuid(),
                    namespace=ns,
                    priority=job.priority,
                    type=job.type,
                    triggered_by="preemption",
                    job_id=job_id,
                    status=EVAL_STATUS_PENDING,
                    create_time=now_ns(),
                    modify_time=now_ns(),
                )
            )
        return evals


# Deployment RPC surface (ref nomad/deployment_endpoint.go) lives in
# deployment_watcher.py; attach its methods to Server here.
install_deployment_endpoints(Server)
