"""Connect service mesh analog (ref Nomad 0.10 Consul Connect:
job_endpoint_hook_connect.go + Consul sidecar routing). An upstream
consumer reaches a connect service through two proxy hops: its local
upstream listener → the destination's sidecar → the service."""

import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.agent import ClientAgent, DevAgent, ServerAgent
from nomad_tpu.jobspec import parse_job
from nomad_tpu.structs.model import (
    ConsulConnect,
    ConsulProxy,
    ConsulSidecarService,
    ConsulUpstream,
    NetworkResource,
    Port,
    Service,
)


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class TestJobspecConnect:
    def test_parse_connect_stanza(self):
        job = parse_job(
            """
            job "mesh" {
              group "api" {
                task "server" {
                  driver = "raw_exec"
                  service {
                    name = "api"
                    port = "http"
                    connect {
                      sidecar_service {
                        proxy {
                          upstreams {
                            destination_name = "db"
                            local_bind_port  = 5432
                          }
                        }
                      }
                    }
                  }
                }
              }
            }
            """
        )
        svc = job.task_groups[0].tasks[0].services[0]
        assert svc.connect is not None
        assert svc.connect.sidecar_service is not None
        ups = svc.connect.sidecar_service.proxy.upstreams
        assert len(ups) == 1
        assert ups[0].destination_name == "db"
        assert ups[0].local_bind_port == 5432


def connect_service(name, port_label="", upstreams=None):
    proxy = (
        ConsulProxy(
            upstreams=[
                ConsulUpstream(destination_name=d, local_bind_port=p)
                for d, p in (upstreams or [])
            ]
        )
        if upstreams
        else None
    )
    return Service(
        name=name,
        port_label=port_label,
        connect=ConsulConnect(
            sidecar_service=ConsulSidecarService(proxy=proxy)
        ),
    )


class TestMeshEndToEnd:
    def test_upstream_traffic_flows_through_sidecars(self, tmp_path):
        agent = DevAgent(num_clients=1, server_config={"seed": 101})
        agent.start()
        try:
            # service job: python http.server on its allocated port,
            # exposed through a connect sidecar
            api = mock.job()
            api.id = "api-job"
            tg = api.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.name = "api"
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": [
                    "-c",
                    'echo mesh-payload > index.html; '
                    'exec python3 -m http.server "$NOMAD_PORT_api_http" '
                    "--bind 127.0.0.1",
                ],
            }
            task.resources.networks = [
                NetworkResource(mbits=1, dynamic_ports=[Port(label="http")])
            ]
            task.services = [connect_service("api", port_label="http")]
            agent.server.job_register(api)

            wait_until(
                lambda: any(
                    a.client_status == "running"
                    and a.connect_proxies.get("api")
                    for a in agent.server.state.allocs_by_job(
                        api.namespace, api.id
                    )
                ),
                msg="api sidecar published",
            )
            entries = agent.server.catalog_service("api-sidecar-proxy")
            assert entries and entries[0]["Port"] > 0

            # consumer job: reaches "api" only via its local upstream port
            bind_port = 29876
            out_file = tmp_path / "fetched.txt"
            web = mock.job()
            web.id = "web-job"
            wtg = web.task_groups[0]
            wtg.count = 1
            wtask = wtg.tasks[0]
            wtask.name = "web"
            wtask.driver = "raw_exec"
            wtask.config = {
                "command": "/bin/sh",
                "args": [
                    "-c",
                    "for i in $(seq 1 100); do "
                    f'python3 -c "import urllib.request;'
                    f"open('{out_file}','w').write("
                    f'urllib.request.urlopen(\'http://127.0.0.1:{bind_port}/\').read().decode())" '
                    "2>/dev/null && break; sleep 0.3; done; sleep 60",
                ],
            }
            wtask.resources.networks = []
            wtask.services = [
                connect_service("web", upstreams=[("api", bind_port)])
            ]
            agent.server.job_register(web)

            wait_until(
                lambda: out_file.exists()
                and out_file.read_text().strip() == "mesh-payload",
                timeout=45,
                msg="payload fetched through both sidecars",
            )
        finally:
            agent.stop()

    def test_mtls_sidecar_hops(self, tmp_path):
        """With cluster TLS, sidecar↔sidecar traffic is mutually
        authenticated: the mesh works end-to-end under TLS and a raw-TCP
        (unauthenticated) probe of the sidecar port is rejected."""
        import socket
        import tempfile

        from nomad_tpu.tlsutil import generate_dev_certs

        d = tempfile.mkdtemp(prefix="connect_tls_")
        server_tls = generate_dev_certs(d, "server")
        client_tls = generate_dev_certs(d, "client")

        server = ServerAgent(
            "ct0", config={"seed": 151, "heartbeat_ttl": 5.0, "tls": server_tls}
        )
        server.start(num_workers=2)
        node_agent = ClientAgent([server.address], tls=client_tls)
        try:
            node_agent.start()
            wait_until(
                lambda: server.server.state.node_by_id(node_agent.node.id)
                is not None,
                msg="tls node registered",
            )
            api_job = mock.job()
            api_job.id = "tls-api"
            tg = api_job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.name = "api"
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": [
                    "-c",
                    'echo tls-mesh > index.html; '
                    'exec python3 -m http.server "$NOMAD_PORT_api_http" '
                    "--bind 127.0.0.1",
                ],
            }
            task.resources.networks = [
                NetworkResource(mbits=1, dynamic_ports=[Port(label="http")])
            ]
            task.services = [connect_service("api", port_label="http")]
            server.server.job_register(api_job)
            wait_until(
                lambda: any(
                    a.client_status == "running" and a.connect_proxies.get("api")
                    for a in server.server.state.allocs_by_job(
                        api_job.namespace, api_job.id
                    )
                ),
                msg="tls api sidecar published",
            )

            bind_port = 29878
            out_file = tmp_path / "tls.txt"
            web = mock.job()
            web.id = "tls-web"
            wtg = web.task_groups[0]
            wtg.count = 1
            wtask = wtg.tasks[0]
            wtask.name = "web"
            wtask.driver = "raw_exec"
            wtask.config = {
                "command": "/bin/sh",
                "args": [
                    "-c",
                    "for i in $(seq 1 100); do "
                    f'python3 -c "import urllib.request;'
                    f"open('{out_file}','w').write("
                    f'urllib.request.urlopen(\'http://127.0.0.1:{bind_port}/\').read().decode())" '
                    "2>/dev/null && break; sleep 0.3; done; sleep 60",
                ],
            }
            wtask.resources.networks = []
            wtask.services = [
                connect_service("web", upstreams=[("api", bind_port)])
            ]
            server.server.job_register(web)
            wait_until(
                lambda: out_file.exists()
                and out_file.read_text().strip() == "tls-mesh",
                timeout=60,
                msg="payload fetched through the mTLS mesh",
            )

            # a raw-TCP client without cluster identity gets nothing
            (alloc,) = server.server.state.allocs_by_job(
                api_job.namespace, api_job.id
            )
            ep = alloc.connect_proxies["api"]
            with socket.create_connection((ep["ip"], ep["port"]), 5) as s:
                s.sendall(b"GET / HTTP/1.0\r\n\r\n")
                s.settimeout(3)
                try:
                    data = s.recv(1024)
                except (ConnectionResetError, socket.timeout, OSError):
                    data = b""
            assert b"tls-mesh" not in data, "plaintext probe must not reach the service"
        finally:
            node_agent.stop()
            server.stop()

    def test_remote_client_resolves_upstream_over_rpc(self, tmp_path):
        """Two node agents on the RPC tier: the consumer's upstream proxy
        resolves the destination sidecar via the Catalog.Service RPC."""
        server = ServerAgent("cn0", config={"seed": 103, "heartbeat_ttl": 5.0})
        server.start(num_workers=2)
        agents = [ClientAgent([server.address]) for _ in range(2)]
        try:
            for a in agents:
                a.start()
            wait_until(
                lambda: all(
                    server.server.state.node_by_id(a.node.id) is not None
                    for a in agents
                ),
                msg="nodes registered",
            )
            api = mock.job()
            api.id = "r-api"
            tg = api.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.name = "api"
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": [
                    "-c",
                    'echo remote-mesh > index.html; '
                    'exec python3 -m http.server "$NOMAD_PORT_api_http" '
                    "--bind 127.0.0.1",
                ],
            }
            task.resources.networks = [
                NetworkResource(mbits=1, dynamic_ports=[Port(label="http")])
            ]
            task.services = [connect_service("api", port_label="http")]
            server.server.job_register(api)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    and a.connect_proxies.get("api")
                    for a in server.server.state.allocs_by_job(
                        api.namespace, api.id
                    )
                ),
                msg="remote api sidecar published",
            )

            bind_port = 29877
            out_file = tmp_path / "remote.txt"
            web = mock.job()
            web.id = "r-web"
            wtg = web.task_groups[0]
            wtg.count = 1
            wtask = wtg.tasks[0]
            wtask.name = "web"
            wtask.driver = "raw_exec"
            wtask.config = {
                "command": "/bin/sh",
                "args": [
                    "-c",
                    "for i in $(seq 1 100); do "
                    f'python3 -c "import urllib.request;'
                    f"open('{out_file}','w').write("
                    f'urllib.request.urlopen(\'http://127.0.0.1:{bind_port}/\').read().decode())" '
                    "2>/dev/null && break; sleep 0.3; done; sleep 60",
                ],
            }
            wtask.resources.networks = []
            wtask.services = [
                connect_service("web", upstreams=[("api", bind_port)])
            ]
            server.server.job_register(web)
            wait_until(
                lambda: out_file.exists()
                and out_file.read_text().strip() == "remote-mesh",
                timeout=60,
                msg="payload fetched across agents through the mesh",
            )
        finally:
            for a in agents:
                a.stop()
            server.stop()
