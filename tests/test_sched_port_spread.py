"""Spread-iterator corpus ported from the reference
(scheduler/spread_test.go — cited per test): targeted percent spreads,
multi-attribute combination, even spread boosts across planning rounds,
max-penalty cases, and the even-spread boost helper."""

import random

from nomad_tpu import mock
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.propertyset import PropertySet
from nomad_tpu.scheduler.rank import (
    RankedNode,
    ScoreNormalizationIterator,
    StaticRankIterator,
)
from nomad_tpu.scheduler.spread import SpreadIterator, even_spread_score_boost
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs.model import (
    Allocation,
    Node,
    Plan,
    Spread,
    SpreadTarget,
    generate_uuid,
)


def collect_ranked(iterator):
    out = []
    while True:
        nxt = iterator.next()
        if nxt is None:
            return out
        out.append(nxt)


def job_alloc(job, tg, node_id):
    return Allocation(
        namespace="default",
        task_group=tg.name,
        job_id=job.id,
        job=job,
        id=generate_uuid(),
        eval_id=generate_uuid(),
        node_id=node_id,
    )


def run_spread(ctx, nodes, job, tg):
    for rn in nodes:
        rn.scores = []
        rn.final_score = 0.0
    static = StaticRankIterator(ctx, nodes)
    it = SpreadIterator(ctx, static)
    it.set_job(job)
    it.set_task_group(tg)
    return collect_ranked(ScoreNormalizationIterator(ctx, it))


class TestSpreadIteratorSingleAttribute:
    def test_targeted_percent_boosts_then_saturates(self):
        # ref TestSpreadIterator_SingleAttribute (spread_test.go:15)
        h = Harness(seed=42)
        dcs = ["dc1", "dc2", "dc1", "dc1"]
        nodes = []
        for i, dc in enumerate(dcs):
            n = mock.node()
            n.datacenter = dc
            h.state.upsert_node(100 + i, n)
            nodes.append(RankedNode(n))

        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 10
        h.state.upsert_allocs(1000, [
            job_alloc(job, tg, nodes[0].node.id),
            job_alloc(job, tg, nodes[2].node.id),
        ])

        tg.spreads = [
            Spread(
                weight=100, attribute="${node.datacenter}",
                spread_target=[SpreadTarget(value="dc1", percent=80)],
            )
        ]
        ctx = EvalContext(h.state.snapshot(), Plan(), rng=random.Random(7))
        out = run_spread(ctx, nodes, job, tg)

        # boost = (desired - actual) / desired: dc1 (8-3)/8 -> .625 after
        # this placement; dc2 (2-1)/2 -> .5
        expected = {"dc1": 0.625, "dc2": 0.5}
        for rn in out:
            assert rn.final_score == expected[rn.node.datacenter], (
                rn.node.datacenter, rn.final_score,
            )

        # add planned allocs until dc1 meets its desired count; dc1 stops
        # boosting, dc2 keeps its boost. A different job's alloc on the
        # same node must be ignored.
        ctx.plan.node_allocation[nodes[0].node.id] = [
            job_alloc(job, tg, nodes[0].node.id),
            job_alloc(job, tg, nodes[0].node.id),
            Allocation(
                namespace="default", task_group="bbb", job_id="ignore 2",
                job=job, id=generate_uuid(), node_id=nodes[0].node.id,
            ),
        ]
        ctx.plan.node_allocation[nodes[3].node.id] = [
            job_alloc(job, tg, nodes[3].node.id) for _ in range(3)
        ]
        out = run_spread(ctx, nodes, job, tg)
        expected = {"dc1": 0.0, "dc2": 0.5}
        for rn in out:
            assert rn.final_score == expected[rn.node.datacenter]


class TestSpreadIteratorMultipleAttributes:
    def test_two_weighted_spreads_combine(self):
        # ref TestSpreadIterator_MultipleAttributes (spread_test.go:173)
        h = Harness(seed=42)
        dcs = ["dc1", "dc2", "dc1", "dc1"]
        racks = ["r1", "r1", "r2", "r2"]
        nodes = []
        for i, dc in enumerate(dcs):
            n = mock.node()
            n.datacenter = dc
            n.meta["rack"] = racks[i]
            h.state.upsert_node(100 + i, n)
            nodes.append(RankedNode(n))

        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 10
        h.state.upsert_allocs(1000, [
            job_alloc(job, tg, nodes[0].node.id),
            job_alloc(job, tg, nodes[2].node.id),
        ])

        tg.spreads = [
            Spread(
                weight=100, attribute="${node.datacenter}",
                spread_target=[
                    SpreadTarget(value="dc1", percent=60),
                    SpreadTarget(value="dc2", percent=40),
                ],
            ),
            Spread(
                weight=50, attribute="${meta.rack}",
                spread_target=[
                    SpreadTarget(value="r1", percent=40),
                    SpreadTarget(value="r2", percent=60),
                ],
            ),
        ]
        ctx = EvalContext(h.state.snapshot(), Plan(), rng=random.Random(7))
        out = run_spread(ctx, nodes, job, tg)

        expected = {
            nodes[0].node.id: 0.500,
            nodes[1].node.id: 0.667,
            nodes[2].node.id: 0.556,
            nodes[3].node.id: 0.556,
        }
        for rn in out:
            assert f"{rn.final_score:.3f}" == f"{expected[rn.node.id]:.3f}"


class TestSpreadIteratorEvenSpread:
    def test_even_spread_across_planning_rounds(self):
        # ref TestSpreadIterator_EvenSpread (spread_test.go:274)
        h = Harness(seed=42)
        dcs = [
            "dc1", "dc2", "dc1", "dc2", "dc1",
            "dc2", "dc2", "dc1", "dc1", "dc1",
        ]
        nodes = []
        for i, dc in enumerate(dcs):
            n = mock.node()
            n.datacenter = dc
            h.state.upsert_node(100 + i, n)
            nodes.append(RankedNode(n))

        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 10
        tg.spreads = [
            Spread(weight=100, attribute="${node.datacenter}")
        ]
        ctx = EvalContext(h.state.snapshot(), Plan(), rng=random.Random(7))

        # nothing placed: every node scores 0
        out = run_spread(ctx, nodes, job, tg)
        for rn in out:
            assert f"{rn.final_score:.3f}" == "0.000"

        # one alloc in each of two dc1 nodes: dc1 penalized, dc2 boosted
        ctx.plan.node_allocation[nodes[0].node.id] = [
            job_alloc(job, tg, nodes[0].node.id)
        ]
        ctx.plan.node_allocation[nodes[2].node.id] = [
            job_alloc(job, tg, nodes[2].node.id)
        ]
        out = run_spread(ctx, nodes, job, tg)
        expected = {"dc1": -1.0, "dc2": 1.0}
        for rn in out:
            assert rn.final_score == expected[rn.node.datacenter]

        # three allocs in dc2 vs two in dc1: boosts flip proportionally
        ctx.plan.node_allocation[nodes[1].node.id] = [
            job_alloc(job, tg, nodes[1].node.id) for _ in range(2)
        ]
        ctx.plan.node_allocation[nodes[3].node.id] = [
            job_alloc(job, tg, nodes[3].node.id)
        ]
        out = run_spread(ctx, nodes, job, tg)
        expected = {"dc1": 0.5, "dc2": -0.5}
        for rn in out:
            assert f"{rn.final_score:.3f}" == f"{expected[rn.node.datacenter]:.3f}"

        # a fresh dc3 node appears and dc1 catches up to dc2: the empty
        # dc gets the max boost, the full ones the max penalty
        n = mock.node()
        n.datacenter = "dc3"
        h.state.upsert_node(1111, n)
        nodes.append(RankedNode(n))
        ctx = EvalContext(
            h.state.snapshot(), ctx.plan, rng=random.Random(7)
        )
        ctx.plan.node_allocation[nodes[4].node.id] = [
            job_alloc(job, tg, nodes[4].node.id)
        ]
        out = run_spread(ctx, nodes, job, tg)
        expected = {"dc1": -1.0, "dc2": -1.0, "dc3": 1.0}
        for rn in out:
            assert f"{rn.final_score:.3f}" == f"{expected[rn.node.datacenter]:.3f}"


class TestSpreadIteratorMaxPenalty:
    def test_unmatched_target_and_missing_attribute_score_minus_one(self):
        # ref TestSpreadIterator_MaxPenalty (spread_test.go:462)
        h = Harness(seed=42)
        nodes = []
        for i in range(5):
            n = mock.node()
            n.datacenter = "dc3"
            h.state.upsert_node(100 + i, n)
            nodes.append(RankedNode(n))

        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 5
        tg.spreads = [
            Spread(
                weight=100, attribute="${node.datacenter}",
                spread_target=[
                    SpreadTarget(value="dc1", percent=80),
                    SpreadTarget(value="dc2", percent=20),
                ],
            )
        ]
        ctx = EvalContext(h.state.snapshot(), Plan(), rng=random.Random(7))
        out = run_spread(ctx, nodes, job, tg)
        for rn in out:
            assert rn.final_score == -1.0

        # spread on an attribute no node carries: also max penalty
        tg.spreads = [
            Spread(
                weight=100, attribute="${meta.foo}",
                spread_target=[
                    SpreadTarget(value="bar", percent=80),
                    SpreadTarget(value="baz", percent=20),
                ],
            )
        ]
        out = run_spread(ctx, nodes, job, tg)
        for rn in out:
            assert rn.final_score == -1.0


class TestEvenSpreadScoreBoostHelper:
    def test_cleared_values_do_not_divide_by_zero(self):
        # ref Test_evenSpreadScoreBoost (spread_test.go:549)
        job = mock.job()
        h = Harness(seed=42)
        ctx = EvalContext(h.state.snapshot(), Plan(), rng=random.Random(7))
        pset = PropertySet(ctx, job)
        pset.existing_values = {}
        pset.proposed_values = {"dc2": 1, "dc1": 1, "dc3": 1}
        pset.cleared_values = {"dc2": 1, "dc3": 1}
        pset.target_attribute = "${node.datacenter}"

        boost = even_spread_score_boost(pset, Node(datacenter="dc2"))
        assert boost == 1.0
