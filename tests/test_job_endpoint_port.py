"""Job endpoint validation port (ref nomad/job_endpoint_test.go
TestJobEndpoint_Register_* validation slices + structs_test.go
TestJob_Validate).

Admission-time rejection contract: a malformed job never reaches the
raft log — ``_validate_job`` raises before ``_apply``, so a bad submit
costs nothing cluster-wide and the submitter gets the precise reason.
The cases here mirror the upstream validation set that this repo
implements: identity/type basics, the priority band (which also feeds
the overload admission classes — see core/overload.classify_priority),
the periodic constraints (batch-only, cron-validated, exclusive with
parameterized), and task-group shape.
"""

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.core.server import Server
from nomad_tpu.raft import InmemTransport, RaftConfig
from nomad_tpu.structs.model import (
    JOB_MAX_PRIORITY,
    JOB_MIN_PRIORITY,
    JOB_TYPE_BATCH,
    JOB_TYPE_SERVICE,
    ParameterizedJobConfig,
    PeriodicConfig,
)

validate = Server._validate_job


class TestValidateBasics:
    def test_valid_job_passes(self):
        validate(mock.job())
        validate(mock.batch_job())
        validate(mock.system_job())
        validate(mock.periodic_job())

    def test_missing_id_rejected(self):
        j = mock.job()
        j.id = ""
        with pytest.raises(ValueError, match="missing job ID"):
            validate(j)

    def test_no_task_groups_rejected_unless_stop(self):
        j = mock.job()
        j.task_groups = []
        with pytest.raises(ValueError, match="at least one task group"):
            validate(j)
        # a stop-submit is a tombstone, not a spec: shape checks waived
        j.stop = True
        validate(j)

    def test_core_type_rejected(self):
        j = mock.job()
        j.type = "_core"
        with pytest.raises(ValueError, match="cannot be core"):
            validate(j)

    def test_task_group_shape(self):
        j = mock.job()
        j.task_groups[0].count = -1
        with pytest.raises(ValueError, match="count must be >= 0"):
            validate(j)
        j = mock.job()
        j.task_groups[0].tasks = []
        with pytest.raises(ValueError, match="at least one task"):
            validate(j)


class TestValidatePriority:
    def test_band_edges(self):
        for p in (JOB_MIN_PRIORITY, 50, JOB_MAX_PRIORITY):
            j = mock.job()
            j.priority = p
            validate(j)

    @pytest.mark.parametrize("priority", [0, -1, 101, 200])
    def test_out_of_band_rejected(self, priority):
        j = mock.job()
        j.priority = priority
        with pytest.raises(ValueError, match="priority must be between"):
            validate(j)


class TestValidatePeriodic:
    def test_periodic_requires_batch(self):
        j = mock.periodic_job()
        j.type = JOB_TYPE_SERVICE
        with pytest.raises(ValueError, match="batch jobs"):
            validate(j)

    def test_periodic_cannot_be_parameterized(self):
        j = mock.periodic_job()
        j.parameterized_job = ParameterizedJobConfig()
        with pytest.raises(ValueError, match="cannot also be parameterized"):
            validate(j)

    def test_disabled_periodic_skips_periodic_checks(self):
        # enabled=False means "not periodic" everywhere (is_periodic());
        # the stanza may ride along on any type without the batch bound
        j = mock.job()
        j.periodic = PeriodicConfig(enabled=False, spec="not a cron")
        validate(j)

    def test_bad_cron_spec_rejected(self):
        j = mock.periodic_job()
        j.periodic.spec = "bad cron"
        with pytest.raises(Exception):
            validate(j)

    def test_unknown_spec_type_rejected(self):
        j = mock.periodic_job()
        j.periodic.spec_type = "iso8601"
        with pytest.raises(ValueError, match="unknown periodic spec type"):
            validate(j)


class TestRegisterEndpoint:
    """End-to-end: the rejection happens at the endpoint, before raft."""

    def _server(self):
        s = Server(
            {
                "seed": 7,
                "raft": {
                    "node_id": "s0",
                    "address": "jobep0",
                    "voters": {"s0": "jobep0"},
                    "transport": InmemTransport(),
                    "config": RaftConfig(
                        heartbeat_interval=0.02,
                        election_timeout_min=0.05,
                        election_timeout_max=0.10,
                    ),
                },
            }
        )
        s.start(num_workers=0, wait_for_leader=5.0)
        return s

    def test_register_rejects_before_raft_and_accepts_valid(self):
        s = self._server()
        try:
            bad = mock.job()
            bad.priority = 400
            idx_before = s.state.latest_index()
            with pytest.raises(ValueError, match="priority must be between"):
                s.job_register(bad)
            assert s.state.latest_index() == idx_before  # nothing applied
            assert s.state.job_by_id(bad.namespace, bad.id) is None

            ok = mock.job()
            s.job_register(ok)
            assert s.state.job_by_id(ok.namespace, ok.id) is not None
        finally:
            s.stop()

    def test_periodic_service_rejected_at_register(self):
        s = self._server()
        try:
            j = mock.job()  # type=service
            j.periodic = PeriodicConfig(
                enabled=True, spec_type="cron", spec="*/5 * * * *"
            )
            with pytest.raises(ValueError, match="batch jobs"):
                s.job_register(j)
            assert s.state.job_by_id(j.namespace, j.id) is None
        finally:
            s.stop()
