"""Live template rendering with change_mode (ref client/allocrunner/
taskrunner/template/template.go:408-445: the reference runs consul-template,
re-renders when upstream data — service catalog entries, vault secrets —
changes, and restarts or signals the task per the template's change_mode).

Template language: the task-env ${...} interpolation (taskenv) extended
with two DYNAMIC sources, each recorded into the template's watch set so
the poll loop re-queries only what the template actually reads:

    ${service.<name>}           all passing addresses, "ip:port,ip:port"
    ${service.<name>.first}     first passing address (stable choice)
    ${vault.<path>.<field>}     field of a Vault KV secret (v1 or v2),
                                read with the task's own vault token

A change in any watched value re-renders; a changed destination file then
applies change_mode: "noop" (nothing), "restart" (restart the task outside
the restart-policy budget), or "signal" (deliver change_signal)."""

from __future__ import annotations

import logging
import os
import re
import threading
from typing import Callable, Optional

from . import taskenv

logger = logging.getLogger("nomad_tpu.template")

_DYNAMIC = re.compile(r"\$\{(service|vault)\.([^}]+)\}")


def resolve_service(entries: list) -> dict:
    """Catalog entries → the template's value forms."""
    addrs = [
        f"{e.get('Address', '')}:{e.get('Port', 0)}"
        for e in entries
        if e.get("Status", "passing") == "passing"
    ]
    return {"all": ",".join(addrs), "first": addrs[0] if addrs else ""}


class TemplateSources:
    """Dynamic lookups for one task's templates: the service catalog via
    the client's server transport, Vault KV via the task's own token."""

    def __init__(
        self,
        catalog: Optional[Callable[[str], list]] = None,
        vault_addr: str = "",
        vault_token: str = "",
    ):
        self.catalog = catalog
        self.vault_addr = vault_addr.rstrip("/")
        self.vault_token = vault_token

    def service(self, name: str) -> dict:
        if self.catalog is None:
            return {"all": "", "first": ""}
        try:
            return resolve_service(self.catalog(name))
        except Exception:
            logger.warning("service lookup failed for %s", name, exc_info=True)
            return {"all": "", "first": ""}

    def vault_read(self, path: str) -> dict:
        """Read a KV secret's data dict; v2 responses nest data.data."""
        if not self.vault_addr:
            return {}
        import json
        import urllib.request

        req = urllib.request.Request(
            f"{self.vault_addr}/v1/{path.lstrip('/')}",
            headers={"X-Vault-Token": self.vault_token},
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                doc = json.loads(resp.read() or b"{}")
        except Exception:
            logger.warning("vault read failed for %s", path, exc_info=True)
            return {}
        data = doc.get("data") or {}
        inner = data.get("data")
        if isinstance(inner, dict) and "metadata" in data:
            return inner  # KV v2
        return data


def render(
    content: str,
    env: dict,
    node,
    sources: TemplateSources,
    watch: Optional[dict] = None,
) -> str:
    """Render one template: dynamic refs first (recording each into
    ``watch`` as {("service", name) | ("vault", path): observed-value}),
    then the standard task-env interpolation."""

    def sub(m: re.Match) -> str:
        kind, rest = m.group(1), m.group(2)
        if kind == "service":
            name, _, attr = rest.partition(".")
            values = sources.service(name)
            if watch is not None:
                watch[("service", name)] = values["all"]
            return values["first"] if attr == "first" else values["all"]
        path, _, field = rest.rpartition(".")
        if not path:  # no field separator: whole-secret ref is invalid
            path, field = rest, ""
        data = sources.vault_read(path)
        value = str(data.get(field, "")) if field else ""
        if watch is not None:
            watch[("vault", path)] = tuple(sorted(data.items()))
        return value

    content = _DYNAMIC.sub(sub, content)
    return taskenv.interpolate(content, env, node)


class TemplateManager:
    """Re-render loop for one task (the template_hook's poststart half).

    Polls the watch set; on change re-renders every template and applies
    change_mode for those whose DESTINATION content changed (the
    reference's render-event → task-runner restart/signal path)."""

    def __init__(
        self,
        task,
        task_dir: str,
        env: dict,
        node,
        sources: TemplateSources,
        restart_fn: Callable[[], None],
        signal_fn: Callable[[str], None],
        event_fn: Callable[[str, str], None],
        poll_interval: float = 3.0,
    ):
        self.task = task
        self.task_dir = task_dir
        self.env = env
        self.node = node
        self.sources = sources
        self.restart_fn = restart_fn
        self.signal_fn = signal_fn
        self.event_fn = event_fn
        self.poll_interval = poll_interval
        self._watch: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- rendering ------------------------------------------------------
    def _dest(self, template) -> str:
        from .hooks import _contained

        return _contained(self.task_dir, template.dest_path)

    def render_all(self, first: bool = False) -> list:
        """Render every template; returns the templates whose destination
        content changed. ``first`` renders unconditionally (prestart)."""
        changed = []
        self._watch.clear()
        for template in self.task.templates:
            content = template.embedded_tmpl
            if not content and template.source_path:
                from .hooks import HookError, _contained

                try:
                    with open(
                        _contained(self.task_dir, template.source_path)
                    ) as f:
                        content = f.read()
                except OSError as e:
                    if first:
                        # prestart contract: a broken template fails the
                        # start (templates_hook semantics)
                        raise HookError(
                            f"template source unreadable: {e}"
                        ) from e
                    continue  # transientally unreadable mid-flight: skip
            rendered = render(
                content, self.env, self.node, self.sources, self._watch
            )
            dest = self._dest(template)
            previous = None
            if not first and os.path.exists(dest):
                try:
                    with open(dest) as f:
                        previous = f.read()
                except OSError:
                    previous = None
            if first or previous != rendered:
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                with open(dest, "w") as f:
                    f.write(rendered)
                try:
                    os.chmod(dest, int(template.perms or "0644", 8))
                except (ValueError, OSError):
                    pass
                if not first:
                    changed.append(template)
        return changed

    def _watched_current(self) -> dict:
        now: dict = {}
        for key in list(self._watch):
            kind, ident = key
            if kind == "service":
                now[key] = self.sources.service(ident)["all"]
            else:
                now[key] = tuple(sorted(self.sources.vault_read(ident).items()))
        return now

    # -- loop -----------------------------------------------------------
    def start(self):
        """Start the re-render loop; only worthwhile when some template
        watches a dynamic source."""
        if not self._watch:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="template-manager"
        )
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(self.poll_interval):
            try:
                if self._watched_current() == self._watch:
                    continue
                changed = self.render_all()
            except Exception:
                logger.exception("template re-render failed")
                continue
            if not changed:
                continue
            self._apply_change_modes(changed)

    def _apply_change_modes(self, changed: list):
        """One restart covers any number of changed restart-templates
        (template.go:408-445 coalesces); each signal template delivers its
        own signal."""
        modes = {t.change_mode or "restart" for t in changed}
        signals = {
            t.change_signal
            for t in changed
            if (t.change_mode or "restart") == "signal" and t.change_signal
        }
        if "restart" in modes:
            self.event_fn(
                "Template", "Template with change_mode restart re-rendered"
            )
            try:
                self.restart_fn()
            except Exception as e:
                logger.warning("template restart failed: %s", e)
            return
        for sig in signals:
            self.event_fn(
                "Template", f"Template re-rendered, signaling {sig}"
            )
            try:
                self.signal_fn(sig)
            except Exception as e:
                logger.warning("template signal failed: %s", e)
