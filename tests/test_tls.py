"""mTLS on the RPC tier (ref helper/tlsutil: CA-pinned mutual TLS over
the muxed RPC/raft listener)."""

import socket
import tempfile
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.agent import ServerAgent
from nomad_tpu.rpc import ConnPool, RpcError
from nomad_tpu.tlsutil import client_context, contexts_from_config, generate_dev_certs


@pytest.fixture(scope="module")
def certs():
    d = tempfile.mkdtemp(prefix="nomad_tls_")
    return {
        "server": generate_dev_certs(d, "server"),
        "client": generate_dev_certs(d, "client"),
        # a SECOND CA: certs from it must be rejected by the cluster CA
        "foreign": generate_dev_certs(tempfile.mkdtemp(prefix="nomad_tls2_"), "evil"),
    }


class TestMutualTLS:
    def test_tls_cluster_serves_and_rejects(self, certs):
        server = ServerAgent(
            "tls-s1",
            config={"seed": 42, "heartbeat_ttl": 60.0, "tls": certs["server"]},
        )
        server.start(num_workers=1, wait_for_leader=5.0)
        try:
            # CA-signed client: full scheduling round-trip over TLS
            ctx = client_context(**certs["client"])
            pool = ConnPool(tls_context=ctx)
            pool.call(
                server.address, "Node.Register", {"node": mock.node().to_dict()}
            )
            job = mock.job()
            job.task_groups[0].count = 1
            job.task_groups[0].tasks[0].resources.networks = []
            eval_id = pool.call(
                server.address, "Job.Register", {"job": job.to_dict()}
            )
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                ev = server.server.state.eval_by_id(eval_id)
                if ev is not None and ev.status == "complete":
                    break
                time.sleep(0.05)
            assert server.server.state.eval_by_id(eval_id).status == "complete"
            pool.close()

            # plaintext caller: refused at the handshake
            plain = ConnPool(timeout=2.0)
            with pytest.raises((RpcError, OSError, ConnectionError)):
                plain.call(server.address, "Status.Leader", {})
            plain.close()

            # cert from a FOREIGN CA: mutual verification rejects it
            evil_ctx = client_context(**certs["foreign"])
            evil = ConnPool(timeout=2.0, tls_context=evil_ctx)
            with pytest.raises((RpcError, OSError, ConnectionError)):
                evil.call(server.address, "Status.Leader", {})
            evil.close()
        finally:
            server.stop()

    def test_contexts_require_full_config(self):
        from nomad_tpu.tlsutil import TLSError

        with pytest.raises(TLSError):
            contexts_from_config({"ca": "/x"})
        assert contexts_from_config({}) == (None, None)
