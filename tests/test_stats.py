"""Host and alloc stats (ref client/stats/host.go, drivers/shared/executor
pid stats, client_stats_endpoint.go, client_alloc_endpoint.go Stats)."""

import os
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.agent import ClientAgent, DevAgent, ServerAgent
from nomad_tpu.api.client import ApiClient
from nomad_tpu.api.http import HTTPServer
from nomad_tpu.client.stats import (
    HostStatsCollector,
    disk_stats,
    pid_stats,
    task_resource_usage,
)


def wait_until(fn, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class TestCollectors:
    def test_host_stats_shape(self):
        from nomad_tpu.client.stats import _read_proc_stat

        c = HostStatsCollector("/")
        first = c.collect()
        assert first["memory"]["total"] > 0
        assert first["disk"]["size"] > 0
        assert first["uptime_s"] > 0
        st = _read_proc_stat()
        if st is None or st["total"] == 0:
            # sandboxed kernels pin /proc/stat at zero: there is no CPU
            # accounting to measure, only the shape assertions above apply
            pytest.skip("kernel exposes no CPU accounting in /proc/stat")
        # burn a little cpu so the delta sample is nonzero somewhere
        sum(i * i for i in range(200_000))
        second = c.collect()
        cpu = second["cpu"]
        # each component is a valid percentage; their sum is NOT asserted
        # against 100 because irq/steal/guest time is intentionally
        # unaccounted and can be large on a loaded/virtualized box
        for key in ("total_percent", "user_percent", "system_percent", "idle_percent"):
            assert 0.0 <= cpu[key] <= 100.0, (key, cpu)
        # busy + idle partition the total by construction
        assert abs(cpu["total_percent"] + cpu["idle_percent"] - 100.0) < 1.0

    def test_zero_delta_returns_previous_sample(self, monkeypatch):
        """Two collects inside one /proc/stat tick: the second must serve
        the previous percentages, not fabricate 0% CPU (the full-suite
        flake: back-to-back samplers landing in the same jiffy)."""
        import nomad_tpu.client.stats as stats_mod

        samples = iter([
            {"user": 100, "system": 50, "idle": 850, "total": 1000},
            {"user": 150, "system": 75, "idle": 1275, "total": 1500},
            {"user": 150, "system": 75, "idle": 1275, "total": 1500},
        ])
        monkeypatch.setattr(stats_mod, "_read_proc_stat", lambda: next(samples))
        c = HostStatsCollector("/")  # consumes the baseline sample
        first = c.collect()["cpu"]
        assert first["total_percent"] == 15.0
        assert first["idle_percent"] == 85.0
        second = c.collect()["cpu"]  # zero delta → previous sample
        assert second == first

    def test_disk_stats_used_percent(self):
        d = disk_stats("/tmp")
        assert d["size"] >= d["used"] >= 0
        assert 0.0 <= d["used_percent"] <= 100.0

    def test_pid_stats_self(self):
        st = pid_stats(os.getpid())
        assert st is not None
        assert st["rss_bytes"] > 1 << 20  # a python process holds >1MiB
        assert st["cpu_time_s"] >= 0.0

    def test_pid_stats_gone(self):
        assert pid_stats(2**22 - 3) is None

    def test_task_resource_usage_subprocess(self):
        import subprocess
        import threading

        from nomad_tpu.client.driver import TaskHandle

        proc = subprocess.Popen(["sleep", "5"])
        handle = TaskHandle(task_name="t", pid=proc.pid)
        try:
            # rss can read 0 for an instant mid-exec; settle briefly
            deadline = time.monotonic() + 5
            usage = task_resource_usage(handle)
            while usage["rss_bytes"] == 0 and time.monotonic() < deadline:
                time.sleep(0.05)
                usage = task_resource_usage(handle)
            assert usage["pids"] >= 1
            assert usage["rss_bytes"] > 0
        finally:
            proc.kill()
            proc.wait()


class TestStatsSurface:
    @pytest.fixture()
    def dev(self):
        agent = DevAgent(num_clients=1, server_config={"seed": 41})
        agent.start()
        http = HTTPServer(agent.server, port=0, agent=agent)
        http.start()
        client = ApiClient(address=http.address)
        yield agent, client
        http.stop()
        agent.stop()

    def test_client_stats_local(self, dev):
        agent, client = dev
        stats = client.client_stats()
        assert stats["node_id"] == agent.clients[0].node.id
        assert stats["memory"]["total"] > 0

    def test_alloc_stats_local_real_process(self, dev):
        agent, client = dev
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = 1
        tg.tasks[0].driver = "raw_exec"
        tg.tasks[0].config = {"command": "/bin/sleep", "args": ["60"]}
        tg.tasks[0].resources.networks = []
        agent.server.job_register(job)
        wait_until(
            lambda: any(
                a.client_status == "running"
                for a in agent.server.state.allocs_by_job(job.namespace, job.id)
            ),
            msg="raw_exec running",
        )
        (alloc,) = agent.server.state.allocs_by_job(job.namespace, job.id)
        stats = client.alloc_stats(alloc.id)
        assert stats["alloc_id"] == alloc.id
        web = stats["tasks"]["web"]
        assert web["state"] == "running"
        assert web["pids"] >= 1
        assert web["rss_bytes"] > 0

    def test_remote_stats_forwarding(self):
        server = ServerAgent("st0", config={"seed": 43, "heartbeat_ttl": 5.0})
        server.start(num_workers=2)
        node_agent = ClientAgent([server.address])
        http = HTTPServer(server.server, port=0, agent=None)
        http.start()
        api = ApiClient(address=http.address)
        try:
            node_agent.start()
            wait_until(
                lambda: server.server.state.node_by_id(node_agent.node.id)
                is not None,
                msg="node registered",
            )
            stats = api.client_stats(node_agent.node.id)
            assert stats["node_id"] == node_agent.node.id
            assert stats["memory"]["total"] > 0

            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.tasks[0].driver = "mock_driver"
            tg.tasks[0].config = {"run_for": "60s"}
            tg.tasks[0].resources.networks = []
            server.server.job_register(job)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    for a in server.server.state.allocs_by_job(
                        job.namespace, job.id
                    )
                ),
                msg="remote alloc running",
            )
            (alloc,) = server.server.state.allocs_by_job(job.namespace, job.id)
            stats = api.alloc_stats(alloc.id)
            assert stats["tasks"]["web"]["state"] == "running"
        finally:
            http.stop()
            node_agent.stop()
            server.stop()


class TestWorkloadRollup:
    def test_client_stats_include_alloc_usage(self):
        """Host stats carry the per-task usage rollup across local allocs
        (driver TaskStats aggregated client-side)."""
        agent = DevAgent(num_clients=1, server_config={"seed": 47})
        agent.start()
        http = HTTPServer(agent.server, port=0, agent=agent)
        http.start()
        client = ApiClient(address=http.address)
        try:
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.tasks[0].driver = "raw_exec"
            tg.tasks[0].config = {"command": "/bin/sleep", "args": ["60"]}
            tg.tasks[0].resources.networks = []
            agent.server.job_register(job)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    for a in agent.server.state.allocs_by_job(
                        job.namespace, job.id
                    )
                ),
                msg="raw_exec running",
            )
            stats = client.client_stats()
            usage = stats["allocs_usage"]
            assert usage["pids"] >= 1
            assert usage["rss_bytes"] > 0
        finally:
            http.stop()
            agent.stop()
