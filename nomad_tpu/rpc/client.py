"""Pooled RPC client + the typed server proxy (ref helper/pool/pool.go
conn pooling and api/ typed client).

``ConnPool.call`` retries once on a not_leader error by re-dialing the
leader address the error carries — the follower→leader forwarding model
(the reference forwards server-side, rpc.go:433; doing it client-side
keeps the wire format trivial and the hop count identical).

A per-address circuit breaker quarantines peers whose connections keep
failing (severed/partitioned servers): after ``circuit_threshold``
consecutive connection-class failures the address fails fast with a
``circuit_open`` error for ``circuit_cooldown`` seconds instead of
re-dialing in a hot loop (the reference reaches the same outcome through
its server manager's failure-ranked rebalancing, client/servers/
manager.go).

``ServerProxy`` exposes the same method surface as ``core.Server`` so the
node agent (client/client.py) works identically in-process or over TCP.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

from .. import metrics
from ..testing import faults as _faults


class RpcError(Exception):
    def __init__(self, code: str, message: str, leader_rpc_addr: Optional[str] = None,
                 retry_after: Optional[float] = None):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message
        self.leader_rpc_addr = leader_rpc_addr
        #: server-supplied pacing hint, set on code == "overloaded": how
        #: long the caller should wait before resubmitting shed work
        self.retry_after = retry_after


class ConnPool:
    """ONE multiplexed session per server address (the yamux-analog pool,
    ref helper/pool + nomad/rpc.go:243): every concurrent call — unary,
    streaming, or duplex — is a logical stream on the shared connection,
    so the process holds one socket per peer regardless of in-flight call
    count. Dead sessions are replaced on next use."""

    #: consecutive connection-class failures before the circuit opens
    CIRCUIT_THRESHOLD = 3
    #: seconds a tripped address fails fast before a probe dial is allowed
    CIRCUIT_COOLDOWN = 5.0
    #: total attempts a call may spend chasing a moving leader: a
    #: not_leader WITH a hint hops to the hinted address; one WITHOUT a
    #: hint means an election is in flight — back off and re-ask the same
    #: server, which answers with the new leader once a quorum knows it
    LEADER_RETRIES = 6
    LEADER_BACKOFF_BASE = 0.02
    LEADER_BACKOFF_MAX = 0.25

    def __init__(self, timeout: float = 10.0, tls_context=None, name: str = "",
                 circuit_threshold: Optional[int] = None,
                 circuit_cooldown: Optional[float] = None):
        self.timeout = timeout
        self.tls_context = tls_context
        #: identity reported to the fault plane as the call source
        self.name = name
        self.circuit_threshold = (
            circuit_threshold
            if circuit_threshold is not None
            else self.CIRCUIT_THRESHOLD
        )
        self.circuit_cooldown = (
            circuit_cooldown
            if circuit_cooldown is not None
            else self.CIRCUIT_COOLDOWN
        )
        self._sessions: dict[str, "MuxSession"] = {}
        # addr -> [consecutive_failures, open_until_monotonic]
        self._circuit: dict[str, list] = {}
        self._lock = threading.Lock()

    # -- circuit breaker -----------------------------------------------
    def _circuit_check(self, addr: str):
        """Fail fast while ``addr``'s circuit is open; past the cooldown
        the next call probes the address again (half-open)."""
        with self._lock:
            entry = self._circuit.get(addr)
            if entry is not None and entry[1] > time.monotonic():
                raise RpcError(
                    "circuit_open",
                    f"{addr}: quarantined after {entry[0]} connection failures",
                )

    def _circuit_record(self, addr: str, ok: bool):
        with self._lock:
            if ok:
                self._circuit.pop(addr, None)
                return
            entry = self._circuit.setdefault(addr, [0, 0.0])
            entry[0] += 1
            if entry[0] >= self.circuit_threshold:
                entry[1] = time.monotonic() + self.circuit_cooldown
                metrics.incr("rpc.circuit_open")

    def circuit_state(self, addr: str) -> dict:
        """Observability/test hook: {failures, open} for ``addr``."""
        with self._lock:
            entry = self._circuit.get(addr)
            return {
                "failures": entry[0] if entry else 0,
                "open": bool(entry and entry[1] > time.monotonic()),
            }

    def _sever(self, addr: str):
        """Kill the cached session to ``addr`` as if the transport failed
        (the fault plane's sever action; every in-flight stream errors)."""
        with self._lock:
            sess = self._sessions.pop(addr, None)
        if sess is not None:
            sess.inject_failure()

    def _inject(self, addr: str, method: str, duplicable: bool = True) -> bool:
        """Consult the fault plane; returns True when the call must be
        duplicated. Raises RpcError for drop/sever — which feed the
        circuit breaker like any real connection failure, so simulated
        partitions trip it exactly as a dead peer would. Seams that
        cannot honor duplication (streams) pass ``duplicable=False`` and
        duplicate rules are skipped without a false trip."""
        plane = _faults.ACTIVE
        if plane is None:
            return False
        # an open circuit short-circuits BEFORE the injected network: the
        # client never dials, so simulated faults can't fire either
        self._circuit_check(addr)
        act = plane.on_rpc(
            self.name, addr, method,
            exclude=() if duplicable else ("duplicate",),
        )
        if act == "drop":
            self._circuit_record(addr, ok=False)
            raise RpcError("connection", f"{addr}: {method}: injected drop")
        if act == "sever":
            self._sever(addr)
            self._circuit_record(addr, ok=False)
            raise RpcError("connection", f"{addr}: {method}: injected sever")
        return act == "duplicate"

    def _session(self, addr: str):
        """→ (session, cached): a cached session may have died since its
        last use; callers retry once on a fresh one when opening fails.
        The dial (and TLS handshake) happens OUTSIDE the pool lock — one
        unreachable server must not stall calls to every other address."""
        from .codec import RPC_STREAMING
        from .mux import MuxSession

        with self._lock:
            sess = self._sessions.get(addr)
            if sess is not None and not sess.dead:
                return sess, True
        host, port = addr.rsplit(":", 1)
        sock = socket.create_connection(
            (host, int(port)), timeout=self.timeout
        )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self.tls_context is not None:
            sock = self.tls_context.wrap_socket(sock)
        sock.sendall(bytes([RPC_STREAMING]))
        sess = MuxSession(sock).start()
        with self._lock:
            racer = self._sessions.get(addr)
            if racer is not None and not racer.dead:
                # another thread dialed first; one session per addr wins
                sess.close()
                return racer, True
            self._sessions[addr] = sess
            return sess, False

    def _open(self, addr: str, method: str, payload, retry_stale: bool):
        """Open a stream, retrying once on a fresh session if the cached
        one died — safe because a failed open means the request frame
        never reached the server whole. Checks the circuit breaker first
        and records connection-class outcomes into it."""
        from .mux import StreamClosed

        self._circuit_check(addr)
        try:
            sess, cached = self._session(addr)
        except OSError as e:
            self._circuit_record(addr, ok=False)
            raise RpcError("connect", f"{addr}: {e}")
        try:
            stream = sess.open(method, payload)
        except StreamClosed:
            with self._lock:
                if self._sessions.get(addr) is sess:
                    del self._sessions[addr]
            if cached and retry_stale:
                return self._open(addr, method, payload, retry_stale=False)
            self._circuit_record(addr, ok=False)
            raise RpcError("connection", f"{addr}: session closed")
        self._circuit_record(addr, ok=True)
        return stream

    @staticmethod
    def _rpc_error(err: dict) -> RpcError:
        return RpcError(
            err.get("code", "error"),
            err.get("message", ""),
            err.get("leader_rpc_addr"),
            retry_after=err.get("retry_after"),
        )

    def call(
        self,
        addr: str,
        method: str,
        payload,
        timeout: Optional[float] = None,
        retry_leader: bool = True,
        retry_stale: bool = True,
    ):
        """One RPC. On a not_leader error the call chases the leader for
        up to ``LEADER_RETRIES`` attempts with exponential backoff: a
        hinted error hops straight to the hinted address; a hint-less one
        (election in flight — the old leader just died) backs off and
        re-asks, so losing the remote leader mid-call converges on the
        re-elected leader instead of surfacing a transient error to the
        submitter. Retrying not_leader is always safe: it is an explicit
        handler answer, so the write was refused, not applied. A dead
        cached session retries once on a fresh one — but ONLY when the
        open failed to send, so the server cannot have executed the call.
        Failures after the request was flushed — including a timeout,
        where the handler may still be running — are never retried:
        re-sending would duplicate a non-idempotent write."""
        from ..trace import tracer
        from ..core import overload as _overload

        ctx = tracer.current()
        if (
            ctx is not None
            and ctx.sampled
            and isinstance(payload, dict)
            and "_trace" not in payload
        ):
            # trace-context propagation: the handler side re-activates
            # this so server-side spans parent under the caller's span.
            # Copied, never mutated in place — the caller may retry the
            # same payload object through another pool
            payload = {**payload, "_trace": ctx.to_dict()}
        deadline_ns = _overload.current_deadline()
        if (
            deadline_ns
            and isinstance(payload, dict)
            and "_deadline" not in payload
        ):
            # deadline propagation (the _trace pattern, core/overload.py):
            # the handler side re-activates it so the server — and any
            # eval/plan minted there — inherits the caller's deadline
            payload = {**payload, "_deadline": deadline_ns}
        with tracer.span(f"rpc.{method}", tags={"addr": addr}):
            return self._call_inner(
                addr, method, payload, timeout, retry_leader, retry_stale
            )

    def _call_inner(
        self, addr, method, payload, timeout, retry_leader, retry_stale
    ):
        from ..core.overload import retry_budget

        attempts = self.LEADER_RETRIES if retry_leader else 1
        origin = addr
        last_err = None
        for attempt in range(attempts):
            if attempt:
                # every RETRY (not the first attempt) spends a token from
                # the process-wide retry budget: when many ladders chase
                # a dead leader at once, the budget — not the product of
                # their individual limits — bounds total retry volume
                # (core/overload.py, the metastable-retry-storm guard)
                if not retry_budget().try_acquire():
                    metrics.incr("rpc.retry_budget_exhausted")
                    raise last_err
                # backoff before the next hop: a hint that points at a
                # just-severed peer (or a hint-less mid-election answer)
                # otherwise hot-loops through the circuit breaker
                time.sleep(
                    min(
                        self.LEADER_BACKOFF_BASE * (2 ** (attempt - 1)),
                        self.LEADER_BACKOFF_MAX,
                    )
                )
            try:
                return self._call_once(addr, method, payload, timeout,
                                       retry_stale)
            except RpcError as err:
                if attempts == 1:
                    raise
                if err.code == "not_leader":
                    last_err = err
                    metrics.incr("rpc.not_leader_retry")
                    if err.leader_rpc_addr:
                        addr = err.leader_rpc_addr
                    # no hint: election in flight — re-ask the same
                    # address, which answers with the new leader once a
                    # quorum knows it
                    continue
                if addr != origin and err.code in ("connect", "circuit_open"):
                    # the HINTED leader is unreachable — likely the very
                    # server whose death caused the election. Both codes
                    # are raised strictly before the request is sent, so
                    # falling back to the origin cannot double-apply
                    last_err = err
                    metrics.incr("rpc.leader_hop_unreachable")
                    addr = origin
                    continue
                raise
        raise last_err

    def _call_once(self, addr, method, payload, timeout, retry_stale):
        from .mux import StreamClosed, StreamError

        duplicate = self._inject(addr, method)
        stream = self._open(addr, method, payload, retry_stale)
        try:
            result = stream.recv(timeout=timeout or self.timeout)
            stream.close()
            if duplicate:
                # fault plane: deliver the request a second time (at-least-
                # once transport semantics); the duplicate's outcome is
                # discarded like a lost response would be
                try:
                    dup = self._open(addr, method, payload, retry_stale=False)
                    dup.recv(timeout=timeout or self.timeout)
                    dup.close()
                except (RpcError, StreamError, StreamClosed, TimeoutError):
                    pass
            return result
        except StreamError as e:
            stream.close()
            raise self._rpc_error(e.error)
        except TimeoutError:
            stream.close()
            raise RpcError("timeout", f"{addr}: {method}: timed out")
        except StreamClosed:
            stream.close()  # release the local stream record
            self._circuit_record(addr, ok=False)
            raise RpcError("connection", f"{addr}: stream closed")

    def call_stream(self, addr: str, method: str, payload,
                    timeout: Optional[float] = None):
        """Streaming RPC: yields chunk frames until end of stream. Rides
        the shared session — other calls proceed concurrently."""
        from .mux import StreamClosed, StreamError

        self._inject(addr, method, duplicable=False)
        stream = self._open(addr, method, payload, retry_stale=True)
        try:
            while True:
                try:
                    yield stream.recv(timeout=timeout or self.timeout)
                except StreamClosed:
                    return
                except StreamError as e:
                    raise self._rpc_error(e.error)
                except TimeoutError:
                    raise RpcError("timeout", f"{addr}: {method}: timed out")
        finally:
            stream.close()

    def call_duplex(self, addr: str, method: str, payload):
        """Open a BIDIRECTIONAL stream (the exec path): returns the live
        mux Stream; the caller drives send()/recv()/close()."""
        self._inject(addr, method, duplicable=False)
        return self._open(addr, method, payload, retry_stale=True)

    def close(self):
        with self._lock:
            for sess in self._sessions.values():
                sess.close()
            self._sessions.clear()


class ServerProxy:
    """RPC-backed stand-in for core.Server: the node agent's view of the
    cluster (ref client/rpc.go + client/servers/ server manager).

    Maintains a server list; each call tries the current server and
    rotates on connection failure (ref client/servers/manager.go)."""

    def __init__(self, servers: list[str], pool: Optional[ConnPool] = None,
                 max_retries: int = 3):
        if not servers:
            raise ValueError("at least one server address required")
        self.servers = list(servers)
        self.pool = pool or ConnPool()
        self.max_retries = max_retries
        self._lock = threading.Lock()
        self._current = 0

    def set_servers(self, servers: list[str]):
        with self._lock:
            self.servers = list(servers)
            self._current = 0

    #: rotation backoff: base * 2^attempt, capped (manager.go's failure
    #: backoff; nonzero from the FIRST failure so a severed cluster is
    #: polled, not hammered)
    RETRY_BACKOFF_BASE = 0.05
    RETRY_BACKOFF_MAX = 1.0

    def _call(self, method: str, payload, timeout: Optional[float] = None):
        from ..core.overload import retry_budget

        last_err = None
        for attempt in range(self.max_retries):
            with self._lock:
                addr = self.servers[self._current % len(self.servers)]
            try:
                return self.pool.call(addr, method, payload, timeout=timeout)
            except RpcError as e:
                if e.code in (
                    "connect", "connection", "not_leader", "circuit_open"
                ):
                    # rotate to the next server (manager.go
                    # NotifyFailedServer); a circuit_open peer costs no
                    # dial, so the sleep is what paces the loop
                    with self._lock:
                        self._current += 1
                    last_err = e
                    if attempt + 1 < self.max_retries:
                        # a rotation retry rides the process-wide retry
                        # budget too (core/overload.py): fail fast with
                        # the last error once the bucket is dry
                        if not retry_budget().try_acquire():
                            metrics.incr("rpc.retry_budget_exhausted")
                            raise last_err
                        time.sleep(
                            min(
                                self.RETRY_BACKOFF_BASE * (2 ** attempt),
                                self.RETRY_BACKOFF_MAX,
                            )
                        )
                    continue
                raise
        raise last_err

    # ------------------------------------------------------------------
    # the node-agent surface (mirrors core.Server methods)
    # ------------------------------------------------------------------
    def node_register(self, node) -> dict:
        return self._call("Node.Register", {"node": node.to_dict()})

    def derive_vault_token(self, alloc_id: str, task: str) -> str:
        """ref node_endpoint.go DeriveVaultToken (client→server RPC)."""
        return self._call(
            "Node.DeriveVaultToken", {"alloc_id": alloc_id, "task": task}
        )

    def node_heartbeat(self, node_id: str) -> dict:
        return self._call("Node.UpdateStatus", {"node_id": node_id, "heartbeat": True})

    def node_update_status(self, node_id: str, status: str) -> dict:
        return self._call(
            "Node.UpdateStatus", {"node_id": node_id, "status": status}
        )

    def node_drain(
        self, node_id: str, drain: bool, deadline_ns: int = 0,
        mark_eligible: bool | None = None,
    ) -> dict:
        return self._call(
            "Node.Drain",
            {
                "node_id": node_id,
                "drain": drain,
                "deadline_ns": deadline_ns,
                "mark_eligible": mark_eligible,
            },
        )

    def node_update_eligibility(self, node_id: str, eligibility: str) -> dict:
        return self._call(
            "Node.Eligibility",
            {"node_id": node_id, "eligibility": eligibility},
        )

    def get_client_allocs(self, node_id: str, min_index: int = 0, timeout: float = 30.0):
        resp = self._call(
            "Node.GetClientAllocs",
            {"node_id": node_id, "min_index": min_index, "timeout": timeout},
            timeout=timeout + 10.0,
        )
        from ..structs.model import Allocation

        return (
            [Allocation.from_dict(d) for d in resp["allocs"]],
            resp["index"],
        )

    def update_allocs(self, allocs) -> None:
        self._call(
            "Node.UpdateAlloc", {"allocs": [a.to_dict() for a in allocs]}
        )

    def alloc_get(self, alloc_id: str):
        return self._call("Alloc.GetAlloc", {"alloc_id": alloc_id})["alloc"]

    def catalog_service(self, name: str) -> list[dict]:
        return self._call("Catalog.Service", {"name": name})["entries"]

    def forward_client_fs(self, alloc_id: str, method: str, params: dict):
        return self._call(
            "ClientFS.Forward",
            {"alloc_id": alloc_id, "method": method, "params": params},
            timeout=45.0,
        )

    # job/eval/etc. surface used by the HTTP API & CLI when remote
    def job_register(self, job) -> str:
        return self._call("Job.Register", {"job": job.to_dict()})

    def job_deregister(self, namespace: str, job_id: str, purge: bool = False) -> str:
        return self._call(
            "Job.Deregister",
            {"namespace": namespace, "job_id": job_id, "purge": purge},
        )
