"""Unbounded-cache checker: the static encoding of the ``_bad_http_addrs``
leak class (r5) and its churn-soak relatives (BlockedEvals'
``_node_unblock_indexes``, PeriodicDispatch's ``_gen``).

The shape: a long-lived dict/list/set — an instance attribute created in
``__init__`` or a module-level global — that some steady-state code path
*grows* (keyed insert, ``append``, ``add``, ``setdefault``) while **no**
path ever shrinks it (``pop``/``del``/``clear``/``remove``/rebind). On a
server that lives for months, every such container is a leak whose key
cardinality is only bounded by traffic: per-address maps, per-node-id
maps, per-job generation counters.

Rule ``unbounded-cache`` flags the *container*, at its creation site,
listing where it grows. Bounded-by-construction registries (one entry
per checker module, per RPC method, per scheduler factory — populated at
import/startup and never from request traffic) are the expected
suppression class: mark them ``# nta: ignore[unbounded-cache]`` with a
WHY.

Heuristics (kept conservative on the shrink side — ANY shrink/rebind
anywhere in the owning scope clears the container, since this checker
cannot prove the path is reachable):

- growth must happen inside a function/method other than the creating
  ``__init__`` (top-level one-shot registration isn't steady-state);
- instance attrs are tracked per class; ``self.X`` rebinds anywhere in
  the class count as shrink. Module globals are tracked per module;
- aliasing (``y = self.X`` then mutations through ``y``) is resolved one
  hop inside the same function body.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .framework import Finding, Project, register

#: planes whose objects are scoped to one evaluation/run by construction
#: (scheduler iterator stacks, struct scratch builders, the one-shot
#: analysis CLI, the loadgen client whose accumulators ARE the run's
#: measurement): a container there dies with its short-lived owner
_EXEMPT_PREFIXES = (
    "nomad_tpu/scheduler/",
    "nomad_tpu/structs/",
    "nomad_tpu/analysis/",
    "nomad_tpu/loadgen/",
)

#: functions whose growth is startup/import-time registration, not
#: steady-state traffic (route tables, endpoint registries, thread
#: launch lists): growth seen ONLY here doesn't flag
_STARTUP_FN_RE = re.compile(
    r"^(start|setup|_setup\w*|register\w*|route|deco|install\w*)$"
)

#: call attrs that grow a container
_GROW_METHODS = {
    "append", "add", "setdefault", "extend", "insert", "update",
    "appendleft", "push",
}
#: call attrs that shrink (or can shrink) a container
_SHRINK_METHODS = {
    "pop", "popitem", "clear", "remove", "discard", "popleft",
}
#: constructor calls that create an empty growable container
_CONTAINER_CALLS = {"dict", "set", "list", "defaultdict", "OrderedDict", "deque"}


def _is_container_ctor(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set)):
        # literal {} / [] — non-empty literals are config tables, not caches
        return not getattr(node, "keys", None) and not getattr(node, "elts", None)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id == "deque":
            # deque with a REAL maxlen is bounded by construction — the
            # ring idiom this checker must not cry wolf on. An explicit
            # maxlen=None is a bare unbounded deque and still flags.
            def _bound(arg):
                return not (
                    isinstance(arg, ast.Constant) and arg.value is None
                )

            for kw in node.keywords:
                if kw.arg == "maxlen":
                    return not _bound(kw.value)
            if len(node.args) == 2:  # deque(iterable, maxlen)
                return not _bound(node.args[1])
        return node.func.id in _CONTAINER_CALLS
    return False


class _Access:
    """One observed use of a tracked container: grow, shrink, or rebind."""

    __slots__ = ("kind", "line", "how")

    def __init__(self, kind: str, line: int, how: str):
        self.kind = kind
        self.line = line
        self.how = how


def _attr_of_self(node: ast.AST) -> Optional[str]:
    """'x' for a ``self.x`` expression."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _scan_function(fn: ast.AST, names: set, is_attr: bool, out: dict):
    """Collect accesses to tracked containers inside one function body.

    ``names`` are attr names (for ``self.X``) or global names; accesses
    land in ``out[name] -> list[_Access]``. One level of aliasing inside
    the function (``alias = self.X``) is followed.
    """
    aliases: dict[str, str] = {}

    # module-global mode: a plain ``NAME = ...`` without a ``global NAME``
    # declaration makes NAME function-LOCAL for the whole scope (Python
    # scoping), so every access to it in this function touches the local
    # shadow, not the tracked global — misreading the shadow as a
    # rebind/shrink of the global silences the rule for exactly the leak
    # class it exists to catch
    shadowed: set = set()
    if not is_attr:
        declared_global: set = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                declared_global.update(node.names)
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                tgts = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in tgts:
                    if (
                        isinstance(t, ast.Name)
                        and t.id in names
                        and t.id not in declared_global
                    ):
                        shadowed.add(t.id)

    def target_name(expr: ast.AST) -> Optional[str]:
        if is_attr:
            name = _attr_of_self(expr)
            if name in names:
                return name
            if isinstance(expr, ast.Name) and expr.id in aliases:
                return aliases[expr.id]
            return None
        if (
            isinstance(expr, ast.Name)
            and expr.id in names
            and expr.id not in shadowed
        ):
            return expr.id
        return None

    fname = getattr(fn, "name", "<fn>")
    in_init = fname == "__init__"
    # pre-pass: register aliases (``m = self.X``) before the access walk,
    # so walk order can't matter and the alias assignment itself isn't
    # misread as a rebind of the container
    alias_nodes: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, val = node.targets[0], node.value
            src = None
            if is_attr:
                src = _attr_of_self(val)
            elif isinstance(val, ast.Name) and val.id in names:
                src = val.id
            if (
                src in names
                and isinstance(tgt, ast.Name)
                and not isinstance(val, ast.Call)
            ):
                aliases[tgt.id] = src
                alias_nodes.add(id(node))
    for node in ast.walk(fn):
        if id(node) in alias_nodes:
            continue
        # rebind: self.X = <anything> outside the creating __init__
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target] if node.value is not None else []
            else:
                targets = [node.target]
            for tgt in targets:
                name = target_name(tgt)
                if name is not None and not isinstance(tgt, ast.Subscript):
                    if isinstance(node, ast.AugAssign):
                        # ``x += [e]`` / ``m |= d`` accumulate INTO the
                        # container — growth, not a rebind. Only the
                        # subtractive ops shrink (``s -= other``,
                        # ``s &= other``); anything else counts as grow
                        # so a leak can't hide behind an odd operator
                        if isinstance(node.op, (ast.Sub, ast.BitAnd)):
                            out.setdefault(name, []).append(
                                _Access("shrink", node.lineno, "augassign")
                            )
                        elif not in_init:
                            out.setdefault(name, []).append(
                                _Access(
                                    "grow", node.lineno, f"{fname}: augassign"
                                )
                            )
                    elif not in_init:
                        out.setdefault(name, []).append(
                            _Access("shrink", node.lineno, "rebind")
                        )
                    continue
                # keyed insert: self.X[k] = v  (AugAssign on a key is
                # accumulation into an existing slot, not new growth)
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(tgt, ast.Subscript)
                ):
                    name = target_name(tgt.value)
                    if name is not None and not in_init:
                        out.setdefault(name, []).append(
                            _Access("grow", node.lineno, f"{fname}: [k] =")
                        )
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                base = tgt.value if isinstance(tgt, ast.Subscript) else tgt
                name = target_name(base)
                if name is not None:
                    out.setdefault(name, []).append(
                        _Access("shrink", node.lineno, "del")
                    )
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            name = target_name(node.func.value)
            if name is None:
                continue
            meth = node.func.attr
            if meth in _GROW_METHODS and not in_init:
                out.setdefault(name, []).append(
                    _Access("grow", node.lineno, f"{fname}: .{meth}()")
                )
            elif meth in _SHRINK_METHODS:
                out.setdefault(name, []).append(
                    _Access("shrink", node.lineno, f".{meth}()")
                )


def _check_class(mod, cls: ast.ClassDef) -> list[Finding]:
    # containers created in __init__ as self.X = {} / [] / set() / ...
    created: dict[str, int] = {}
    for stmt in cls.body:
        if not (
            isinstance(stmt, ast.FunctionDef) and stmt.name == "__init__"
        ):
            continue
        for node in ast.walk(stmt):
            tgt = val = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt, val = node.target, node.value
            if tgt is not None:
                name = _attr_of_self(tgt)
                if name is not None and _is_container_ctor(val):
                    created[name] = node.lineno
    if not created:
        return []
    accesses: dict[str, list[_Access]] = {}
    for stmt in ast.walk(cls):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_function(stmt, set(created), True, accesses)
    return _emit(mod, cls.name, created, accesses)


def _check_module_globals(mod) -> list[Finding]:
    created: dict[str, int] = {}
    for stmt in mod.tree.body:
        tgt = val = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt, val = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            tgt, val = stmt.target, stmt.value
        if (
            tgt is not None
            and isinstance(tgt, ast.Name)
            and _is_container_ctor(val)
        ):
            created[tgt.id] = stmt.lineno
    if not created:
        return []
    accesses: dict[str, list[_Access]] = {}
    for stmt in ast.walk(mod.tree):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_function(stmt, set(created), False, accesses)
    return _emit(mod, None, created, accesses)


def _emit(mod, cls_name, created, accesses) -> list[Finding]:
    findings = []
    for name, line in sorted(created.items()):
        acc = accesses.get(name, [])
        grows = [
            a
            for a in acc
            if a.kind == "grow"
            and not _STARTUP_FN_RE.match(a.how.split(":", 1)[0])
        ]
        shrinks = [a for a in acc if a.kind == "shrink"]
        if not grows or shrinks:
            continue
        owner = f"{cls_name}.{name}" if cls_name else name
        hows = sorted({a.how for a in grows})
        findings.append(
            Finding(
                "unbounded-cache", mod.relpath, line,
                f"{owner} only ever grows ({'; '.join(hows[:4])}) — no "
                "eviction/pop/clear/rebind on any path; bound it or "
                "suppress with a WHY if key cardinality is fixed",
            )
        )
    return findings


@register(
    "unbounded-cache",
    "long-lived dict/list/set grown on steady-state paths with no "
    "eviction anywhere (the _bad_http_addrs leak class)",
)
def check_unbounded_cache(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for mod in project.modules:
        if any(mod.relpath.startswith(p) for p in _EXEMPT_PREFIXES):
            continue
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                findings.extend(_check_class(mod, node))
        findings.extend(_check_module_globals(mod))
    return findings
