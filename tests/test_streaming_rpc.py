"""Streaming RPC (ref structs/streaming_rpc.go): multi-frame responses on
the RPC tier, exercised by the client agent's log-follow stream."""

import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.agent import ClientAgent, ServerAgent
from nomad_tpu.rpc import ConnPool, RpcServer
from nomad_tpu.rpc.client import RpcError


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class TestStreamFraming:
    def test_chunks_then_eof(self):
        server = RpcServer("127.0.0.1", 0)

        def counter(payload):
            for i in range(int(payload["n"])):
                yield {"i": i}

        server.register_stream("Test.Count", counter)
        server.register("Test.Plain", lambda p: {"ok": True})
        server.start()
        pool = ConnPool()
        try:
            chunks = list(pool.call_stream(server.address, "Test.Count", {"n": 5}))
            assert [c["i"] for c in chunks] == [0, 1, 2, 3, 4]
            # the connection returns to the pool and serves plain calls
            assert pool.call(server.address, "Test.Plain", {})["ok"] is True
            # a second stream on the same (pooled) connection
            chunks = list(pool.call_stream(server.address, "Test.Count", {"n": 2}))
            assert len(chunks) == 2
        finally:
            pool.close()
            server.stop()

    def test_stream_handler_error_frames(self):
        server = RpcServer("127.0.0.1", 0)

        def boom(payload):
            raise ValueError("bad stream request")
            yield  # pragma: no cover

        server.register_stream("Test.Boom", boom)
        server.start()
        pool = ConnPool()
        try:
            with pytest.raises(RpcError) as err:
                list(pool.call_stream(server.address, "Test.Boom", {}))
            assert err.value.code == "invalid"
        finally:
            pool.close()
            server.stop()


class TestLogFollowStream:
    def test_follow_pushes_growing_logs(self):
        """A task that writes continuously streams its log growth as push
        frames over the client's RPC listener."""
        server = ServerAgent("ls1", config={"seed": 157, "heartbeat_ttl": 5.0})
        server.start(num_workers=2)
        node_agent = ClientAgent([server.address])
        pool = ConnPool()
        try:
            node_agent.start()
            wait_until(
                lambda: server.server.state.node_by_id(node_agent.node.id)
                is not None,
                msg="node registered",
            )
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": [
                    "-c",
                    "i=0; while true; do echo line-$i; i=$((i+1)); sleep 0.1; done",
                ],
            }
            task.resources.networks = []
            server.server.job_register(job)
            wait_until(
                lambda: any(
                    a.client_status == "running"
                    for a in server.server.state.allocs_by_job(
                        job.namespace, job.id
                    )
                ),
                msg="writer running",
            )
            (alloc,) = server.server.state.allocs_by_job(job.namespace, job.id)
            node = server.server.state.node_by_id(alloc.node_id)
            addr = node.attributes["unique.advertise.client_rpc"]

            collected = ""
            frames = 0
            for chunk in pool.call_stream(
                addr,
                "ClientFS.LogsFollow",
                {
                    "alloc_id": alloc.id,
                    "secret": node.secret_id,
                    "task": "web",
                    "type": "stdout",
                    "duration": 2.0,
                },
                timeout=10.0,
            ):
                collected += chunk["Data"]
                frames += 1
            assert frames >= 2, "follow must push multiple frames"
            assert "line-0" in collected
            # growth across frames: later lines arrived in later frames
            assert "line-5" in collected
        finally:
            pool.close()
            node_agent.stop()
            server.stop()
