"""Watchdog: cheap rules over the flight recorder, auto-capturing a
debug bundle when one trips.

Post-incident debugging starts with "what did it look like right
before" — which is exactly what nobody captured. The watchdog closes
that loop: every flight-recorder sample is evaluated against a handful
of O(window) rules, and the first breach (per rule, per cooldown)
snapshots a full debug bundle (bundle.py) while the incident is STILL
HAPPENING — profiles included, so the stuck thread's stack is in the
artifact, not reconstructed from folklore.

Rules (thresholds config-overridable via the ``debug.watchdog`` stanza):

- ``plan_queue_wait_p99`` — the applier saturation signal (ROADMAP
  item 1): p99 above threshold for N consecutive samples. Retuned for
  the pipelined applier: the pre-pipeline 2000ms default tolerated the
  serialized applier's normal convoying; with overlapped commits the
  bench target is p99 <50ms, so 500ms (10x the target) is a real
  anomaly, not noise. Kept (not retired): the rule still fires exactly
  when the pipeline saturates — overlay at depth, every worker parked
  in plan.submit — which is the bundle an operator wants;
- ``stalled_worker`` — ready evals with zero in-flight work and a flat
  evals-processed counter across N samples: the workers stopped
  consuming (the synthetic-refresh-index bug class, PR 3);
- ``rss_slope`` — sustained least-squares RSS growth over the tail
  window (the ``_bad_http_addrs`` leak class, caught while leaking);
- ``lock_contention`` — lock-wait seconds accumulating faster than
  ``threshold`` per wall second across the window (lockdep installs
  only; a convoy collapse, not a single slow acquire);
- ``subscriber_lag`` — max event-stream subscriber lag (broker head
  index minus the subscriber's last drained index) above threshold for
  N consecutive samples while subscribers exist: fan-out overload
  becomes a debug bundle — whose findings carry the per-subscriber lag
  top-N and broker ring stats — not a pager;
- ``acl_replication_lag`` — seconds since this (non-authoritative,
  replicating) region last successfully mirrored the authoritative
  region's ACL state, above threshold for N consecutive samples: a
  severed WAN or dead authoritative leader becomes a bundle whose
  findings carry the per-region replication/forwarding stats. The rule
  keys off ``acl_replication_lag_s``, which only replicating servers
  emit — single-region clusters never see it;
- ``recompile_storm`` — planner compile-cache growth of ≥ ``growth``
  entries across the flight tail while the server is PAST its warmup
  (evals already processed before the window opened — the prewarm
  ladder's legitimate boot-time compiles never trip it): the
  51200-vs-50176 shape-drift class silently re-paying XLA compiles in
  steady state becomes a bundle whose device section names the shapes;
- ``h2d_thrash`` — paged-planner tile RE-upload bytes per committed
  placement sustained above ``bytes_per_placement`` across the window
  (plus an absolute ``min_reupload_mb`` floor): the device node budget
  is too tight for the working set and tiles are being evicted and
  re-streamed wholesale instead of staying resident. Keys ride the
  devprof transfer ledger, so servers that never page stay at 0;
- ``overload`` — sustained admission shedding above ``shed_per_s``
  across the window, or any brownout level above ``brownout_level``:
  the bundle captures the admission/brownout/retry-budget state while
  the storm is still in progress. Keys exist only on servers with an
  ``overload{}`` stanza, so unconfigured agents never trip it.

Trips are always recorded + counted (``debug.watchdog_trips``); the
bundle write additionally needs a configured ``bundle_dir`` so a
default agent never surprises an operator with disk writes.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from .flight import rss_slope

logger = logging.getLogger("nomad_tpu.debug.watchdog")

#: rule name -> default parameters (override via debug.watchdog.<rule>)
DEFAULT_RULES = {
    "plan_queue_wait_p99": {"threshold_ms": 500.0, "consecutive": 3},
    "stalled_worker": {"consecutive": 8},
    "rss_slope": {
        "threshold_mb_per_min": 512.0,
        "window": 120,
        "min_span_s": 60.0,
    },
    "lock_contention": {"threshold_frac": 0.5, "window": 30,
                        "min_span_s": 5.0},
    "subscriber_lag": {"threshold": 10_000, "consecutive": 5},
    "acl_replication_lag": {"threshold_s": 30.0, "consecutive": 3},
    "recompile_storm": {"growth": 4, "window": 60, "min_span_s": 10.0},
    "h2d_thrash": {
        "bytes_per_placement": 1_000_000.0,
        "min_reupload_mb": 16.0,
        "window": 60,
        "min_span_s": 10.0,
    },
    "plane_divergence": {"threshold": 1},
    "overload": {"shed_per_s": 50.0, "consecutive": 5, "brownout_level": 0},
}

MAX_TRIP_LOG = 64


class Watchdog:
    """Evaluates rules on every flight-recorder sample (installed as the
    recorder's ``observer``); thread-safe, never raises into the
    recorder."""

    def __init__(self, server, recorder, config=None, bundle_dir: str = "",
                 cooldown_s: float = 60.0, profile_seconds: float = 1.0):
        self.server = server
        self.recorder = recorder
        config = dict(config or {})
        self.rules: dict[str, dict] = {}
        for name, defaults in DEFAULT_RULES.items():
            override = config.get(name)
            if override is False:
                continue  # rule disabled
            merged = dict(defaults)
            if isinstance(override, dict):
                merged.update(override)
            self.rules[name] = merged
        self.bundle_dir = bundle_dir or str(config.get("bundle_dir") or "")
        #: newest on-disk auto-captured bundles kept; older watchdog-*
        #: dirs are pruned after each capture (the in-memory trip log is
        #: capped — the disk must be too, or a recurring trip fills it)
        self.bundle_keep = int(config.get("bundle_keep", 8))
        self.cooldown_s = float(config.get("cooldown_s", cooldown_s))
        self.profile_seconds = float(
            config.get("profile_seconds", profile_seconds)
        )
        self._lock = threading.Lock()
        # nta: ignore[unbounded-cache] WHY: keyed by rule name — the
        # code-fixed DEFAULT_RULES vocabulary
        self._last_trip: dict[str, float] = {}
        self.trip_log: list[dict] = []
        self.trip_count = 0
        self.bundles: list[str] = []
        self._capturing = False
        self._bundle_seq = 0

    # ------------------------------------------------------------------
    def on_sample(self, sample: dict):
        window = self.recorder.samples(
            last=max(
                r.get("window", r.get("consecutive", 1))
                for r in self.rules.values()
            )
            if self.rules
            else 1
        )
        if not window:
            return
        for name, params in self.rules.items():
            try:
                detail = getattr(self, f"_rule_{name}")(sample, window, params)
            except Exception:
                logger.exception("watchdog rule %s failed", name)
                continue
            if detail is not None:
                self._trip(name, detail, sample)

    # -- rules ----------------------------------------------------------
    def _rule_plan_queue_wait_p99(self, sample, window, p):
        tail = window[-int(p["consecutive"]):]
        if len(tail) < int(p["consecutive"]):
            return None
        # activity gate: the timer window never decays while idle, so a
        # historical spike would re-breach every cooldown forever. A
        # breach only counts while the plan plane is live — plans
        # queued now, or evals completing across the window (a stuck
        # applier with a flat counter is stalled_worker's rule)
        active = tail[-1].get("plan_queue_depth", 0) > 0 or (
            tail[-1].get("evals_processed", 0)
            > tail[0].get("evals_processed", 0)
        )
        if active and all(
            s.get("plan_queue_wait_p99_ms", 0.0) > p["threshold_ms"]
            for s in tail
        ):
            return {
                "p99_ms": sample.get("plan_queue_wait_p99_ms"),
                "threshold_ms": p["threshold_ms"],
            }
        return None

    def _rule_stalled_worker(self, sample, window, p):
        tail = window[-int(p["consecutive"]):]
        if len(tail) < int(p["consecutive"]):
            return None
        if all(
            s.get("broker_ready", 0) > 0 and s.get("broker_unacked", 0) == 0
            for s in tail
        ) and tail[-1].get("evals_processed", 0) == tail[0].get(
            "evals_processed", 0
        ):
            return {
                "broker_ready": sample.get("broker_ready"),
                "flat_for_samples": len(tail),
            }
        return None

    def _rule_subscriber_lag(self, sample, window, p):
        tail = window[-int(p["consecutive"]):]
        if len(tail) < int(p["consecutive"]):
            return None
        # the lag tap reads live subscribers only, so a breach can't
        # outlive its cause: a drained (or closed) consumer resets the
        # streak by construction — no idle-decay gate needed
        if all(
            s.get("subscribers", 0) > 0
            and s.get("subscriber_lag_max", 0) > p["threshold"]
            for s in tail
        ):
            return {
                "lag_max": sample.get("subscriber_lag_max"),
                "lag_p99": sample.get("subscriber_lag_p99"),
                "threshold": p["threshold"],
                "subscribers": sample.get("subscribers"),
            }
        return None

    def _rule_acl_replication_lag(self, sample, window, p):
        tail = window[-int(p["consecutive"]):]
        if len(tail) < int(p["consecutive"]):
            return None
        # the key exists only on replicating servers, so the rule is
        # structurally silent everywhere else; a successful round resets
        # the lag (and the streak) by construction
        if all(
            s.get("acl_replication_lag_s") is not None
            and s["acl_replication_lag_s"] > p["threshold_s"]
            for s in tail
        ):
            return {
                "lag_s": sample.get("acl_replication_lag_s"),
                "threshold_s": p["threshold_s"],
                "failures": sample.get("acl_replication_failures"),
                "region": sample.get("region"),
            }
        return None

    def _rule_recompile_storm(self, sample, window, p):
        tail = window[-int(p["window"]):]
        if (
            len(tail) < 2
            or tail[-1]["t"] - tail[0]["t"] < p["min_span_s"]
            or "compile_cache_size" not in tail[-1]
            or "compile_cache_size" not in tail[0]
        ):
            return None
        # warmup gate: the prewarm ladder legitimately compiles a burst
        # of programs at boot — growth only counts once the server had
        # ALREADY processed evals before this window opened (a storm in
        # steady state is drift, the same signal the trace plane's
        # [recompile]-flagged spans carry per-dispatch)
        if tail[0].get("evals_processed", 0) <= 0:
            return None
        growth = (
            tail[-1]["compile_cache_size"] - tail[0]["compile_cache_size"]
        )
        if growth >= p["growth"]:
            return {
                "cache_growth": growth,
                "cache_size": sample.get("compile_cache_size"),
                "threshold": p["growth"],
                "span_s": round(tail[-1]["t"] - tail[0]["t"], 2),
            }
        return None

    def _rule_h2d_thrash(self, sample, window, p):
        # paged node axis (tpu/paging.py): a healthy pager re-uploads a
        # tile's small dynamic planes when a commit dirtied it — thrash
        # is when the device budget is so tight relative to the working
        # set that tiles keep getting EVICTED and re-admitted wholesale,
        # and the signature is re-upload bytes growing far faster than
        # committed placements. The absolute-bytes floor keeps an idle
        # server (zero placements, one dirty refresh) from tripping.
        tail = window[-int(p["window"]):]
        if (
            len(tail) < 2
            or tail[-1]["t"] - tail[0]["t"] < p["min_span_s"]
            or "paged_tile_reupload_bytes" not in tail[-1]
            or "paged_tile_reupload_bytes" not in tail[0]
        ):
            return None
        re_bytes = (
            tail[-1]["paged_tile_reupload_bytes"]
            - tail[0]["paged_tile_reupload_bytes"]
        )
        if re_bytes < float(p["min_reupload_mb"]) * 1e6:
            return None
        placed = (
            tail[-1].get("placements_total", 0)
            - tail[0].get("placements_total", 0)
        )
        per = re_bytes / max(placed, 1)
        if per > float(p["bytes_per_placement"]):
            return {
                "reupload_bytes": re_bytes,
                "placements": placed,
                "bytes_per_placement": round(per, 1),
                "threshold": p["bytes_per_placement"],
                "reuploads_total": sample.get("paged_tile_reuploads"),
                "span_s": round(tail[-1]["t"] - tail[0]["t"], 2),
            }
        return None

    def _rule_plane_divergence(self, sample, window, p):
        # divergence between the committed planes and a cold rebuild of
        # the MVCC tables is impossible by construction (the same write
        # transaction patches both) — which is exactly why it is audited:
        # a single nonzero row means a write path bypassed the commit
        # protocol, and that warrants a bundle immediately, no
        # consecutive-sample streak required
        rows = sample.get("plane_divergence_rows", 0)
        recs = sample.get("plane_divergence_recs", 0)
        if rows >= p["threshold"] or recs >= p["threshold"]:
            return {
                "rows": rows,
                "recs": recs,
                "planes_version": sample.get("plane_audit_version"),
            }
        return None

    def _rule_overload(self, sample, window, p):
        # sustained shedding — or any brownout past the configured floor
        # — is an incident whose evidence (admission state, brownout
        # level, retry-budget depth) is exactly what vanishes once the
        # storm passes; the bundle captures it while it is happening.
        # Keys exist only when the overload{} stanza built a controller,
        # so unconfigured servers never evaluate past the gate.
        tail = window[-int(p["consecutive"]):]
        if (
            len(tail) < int(p["consecutive"])
            or "overload_shed_total" not in tail[-1]
            or "overload_shed_total" not in tail[0]
        ):
            return None
        level = sample.get("brownout_level", 0)
        if level > int(p["brownout_level"]):
            return {
                "brownout_level": level,
                "overload_load": sample.get("overload_load"),
                "shed_total": sample.get("overload_shed_total"),
                "dl_exceeded_total": sample.get("overload_dl_exceeded_total"),
            }
        span = tail[-1]["t"] - tail[0]["t"]
        if span <= 0:
            return None
        shed_rate = (
            tail[-1]["overload_shed_total"] - tail[0]["overload_shed_total"]
        ) / span
        if shed_rate > float(p["shed_per_s"]):
            return {
                "shed_per_s": round(shed_rate, 1),
                "threshold_per_s": p["shed_per_s"],
                "overload_load": sample.get("overload_load"),
                "shed_total": sample.get("overload_shed_total"),
                "dl_exceeded_total": sample.get("overload_dl_exceeded_total"),
            }
        return None

    def _rule_rss_slope(self, sample, window, p):
        tail = window[-int(p["window"]):]
        if (
            len(tail) < 2
            or tail[-1]["t"] - tail[0]["t"] < p["min_span_s"]
        ):
            return None
        slope = rss_slope(tail)
        if slope > p["threshold_mb_per_min"]:
            return {
                "slope_mb_per_min": round(slope, 2),
                "threshold_mb_per_min": p["threshold_mb_per_min"],
                "rss_mb": sample.get("rss_mb"),
            }
        return None

    def _rule_lock_contention(self, sample, window, p):
        tail = window[-int(p["window"]):]
        if (
            len(tail) < 2
            or "lock_wait_s" not in tail[-1]
            or "lock_wait_s" not in tail[0]
            or tail[-1]["t"] - tail[0]["t"] < p["min_span_s"]
        ):
            return None
        span = tail[-1]["t"] - tail[0]["t"]
        frac = (tail[-1]["lock_wait_s"] - tail[0]["lock_wait_s"]) / span
        if frac > p["threshold_frac"]:
            return {
                "lock_wait_frac": round(frac, 3),
                "threshold_frac": p["threshold_frac"],
            }
        return None

    # -- trip handling --------------------------------------------------
    def _trip(self, rule: str, detail: dict, sample: dict):
        from .. import metrics

        now = time.monotonic()
        with self._lock:
            last = self._last_trip.get(rule, 0.0)
            if last and now - last < self.cooldown_s:
                return
            self._last_trip[rule] = now
            self.trip_count += 1
            entry = {
                "rule": rule,
                "t": sample.get("t"),
                "wall": sample.get("wall"),
                "detail": detail,
            }
            self.trip_log.append(entry)
            if len(self.trip_log) > MAX_TRIP_LOG:
                del self.trip_log[: len(self.trip_log) - MAX_TRIP_LOG]
            capture = self.bundle_dir and not self._capturing
            if capture:
                self._capturing = True
                self._bundle_seq += 1
                seq = self._bundle_seq
        metrics.incr("debug.watchdog_trips")
        metrics.incr(f"debug.watchdog_trip.{rule}")
        logger.warning("watchdog trip: %s %s", rule, detail)
        if capture:
            # bundle capture profiles for profile_seconds — far too slow
            # for the recorder's sampling thread; one capture at a time
            try:
                threading.Thread(
                    target=self._capture,
                    args=(rule, seq, entry),
                    daemon=True,
                    name="debug-bundle-capture",
                ).start()
            except Exception:
                # thread exhaustion IS an incident condition — a failed
                # spawn must not latch _capturing and disable every
                # future capture
                with self._lock:
                    self._capturing = False
                logger.exception("watchdog bundle-capture spawn failed")

    def _capture(self, rule: str, seq: int, entry: dict):
        from .bundle import capture_bundle

        try:
            # wall-clock stamp + process-local seq: unique across agent
            # restarts (a restart must never overwrite a prior
            # incident's evidence) and never relied on for ordering —
            # _prune_bundles orders by mtime, not name
            stamp = time.strftime("%Y%m%d-%H%M%S")
            dest = os.path.join(
                self.bundle_dir, f"watchdog-{stamp}-{seq}-{rule}"
            )
            manifest = capture_bundle(
                self.server,
                dest,
                profile_seconds=self.profile_seconds,
                reason=f"watchdog:{rule}",
            )
            with self._lock:
                self.bundles.append(manifest["path"])
                if len(self.bundles) > MAX_TRIP_LOG:
                    del self.bundles[: len(self.bundles) - MAX_TRIP_LOG]
                entry["bundle"] = manifest["path"]
            self._prune_bundles()
        except Exception:
            logger.exception("watchdog bundle capture failed")
        finally:
            with self._lock:
                self._capturing = False

    def _prune_bundles(self):
        """Keep the newest ``bundle_keep`` auto-captured bundle dirs on
        disk; only watchdog-minted ``watchdog-*`` directories are ever
        deleted (operator-captured bundles in the same dir are not ours
        to reap)."""
        import shutil

        def _mtime(path):
            try:
                return os.path.getmtime(path)
            except OSError:
                return 0.0

        try:
            # oldest-first by mtime — names are identity, not order
            mine = sorted(
                (
                    os.path.join(self.bundle_dir, name)
                    for name in os.listdir(self.bundle_dir)
                    if name.startswith("watchdog-")
                    and os.path.isdir(os.path.join(self.bundle_dir, name))
                ),
                key=_mtime,
            )
        except OSError:
            return
        for path in mine[: max(0, len(mine) - self.bundle_keep)]:
            shutil.rmtree(path, ignore_errors=True)

    # ------------------------------------------------------------------
    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Block until no bundle capture is in flight (test/shutdown
        barrier); True when idle."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._capturing:
                    return True
            time.sleep(0.05)
        return False

    def stats(self) -> dict:
        with self._lock:
            return {
                "trips": self.trip_count,
                # entry dicts are copied, not shared: _capture adds the
                # "bundle" key to the live entry (under the lock) after
                # stats() may have handed the log to a json.dump running
                # outside it
                "trip_log": [dict(e) for e in self.trip_log],
                "bundles": list(self.bundles),
                "rules": {k: dict(v) for k, v in self.rules.items()},
                "bundle_dir": self.bundle_dir,
            }
