"""End-to-end trace plane: per-eval span trees from submit to device and
back, with critical-path attribution (see OBSERVABILITY.md).

- :mod:`.span` — SpanContext/Span, the process :data:`tracer`
  (propagation registries, eval lifecycle, metric-unified spans);
- :mod:`.store` — bounded ring store with slowest-N + error tail keeps;
- :mod:`.critical_path` — per-stage attribution of ``eval.e2e`` from
  retained traces (the `/v1/trace/critical-path` + CLI surface).
"""

from .critical_path import (  # noqa: F401
    attribute,
    attribute_trace,
    build_tree,
    format_report,
    orphan_count,
)
from .span import NOOP_SPAN, Span, SpanContext, Tracer, tracer  # noqa: F401
from .store import TraceStore  # noqa: F401
