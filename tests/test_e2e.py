"""End-to-end cluster scenario over the real network tier (the e2e/
suite's role, SURVEY §4.6): a 3-server TCP raft cluster with two remote
node agents runs a service job through rolling update, node drain, leader
failure, and GC."""

import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.agent import ClientAgent, ServerAgent
from nomad_tpu.api.client import ApiClient
from nomad_tpu.api.http import HTTPServer
from nomad_tpu.structs.model import UpdateStrategy


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class TestClusterLifecycle:
    def test_full_lifecycle(self):
        # -- cluster formation ------------------------------------------
        agents = [
            ServerAgent(f"e2e-s{i}", config={"seed": 42, "heartbeat_ttl": 10.0})
            for i in range(3)
        ]
        voters = {a.name: a.address for a in agents}
        for a in agents:
            a.start(voters=dict(voters), num_workers=2)
        clients = []
        https = []
        try:
            leader = None
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and leader is None:
                leader = next(
                    (a for a in agents if a.server.is_leader()), None
                )
                time.sleep(0.05)
            assert leader is not None, "no leader elected"

            server_addrs = [a.address for a in agents]
            clients = [ClientAgent(list(server_addrs)) for _ in range(2)]
            for c in clients:
                c.start()
            wait_until(
                lambda: all(
                    leader.server.state.node_by_id(c.node.id) is not None
                    for c in clients
                ),
                msg="both nodes registered",
            )

            # -- HTTP on every server; writes through a follower must
            # leader-forward (static http table: no gossip in this cluster)
            https = []
            for a in agents:
                h = HTTPServer(a.server, port=0)
                h.start()
                https.append(h)
            table = {
                a.name: h.address for a, h in zip(agents, https)
            }
            for a in agents:
                a.server.config["server_http_addrs"] = table
            follower = next(a for a in agents if a is not leader)
            api = ApiClient(
                address=table[follower.name]
            )

            job = mock.job()
            job.id = "e2e-web"
            tg = job.task_groups[0]
            tg.count = 2
            tg.update = UpdateStrategy(
                max_parallel=1,
                min_healthy_time=int(0.1 * 1e9),
                healthy_deadline=int(20 * 1e9),
                progress_deadline=int(60 * 1e9),
                auto_revert=False,
            )
            task = tg.tasks[0]
            task.driver = "mock_driver"
            task.config = {"run_for": "600s"}
            task.resources.networks = []
            out = api.register_job(job.to_dict())
            assert out["EvalID"]

            def running_allocs():
                return [
                    a
                    for a in leader.server.state.allocs_by_job(
                        job.namespace, job.id
                    )
                    if a.client_status == "running"
                ]

            wait_until(
                lambda: len(running_allocs()) == 2, msg="v0 allocs running"
            )

            # -- rolling update drives a deployment to success ----------
            job_v1 = job.copy()
            job_v1.task_groups[0].tasks[0].config = {"run_for": "601s"}
            api.register_job(job_v1.to_dict())
            wait_until(
                lambda: any(
                    d.status == "successful"
                    for d in leader.server.state.deployments_by_job(
                        job.namespace, job.id
                    )
                ),
                timeout=60,
                msg="rolling update deployment successful",
            )
            wait_until(
                lambda: len(running_allocs()) == 2, msg="v1 allocs running"
            )

            # -- drain the node with at least one alloc -----------------
            victim_node = running_allocs()[0].node_id
            leader.server.node_drain(victim_node, drain=True)
            other = next(
                c.node.id for c in clients if c.node.id != victim_node
            )
            wait_until(
                lambda: len(running_allocs()) == 2
                and all(a.node_id == other for a in running_allocs()),
                timeout=60,
                msg="allocs migrated off the drained node",
            )

            # -- leader failure: cluster re-elects, scheduling resumes --
            old_leader = leader
            old_leader.stop()
            agents.remove(old_leader)
            deadline = time.monotonic() + 15
            leader = None
            while time.monotonic() < deadline and leader is None:
                leader = next(
                    (a for a in agents if a.server.is_leader()), None
                )
                time.sleep(0.05)
            assert leader is not None, "no new leader after failure"

            batch = mock.batch_job()
            batch.id = "e2e-batch"
            btg = batch.task_groups[0]
            btg.count = 1
            btg.tasks[0].driver = "mock_driver"
            btg.tasks[0].config = {"run_for": "0s"}
            btg.tasks[0].resources.networks = []
            leader.server.job_register(batch)
            wait_until(
                lambda: [
                    a.client_status
                    for a in leader.server.state.allocs_by_job(
                        batch.namespace, batch.id
                    )
                ]
                == ["complete"],
                timeout=60,
                msg="batch job completes after failover",
            )

            # -- teardown: stop + purge + force GC bounds state ---------
            leader.server.job_deregister(job.namespace, job.id, purge=True)
            leader.server.job_deregister(
                batch.namespace, batch.id, purge=True
            )
            wait_until(
                lambda: leader.server.state.job_by_id(job.namespace, job.id)
                is None,
                msg="job purged",
            )
            def gc_converged():
                # force-GC each round: allocs reach terminal status
                # asynchronously as clients confirm their stops
                leader.server.system_gc()
                time.sleep(0.2)
                return not [
                    a
                    for a in leader.server.state.allocs()
                    if a.job_id in ("e2e-web", "e2e-batch")
                ]

            wait_until(gc_converged, timeout=60, msg="allocs reaped")
        finally:
            for h in https:
                h.stop()
            for c in clients:
                c.stop()
            for a in agents:
                a.stop()
