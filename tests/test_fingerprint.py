"""Real host fingerprinting (ref client/fingerprint/fingerprint.go:31-50,
fingerprint_manager.go periodic re-fingerprint)."""

import os
import re
import time

from nomad_tpu.client import fingerprint as fp


class TestFingerprinters:
    def test_cpu_matches_host(self):
        cpu = fp.cpu_fingerprint()
        assert cpu["cores"] == os.cpu_count()
        assert cpu["mhz"] > 0
        assert cpu["total_compute"] >= cpu["cores"]

    def test_memory_matches_proc_meminfo(self):
        mb = fp.memory_fingerprint()
        with open("/proc/meminfo") as f:
            expected = int(re.search(r"MemTotal:\s*(\d+)", f.read()).group(1)) // 1024
        assert mb == expected
        assert mb > 0

    def test_storage_matches_statvfs(self, tmp_path):
        total, free = fp.storage_fingerprint(str(tmp_path))
        st = os.statvfs(str(tmp_path))
        assert total == st.f_blocks * st.f_frsize // (1024 * 1024)
        assert 0 < free <= total

    def test_host_identity(self):
        host = fp.host_fingerprint()
        assert host["kernel.name"] == "linux"
        assert host["kernel.version"]
        assert host["arch"]

    def test_network_has_usable_link(self):
        nets = fp.network_fingerprint()
        assert nets and nets[0].ip
        assert nets[0].mbits > 0


class TestEnvFingerprint:
    def _serve(self, handler_cls):
        import http.server
        import threading

        httpd = http.server.HTTPServer(("127.0.0.1", 0), handler_cls)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd, f"http://127.0.0.1:{httpd.server_address[1]}/"

    def test_aws_detected_against_fake_metadata(self):
        import http.server

        from nomad_tpu.client.fingerprint import env_aws_fingerprint

        answers = {
            "/instance-id": "i-0abc",
            "/instance-type": "m5.large",
            "/placement/availability-zone": "us-east-1a",
            "/local-ipv4": "10.0.0.7",
            "/local-hostname": "ip-10-0-0-7",
            "/ami-id": "ami-123",
        }

        class Meta(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                body = answers.get(self.path)
                if body is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):
                pass

        httpd, base = self._serve(Meta)
        try:
            attrs = env_aws_fingerprint(base=base)
            assert attrs["unique.platform.aws.instance-id"] == "i-0abc"
            assert attrs["platform.aws.instance-type"] == "m5.large"
            assert (
                attrs["platform.aws.placement.availability-zone"]
                == "us-east-1a"
            )
        finally:
            httpd.shutdown()

    def test_gce_detected_and_flavor_enforced(self):
        import http.server

        from nomad_tpu.client.fingerprint import env_gce_fingerprint

        class Meta(http.server.BaseHTTPRequestHandler):
            flavored = True

            def do_GET(self):
                values = {
                    "/id": "1234567",
                    "/hostname": "vm.c.proj.internal",
                    "/machine-type": "projects/1/machineTypes/n1-standard-4",
                    "/zone": "projects/1/zones/us-central1-a",
                }
                data = values.get(self.path, "").encode()
                self.send_response(200)
                if type(self).flavored:
                    self.send_header("Metadata-Flavor", "Google")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):
                pass

        httpd, base = self._serve(Meta)
        try:
            attrs = env_gce_fingerprint(base=base)
            assert attrs["platform.gce.machine-type"] == "n1-standard-4"
            assert attrs["platform.gce.zone"] == "us-central1-a"
            # a generic http server (no flavor header) must not pass
            Meta.flavored = False
            assert env_gce_fingerprint(base=base) == {}
        finally:
            httpd.shutdown()

    def test_off_cloud_returns_empty(self):
        from nomad_tpu.client.fingerprint import (
            env_aws_fingerprint,
            env_gce_fingerprint,
        )

        # unroutable/refused endpoints: both probes come back empty
        assert env_aws_fingerprint(base="http://127.0.0.1:9/") == {}
        assert env_gce_fingerprint(base="http://127.0.0.1:9/") == {}


class TestClientFingerprint:
    def test_node_reflects_real_host(self, tmp_path):
        from nomad_tpu.client.client import Client

        class NullServer:
            pass

        c = Client(NullServer(), data_dir=str(tmp_path))
        node = c.node
        mem = fp.memory_fingerprint()
        assert node.node_resources.memory.memory_mb == mem
        assert node.node_resources.cpu.cpu_shares == fp.cpu_fingerprint()["total_compute"]
        assert int(node.attributes["cpu.numcores"]) == os.cpu_count()
        assert node.attributes["kernel.version"]
        # disk advertises the real free space of the data dir's volume
        _, free = fp.storage_fingerprint(str(tmp_path))
        assert abs(node.node_resources.disk.disk_mb - free) < 1024

    def test_driver_health_change_triggers_reregister(self, tmp_path):
        from nomad_tpu.client.client import Client
        from nomad_tpu.client.driver import MockDriver

        registrations = []

        class RecordingServer:
            def node_register(self, node):
                registrations.append(node.drivers["mock_driver"].healthy)
                return {"heartbeat_ttl": 600.0}

            def node_update_status(self, node_id, status):
                return {}

            def get_client_allocs(self, node_id, min_index=0, timeout=0.5):
                time.sleep(timeout)
                return [], min_index

            def node_heartbeat(self, node_id):
                return {}

            def update_allocs(self, updates):
                return {}

        flaky = MockDriver()
        healthy = {"value": True}
        flaky.fingerprint = lambda: {
            "detected": True,
            "healthy": healthy["value"],
            "attributes": {},
        }
        c = Client(
            RecordingServer(),
            data_dir=str(tmp_path),
            drivers={"mock_driver": flaky},
        )
        c.fingerprint_interval = 0.2
        c.start()
        try:
            healthy["value"] = False
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if False in registrations:
                    break
                time.sleep(0.05)
            assert False in registrations, "health change must re-register"
        finally:
            c.stop()
