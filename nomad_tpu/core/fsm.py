"""Replicated finite-state machine: applies typed log entries into the
state store (ref nomad/fsm.go:173-1073).

The reference's raft FSM dispatches 31 log message types into the
StateStore and — on the leader, where the eval broker / blocked-evals /
periodic dispatcher are enabled — re-enqueues applied evaluations into the
in-memory brokers (fsm.go:190-252 switch, :1059 Snapshot, :1073 Restore).
This FSM keeps the same shape: every server (leader or follower) applies
the identical log; broker side effects are no-ops on followers because the
brokers are disabled there (eval_broker.go enqueue guards).

All writes in the framework flow through here: the server endpoints build
plain-dict payloads, consensus orders them, and `FSM.apply` mutates state
at the entry's log index, so the state-store index equals the raft index —
the invariant blocking queries and SnapshotMinIndex rely on.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Optional

from ..state.store import StateStore
from ..testing import faults as _faults
from ..structs.model import (
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_PENDING,
    Allocation,
    Deployment,
    DeploymentStatusUpdate,
    Evaluation,
    Job,
    JobSummary,
    Node,
    Plan,
    PlanResult,
    fast_alloc_clone,
)

logger = logging.getLogger("nomad_tpu.fsm")

# Log message types (ref fsm.go:190-252 / structs.go MessageType consts)
NODE_REGISTER = "node_register"
NODE_DEREGISTER = "node_deregister"
NODE_STATUS_UPDATE = "node_status_update"
NODE_DRAIN_UPDATE = "node_drain_update"
NODE_ELIGIBILITY_UPDATE = "node_eligibility_update"
NODE_EVENTS_UPSERT = "node_events_upsert"
JOB_REGISTER = "job_register"
JOB_DEREGISTER = "job_deregister"
JOB_BATCH_DEREGISTER = "job_batch_deregister"
JOB_STABILITY = "job_stability"
EVAL_UPDATE = "eval_update"
EVAL_DELETE = "eval_delete"
ALLOC_UPDATE = "alloc_update"
ALLOC_CLIENT_UPDATE = "alloc_client_update"
ALLOC_DESIRED_TRANSITION = "alloc_desired_transition"
APPLY_PLAN_RESULTS = "apply_plan_results"
APPLY_PLAN_RESULTS_BATCH = "apply_plan_results_batch"
DEPLOYMENT_STATUS_UPDATE = "deployment_status_update"
DEPLOYMENT_PROMOTE = "deployment_promote"
DEPLOYMENT_ALLOC_HEALTH = "deployment_alloc_health"
DEPLOYMENT_DELETE = "deployment_delete"
PERIODIC_LAUNCH = "periodic_launch"
SCHEDULER_CONFIG = "scheduler_config"
AUTOPILOT_CONFIG = "autopilot_config"
RECONCILE_SUMMARIES = "reconcile_summaries"
ACL_POLICY_UPSERT = "acl_policy_upsert"
ACL_POLICY_DELETE = "acl_policy_delete"
ACL_TOKEN_UPSERT = "acl_token_upsert"
ACL_TOKEN_DELETE = "acl_token_delete"
VAULT_ACCESSOR_UPSERT = "vault_accessor_upsert"
VAULT_ACCESSOR_DELETE = "vault_accessor_delete"
NOOP = "noop"


class FSM:
    """Applies ordered log entries into a StateStore, with leader-side
    broker re-enqueue hooks (ref fsm.go nomadFSM)."""

    def __init__(
        self,
        state: Optional[StateStore] = None,
        eval_broker=None,
        blocked_evals=None,
        periodic_dispatcher=None,
        time_table=None,
        event_broker=None,
    ):
        self.state = state if state is not None else StateStore()
        self.eval_broker = eval_broker
        self.blocked_evals = blocked_evals
        self.periodic_dispatcher = periodic_dispatcher
        self.time_table = time_table
        #: cluster event stream source (events/broker.py): every apply
        #: derives typed events tagged with its raft index — on every
        #: server, so followers serve /v1/event/stream too (ref
        #: nomad/state/events.go eventsFromChanges)
        self.event_broker = event_broker
        self._appliers: dict[str, Callable[[int, dict], Any]] = {
            NODE_REGISTER: self._apply_node_register,
            NODE_DEREGISTER: self._apply_node_deregister,
            NODE_STATUS_UPDATE: self._apply_node_status_update,
            NODE_DRAIN_UPDATE: self._apply_node_drain_update,
            NODE_ELIGIBILITY_UPDATE: self._apply_node_eligibility_update,
            NODE_EVENTS_UPSERT: self._apply_node_events_upsert,
            JOB_REGISTER: self._apply_job_register,
            JOB_DEREGISTER: self._apply_job_deregister,
            JOB_BATCH_DEREGISTER: self._apply_job_batch_deregister,
            JOB_STABILITY: self._apply_job_stability,
            EVAL_UPDATE: self._apply_eval_update,
            EVAL_DELETE: self._apply_eval_delete,
            ALLOC_UPDATE: self._apply_alloc_update,
            ALLOC_CLIENT_UPDATE: self._apply_alloc_client_update,
            ALLOC_DESIRED_TRANSITION: self._apply_alloc_desired_transition,
            APPLY_PLAN_RESULTS: self._apply_plan_results,
            APPLY_PLAN_RESULTS_BATCH: self._apply_plan_results_batch,
            DEPLOYMENT_STATUS_UPDATE: self._apply_deployment_status_update,
            DEPLOYMENT_PROMOTE: self._apply_deployment_promote,
            DEPLOYMENT_ALLOC_HEALTH: self._apply_deployment_alloc_health,
            DEPLOYMENT_DELETE: self._apply_deployment_delete,
            PERIODIC_LAUNCH: self._apply_periodic_launch,
            SCHEDULER_CONFIG: self._apply_scheduler_config,
            AUTOPILOT_CONFIG: self._apply_autopilot_config,
            RECONCILE_SUMMARIES: self._apply_reconcile_summaries,
            ACL_POLICY_UPSERT: self._apply_acl_policy_upsert,
            ACL_POLICY_DELETE: self._apply_acl_policy_delete,
            ACL_TOKEN_UPSERT: self._apply_acl_token_upsert,
            ACL_TOKEN_DELETE: self._apply_acl_token_delete,
            VAULT_ACCESSOR_UPSERT: self._apply_vault_accessor_upsert,
            VAULT_ACCESSOR_DELETE: self._apply_vault_accessor_delete,
            NOOP: lambda index, payload: None,
        }

    # ------------------------------------------------------------------
    def apply(self, index: int, msg_type: str, payload: dict) -> Any:
        """Apply one committed log entry. Returns the applier's response
        (surfaced to the caller that proposed the entry)."""
        applier = self._appliers.get(msg_type)
        if applier is None:
            # Unknown types must not crash replication (fsm.go ignores
            # ignoreUnknownTypeFlag entries); log and skip.
            logger.error("fsm: unknown message type %r at index %d", msg_type, index)
            return None
        if self.time_table is not None and msg_type != NOOP:
            # witness index→time for GC age thresholds (fsm.go:258).
            # Noops are excluded to match the reference, where LogNoop
            # entries never reach fsm.Apply at all — every election
            # appends a term-start noop (the leadership barrier rides
            # its apply), and witnessing it would stamp "now" before any
            # real write (on a fresh cluster that poisons backdated
            # test witnesses; the next real apply witnesses anyway)
            self.time_table.witness(index)
        pre = None
        if self.event_broker is not None and msg_type in (
            DEPLOYMENT_DELETE, EVAL_DELETE,
        ):
            # deletions derive their events from objects that no longer
            # exist post-apply: capture them first so the events carry
            # the real namespace instead of a guessed 'default'
            pre = self._capture_pre_delete(msg_type, payload)
        # chaos crash points (testing/faults.py): a seeded kill before /
        # after the state mutation simulates a server dying mid-apply —
        # the crash-recovery storm restores from snapshot + log replay
        # and must find planes byte-identical to a cold rebuild
        _faults.fault_point("fsm.apply.pre")
        resp = applier(index, payload)
        _faults.fault_point("fsm.apply.post_state")
        if self.event_broker is not None and msg_type in (
            ACL_POLICY_UPSERT, ACL_POLICY_DELETE,
            ACL_TOKEN_UPSERT, ACL_TOKEN_DELETE,
        ):
            # capabilities may have shrunk: token-backed stream
            # subscriptions must re-resolve, not keep old grants
            self.event_broker.acl_changed()
        if self.event_broker is not None:
            # events derive AFTER the applier so lookups see post-apply
            # state; a derivation bug must never stall replication
            try:
                events = derive_events(
                    self.state, index, msg_type, payload, pre=pre
                )
                if events:
                    self.event_broker.publish(index, events)
            except Exception:
                logger.exception(
                    "fsm: event derivation failed for %r at index %d",
                    msg_type, index,
                )
        return resp

    def _capture_pre_delete(self, msg_type: str, payload: dict) -> dict:
        """The soon-to-be-deleted objects, keyed by id (event derivation
        needs their namespace/job after the applier removed them)."""
        if msg_type == DEPLOYMENT_DELETE:
            return {
                did: self.state.deployment_by_id(did)
                for did in payload.get("deployment_ids") or []
            }
        return {
            eid: self.state.eval_by_id(eid)
            for eid in payload.get("eval_ids") or []
        }

    # ------------------------------------------------------------------
    # snapshot / restore (ref fsm.go:1059,1073)
    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        return self.state.persist()

    def restore(self, data: dict):
        self.state.restore(data)
        if self.event_broker is not None:
            # the event ring is re-derivable, never snapshotted: reset it
            # to the restored index so resuming subscribers observe an
            # explicit gap instead of silently missing the history
            self.event_broker.reset(self.state.latest_index())

    # ------------------------------------------------------------------
    # node appliers (ref fsm.go applyUpsertNode / applyDeregisterNode /
    # applyStatusUpdate / applyDrainUpdate / applyEligibilityUpdate)
    # ------------------------------------------------------------------
    def _apply_node_register(self, index: int, payload: dict):
        node = Node.from_dict(payload["node"])
        self.state.upsert_node(index, node)
        # new capacity unblocks class-matching blocked evals
        if self.blocked_evals is not None:
            if node.computed_class:
                self.blocked_evals.unblock(node.computed_class, index)
            self.blocked_evals.unblock_node(node.id, index)
        return index

    def _apply_node_deregister(self, index: int, payload: dict):
        self.state.delete_node(index, payload["node_id"])
        return index

    def _apply_node_status_update(self, index: int, payload: dict):
        self.state.update_node_status(
            index,
            payload["node_id"],
            payload["status"],
            updated_at_ns=payload.get("updated_at", 0),
        )
        if self.blocked_evals is not None and payload["status"] == "ready":
            node = self.state.node_by_id(payload["node_id"])
            if node is not None and node.computed_class:
                self.blocked_evals.unblock(node.computed_class, index)
            self.blocked_evals.unblock_node(payload["node_id"], index)
        return index

    def _apply_node_drain_update(self, index: int, payload: dict):
        from ..structs.model import DrainStrategy

        strategy = payload.get("drain_strategy")
        self.state.update_node_drain(
            index,
            payload["node_id"],
            payload["drain"],
            strategy=DrainStrategy.from_dict(strategy) if strategy else None,
            mark_eligible=payload.get("mark_eligible", False),
            updated_at_ns=payload.get("updated_at", 0),
        )
        return index

    def _apply_node_eligibility_update(self, index: int, payload: dict):
        self.state.update_node_eligibility(
            index,
            payload["node_id"],
            payload["eligibility"],
            updated_at_ns=payload.get("updated_at", 0),
        )
        return index

    def _apply_node_events_upsert(self, index: int, payload: dict):
        """ref fsm.go applyUpsertNodeEvent (NodeEventsUpsertRequestType):
        operational events — driver health flaps, device faults — appended
        to each node's bounded event ring."""
        self.state.upsert_node_events(index, payload["events"])
        return index

    # ------------------------------------------------------------------
    # job appliers (ref fsm.go applyUpsertJob / applyDeregisterJob)
    # ------------------------------------------------------------------
    def _apply_job_register(self, index: int, payload: dict):
        job = Job.from_dict(payload["job"])
        self.state.upsert_job(index, job)
        stored = self.state.job_by_id(job.namespace, job.id)
        if stored.is_periodic() and not stored.stopped():
            # Seed the launch checkpoint at registration (ref fsm.go
            # applyUpsertJob → UpsertPeriodicLaunch when none exists) so a
            # leader restored after downtime knows the job existed before
            # the outage and can catch up its missed first run. Stamped
            # with submit_time, which is deterministic across replicas.
            if self.state.periodic_launch_by_id(stored.namespace, stored.id) is None:
                self.state.upsert_periodic_launch(
                    index, stored.namespace, stored.id, stored.submit_time
                )
        if self.periodic_dispatcher is not None:
            # leader tracks periodic jobs as they are applied (fsm.go:330)
            if stored.is_periodic() and not stored.stopped():
                self.periodic_dispatcher.add(stored)
            else:
                self.periodic_dispatcher.remove(stored.namespace, stored.id)
        return index

    def _apply_job_deregister(self, index: int, payload: dict):
        ns, job_id = payload["namespace"], payload["job_id"]
        if payload.get("purge"):
            self.state.delete_job(index, ns, job_id)
        else:
            job = self.state.job_by_id(ns, job_id)
            if job is not None:
                stopped = job.copy()
                stopped.stop = True
                self.state.upsert_job(index, stopped)
        if self.periodic_dispatcher is not None:
            self.periodic_dispatcher.remove(ns, job_id)
        if self.blocked_evals is not None:
            self.blocked_evals.untrack(ns, job_id)
        return index

    def _apply_job_batch_deregister(self, index: int, payload: dict):
        for item in payload["jobs"]:
            self._apply_job_deregister(
                index,
                {
                    "namespace": item["namespace"],
                    "job_id": item["job_id"],
                    "purge": item.get("purge", False),
                },
            )
        self._apply_eval_update(index, {"evals": payload.get("evals", [])})
        return index

    def _apply_job_stability(self, index: int, payload: dict):
        self.state.update_job_stability(
            index,
            payload["namespace"],
            payload["job_id"],
            payload["version"],
            payload["stable"],
        )
        return index

    # ------------------------------------------------------------------
    # eval appliers (ref fsm.go applyUpdateEval:560-620)
    # ------------------------------------------------------------------
    def _apply_eval_update(self, index: int, payload: dict):
        evals = [Evaluation.from_dict(d) for d in payload["evals"]]
        if not evals:
            return index
        self.state.upsert_evals(index, evals)
        self._handle_upserted_evals(evals)
        return index

    def _handle_upserted_evals(self, evals: list[Evaluation]):
        """Leader-side broker routing of applied evals (fsm.go:585-618):
        pending → broker, blocked → blocked-tracker, others untracked."""
        for ev in evals:
            stored = self.state.eval_by_id(ev.id)
            if stored is None:
                continue
            if stored.should_enqueue():
                if self.eval_broker is not None:
                    self.eval_broker.enqueue(stored)
            elif stored.should_block():
                if self.blocked_evals is not None:
                    self.blocked_evals.block(stored)
            elif (
                self.blocked_evals is not None
                and stored.status == "complete"
                and not stored.failed_tg_allocs
            ):
                # fully-satisfied eval: drop any tracked blocked eval for
                # the job (fsm.go:612-617)
                self.blocked_evals.untrack(stored.namespace, stored.job_id)

    def _apply_eval_delete(self, index: int, payload: dict):
        self.state.delete_evals(
            index, payload.get("eval_ids", []), payload.get("alloc_ids", [])
        )
        return index

    # ------------------------------------------------------------------
    # alloc appliers (ref fsm.go applyAllocUpdate / applyAllocClientUpdate /
    # applyAllocUpdateDesiredTransition)
    # ------------------------------------------------------------------
    def _apply_alloc_update(self, index: int, payload: dict):
        allocs = [Allocation.from_dict(d) for d in payload["allocs"]]
        self.state.upsert_allocs(index, allocs)
        return index

    def _apply_alloc_client_update(self, index: int, payload: dict):
        allocs = [Allocation.from_dict(d) for d in payload["allocs"]]
        self.state.update_allocs_from_client(index, allocs)
        # an alloc turning terminal frees capacity on ITS node: per-node
        # system blocked evals re-enter (ref blocked_evals_system.go;
        # the fsm's applyAllocClientUpdate → UnblockNode)
        if self.blocked_evals is not None:
            for a in allocs:
                if a.node_id and a.terminal_status():
                    self.blocked_evals.unblock_node(a.node_id, index)
        # evals created by the endpoint ride the same log entry
        # (ref node_endpoint.go UpdateAlloc → AllocUpdateRequest.Evals)
        self._apply_eval_update(index, {"evals": payload.get("evals", [])})
        return index

    def _apply_alloc_desired_transition(self, index: int, payload: dict):
        updates = []
        for alloc_id, transition in payload["allocs"].items():
            stored = self.state.alloc_by_id(alloc_id)
            if stored is None:
                continue
            ac = stored.copy()
            if transition.get("migrate") is not None:
                ac.desired_transition.migrate = transition["migrate"]
            if transition.get("reschedule") is not None:
                ac.desired_transition.reschedule = transition["reschedule"]
            if transition.get("force_reschedule") is not None:
                ac.desired_transition.force_reschedule = transition["force_reschedule"]
            updates.append(ac)
        if updates:
            self.state.upsert_allocs(index, updates)
        self._apply_eval_update(index, {"evals": payload.get("evals", [])})
        return index

    # ------------------------------------------------------------------
    # plan apply (ref fsm.go applyPlanResults → UpsertPlanResults)
    # ------------------------------------------------------------------
    def _apply_plan_results_batch(self, index: int, payload: dict):
        """Several independent verified plans committed in ONE raft entry
        (one fsync, one consensus round-trip): the applier batches queued
        plans it has verified against stacked optimistic snapshots, so the
        sequential application here reproduces exactly the world each was
        verified against (ref plan_apply.go:49-180 — the reference keeps
        one commit in flight; batching amortizes the consensus cost the
        same way its async applyPlan pipelining does)."""
        for item in payload.get("plans", []):
            self._apply_plan_results(index, item)
        return index

    def _apply_plan_results(self, index: int, payload: dict):
        from ..trace import tracer

        # raft-entry trace annotation (leader-minted): spans THIS
        # replica's apply and links the committed index to the eval's
        # trace so the ColumnarMirror's patch spans attach later. Popped
        # before use — it never reaches state-store objects. Followers,
        # whose store never opened the leader's trace, skip recording
        # entirely (their spans would only be dropped on arrival)
        trace_ctx = tracer.ctx_from_annotation(payload.get("trace"))
        if trace_ctx is not None and not tracer.store.knows(
            trace_ctx.trace_id
        ):
            trace_ctx = None
        t0 = time.monotonic()
        plan = Plan.from_dict(payload["plan"])
        if payload.get("normalized"):
            result = self._denormalize_plan_result(payload["result"])
        else:
            result = PlanResult.from_dict(payload["result"])
        preemption_evals = [
            Evaluation.from_dict(d) for d in payload.get("preemption_evals", [])
        ]
        if trace_ctx is not None:
            # linked BEFORE the upsert publishes the plan frame: a
            # mirror sync on another thread can consume the frame
            # immediately, and its ctxs_for_index lookup must not race
            # an unlinked index (the mirror.patch hop would be lost)
            tracer.link_index(index, trace_ctx)
        self.state.upsert_plan_results(
            index, plan, result, preemption_evals=preemption_evals
        )
        self._handle_upserted_evals(preemption_evals)
        if trace_ctx is not None:
            tracer.record_span(
                "fsm.apply_plan", trace_ctx, t0, time.monotonic(),
                tags={"index": index},
            )
        return index

    def _denormalize_plan_result(self, doc: dict) -> PlanResult:
        """Rehydrate stop/preemption diffs from this replica's own state
        (ref fsm.go denormalizeAllocationDiffSlice): the full documents are
        already replicated here, the diff carries only what changed."""

        def rehydrate(diff_map: dict) -> dict:
            out: dict = {}
            for node_id, diffs in diff_map.items():
                allocs = []
                for d in diffs:
                    stored = self.state.alloc_by_id(d["id"])
                    if stored is None:
                        logger.warning(
                            "plan diff references unknown alloc %s", d["id"]
                        )
                        continue
                    # shallow clone (bulk stops are the raft hot path) that
                    # keeps stored.job: nulling the job would make the
                    # store re-attach plan.job, which for a PREEMPTION
                    # victim is the preemptor's job, not the victim's
                    a = fast_alloc_clone(stored)
                    a.desired_status = d["desired_status"]
                    a.desired_description = d["desired_description"]
                    if d.get("client_status"):
                        a.client_status = d["client_status"]
                    if d.get("preempted_by_allocation"):
                        a.preempted_by_allocation = d["preempted_by_allocation"]
                    allocs.append(a)
                out[node_id] = allocs
            return out

        # shared job documents ship once per plan; reattach by ref. The
        # parsed Job object is deliberately shared across the plan's
        # placements — the store treats published objects as immutable.
        jobs = {
            jkey: Job.from_dict(jd)
            for jkey, jd in doc.get("jobs", {}).items()
        }

        def placement(x: dict) -> Allocation:
            # get, not pop: the payload dict lives in the raft log and may
            # be re-applied on restore; from_dict ignores unknown keys
            jkey = x.get("job_ref")
            a = Allocation.from_dict(x)
            if jkey is not None:
                a.job = jobs[jkey]
            return a

        return PlanResult(
            node_update=rehydrate(doc.get("node_update", {})),
            node_preemptions=rehydrate(doc.get("node_preemptions", {})),
            node_allocation={
                node_id: [placement(x) for x in allocs]
                for node_id, allocs in doc.get("node_allocation", {}).items()
            },
            deployment=(
                Deployment.from_dict(doc["deployment"])
                if doc.get("deployment")
                else None
            ),
            deployment_updates=[
                DeploymentStatusUpdate.from_dict(u)
                for u in doc.get("deployment_updates", [])
            ],
            refresh_index=doc.get("refresh_index", 0),
        )

    # ------------------------------------------------------------------
    # deployment appliers (ref fsm.go applyDeployment*)
    # ------------------------------------------------------------------
    def _apply_deployment_status_update(self, index: int, payload: dict):
        update = DeploymentStatusUpdate.from_dict(payload["update"])
        self.state.update_deployment_status(index, update)
        if payload.get("job") is not None:
            self.state.upsert_job(index, Job.from_dict(payload["job"]))
        self._apply_eval_update(
            index,
            {"evals": [payload["eval"]] if payload.get("eval") else []},
        )
        return index

    def _apply_deployment_promote(self, index: int, payload: dict):
        self.state.update_deployment_promotion(
            index,
            payload["deployment_id"],
            payload.get("groups", []),
            payload.get("all", False),
        )
        self._apply_eval_update(
            index,
            {"evals": [payload["eval"]] if payload.get("eval") else []},
        )
        return index

    def _apply_deployment_alloc_health(self, index: int, payload: dict):
        self.state.update_deployment_alloc_health(
            index,
            payload["deployment_id"],
            payload.get("healthy_ids", []),
            payload.get("unhealthy_ids", []),
            timestamp_ns=payload.get("timestamp", 0),
        )
        if payload.get("deployment_status_update") is not None:
            self.state.update_deployment_status(
                index,
                DeploymentStatusUpdate.from_dict(
                    payload["deployment_status_update"]
                ),
            )
        if payload.get("job") is not None:
            self.state.upsert_job(index, Job.from_dict(payload["job"]))
        self._apply_eval_update(
            index,
            {"evals": [payload["eval"]] if payload.get("eval") else []},
        )
        return index

    def _apply_deployment_delete(self, index: int, payload: dict):
        self.state.delete_deployment(index, payload["deployment_ids"])
        return index

    # ------------------------------------------------------------------
    def _apply_periodic_launch(self, index: int, payload: dict):
        self.state.upsert_periodic_launch(
            index, payload["namespace"], payload["job_id"], payload["launch"]
        )
        return index

    def _apply_scheduler_config(self, index: int, payload: dict):
        self.state.set_scheduler_config(index, payload["config"])
        return index

    def _apply_autopilot_config(self, index: int, payload: dict):
        self.state.set_autopilot_config(index, payload["config"])
        return index

    def _apply_reconcile_summaries(self, index: int, payload: dict):
        self.state.reconcile_job_summaries(index)
        return index

    # ------------------------------------------------------------------
    # ACL appliers (ref fsm.go applyACL*; store methods land with the ACL
    # subsystem — gated so replication of ACL entries never crashes)
    # ------------------------------------------------------------------
    def _apply_vault_accessor_upsert(self, index: int, payload: dict):
        self.state.upsert_vault_accessors(index, payload["accessors"])
        return index

    def _apply_vault_accessor_delete(self, index: int, payload: dict):
        self.state.delete_vault_accessors(index, payload["accessors"])
        return index

    def _apply_acl_policy_upsert(self, index: int, payload: dict):
        if hasattr(self.state, "upsert_acl_policies"):
            self.state.upsert_acl_policies(index, payload["policies"])
        return index

    def _apply_acl_policy_delete(self, index: int, payload: dict):
        if hasattr(self.state, "delete_acl_policies"):
            self.state.delete_acl_policies(index, payload["names"])
        return index

    def _apply_acl_token_upsert(self, index: int, payload: dict):
        self.state.upsert_acl_tokens(
            index, payload["tokens"], bootstrap=payload.get("bootstrap", False)
        )
        return index

    def _apply_acl_token_delete(self, index: int, payload: dict):
        if hasattr(self.state, "delete_acl_tokens"):
            self.state.delete_acl_tokens(index, payload["accessors"])
        return index


# ----------------------------------------------------------------------
# Event derivation (ref nomad/state/events.go eventsFromChanges: each
# applied message type maps to typed events tagged with its raft index).
# Module-level and pure-ish (reads post-apply state for lookups only) so
# the mapping is testable without a full FSM.
# ----------------------------------------------------------------------

def _alloc_doc(state, alloc_id: str, fallback: Optional[dict] = None) -> dict:
    """Canonical slim alloc doc from post-apply state (client updates
    ship only client-owned fields, so the payload alone can't provide
    job/deployment filter keys); falls back to the payload doc when the
    alloc is already GC'd. Carries the alloc's dense usage vector and
    terminal flag so the columnar mirror (tpu/mirror.py) can patch its
    ``used`` plane from the event alone — derived here, synchronously
    inside the apply, so the vector reflects exactly this raft index."""
    stored = state.alloc_by_id(alloc_id)
    if stored is None:
        # already deleted: whatever it contributed is gone with it
        return dict(fallback or {}, id=alloc_id, _terminal=True)
    from ..state.planes import exotic_flag, usage_vec

    return {
        "id": stored.id,
        "namespace": stored.namespace,
        "job_id": stored.job_id,
        "node_id": stored.node_id,
        "task_group": stored.task_group,
        "desired_status": stored.desired_status,
        "client_status": stored.client_status,
        "eval_id": stored.eval_id,
        "deployment_id": stored.deployment_id,
        "_terminal": stored.terminal_status(),
        "_usage": usage_vec(stored),
        # ports/devices flag: lets the mirror keep per-row exotic counts
        # so the plan applier's dense device verify knows which rows must
        # take the exact host check (core/plan_apply.py)
        "_exotic": exotic_flag(stored),
    }


def _alloc_event(index: int, doc: dict, event_type: str) -> "Event":
    from ..events import TOPIC_ALLOC, Event

    filter_keys = tuple(
        k for k in (
            doc.get("job_id"), doc.get("node_id"),
            doc.get("eval_id"), doc.get("deployment_id"),
        ) if k
    )
    payload = {
        "ID": doc.get("id", ""),
        "JobID": doc.get("job_id", ""),
        "NodeID": doc.get("node_id", ""),
        "TaskGroup": doc.get("task_group", ""),
        "DesiredStatus": doc.get("desired_status", ""),
        "ClientStatus": doc.get("client_status", ""),
        "DeploymentID": doc.get("deployment_id", ""),
    }
    if "_terminal" in doc:
        # mirror-plane fields (tpu/mirror.py): terminality + the alloc's
        # dense (cpu, mem, disk, mbits) contribution at this raft index
        payload["Terminal"] = bool(doc["_terminal"])
        if doc.get("_usage") is not None:
            payload["Resources"] = list(doc["_usage"])
        # missing (GC-fallback doc) reads as True downstream — the mirror
        # defaults unknown allocs to exotic, degrading verify not parity
        if "_exotic" in doc:
            payload["Exotic"] = bool(doc["_exotic"])
    return Event(
        topic=TOPIC_ALLOC,
        type=event_type,
        key=doc.get("id", ""),
        index=index,
        namespace=doc.get("namespace", "default"),
        payload=payload,
        filter_keys=filter_keys,
    )


def _eval_events(index: int, evals: list, event_type: str = "EvalUpdated"):
    from ..events import TOPIC_EVAL, Event

    out = []
    for doc in evals or []:
        out.append(
            Event(
                topic=TOPIC_EVAL,
                type=event_type,
                key=doc.get("id", ""),
                index=index,
                namespace=doc.get("namespace", "default"),
                payload={
                    "ID": doc.get("id", ""),
                    "JobID": doc.get("job_id", ""),
                    "Status": doc.get("status", ""),
                    "Type": doc.get("type", ""),
                    "TriggeredBy": doc.get("triggered_by", ""),
                    "DeploymentID": doc.get("deployment_id", ""),
                },
                filter_keys=tuple(
                    k for k in (doc.get("job_id"), doc.get("deployment_id"))
                    if k
                ),
            )
        )
    return out


def _node_event(index: int, node_id: str, event_type: str, payload: dict):
    from ..events import TOPIC_NODE, Event

    return Event(
        topic=TOPIC_NODE,
        type=event_type,
        key=node_id,
        index=index,
        payload=dict(payload, ID=node_id),
    )


def _deployment_event(
    state, index: int, deployment_id: str, event_type: str, payload: dict,
    deployment=None,
):
    from ..events import TOPIC_DEPLOYMENT, Event

    d = deployment if deployment is not None else state.deployment_by_id(
        deployment_id
    )
    return Event(
        topic=TOPIC_DEPLOYMENT,
        type=event_type,
        key=deployment_id,
        index=index,
        namespace=d.namespace if d is not None else "default",
        payload=dict(
            payload,
            ID=deployment_id,
            JobID=d.job_id if d is not None else "",
            Status=d.status if d is not None else "",
        ),
        filter_keys=(d.job_id,) if d is not None and d.job_id else (),
    )


def _job_event(index: int, namespace: str, job_id: str, event_type: str,
               payload: Optional[dict] = None):
    from ..events import TOPIC_JOB, Event

    return Event(
        topic=TOPIC_JOB,
        type=event_type,
        key=job_id,
        index=index,
        namespace=namespace or "default",
        payload=dict(payload or {}, ID=job_id, Namespace=namespace),
    )


def _job_registered_event(state, index: int, job_doc: dict):
    """The registered-job event, versioned from POST-apply state: the
    store assigns the version during apply (existing.version + 1), so the
    raft payload's own version field is stale on every update."""
    ns = job_doc.get("namespace", "default")
    job_id = job_doc.get("id", "")
    stored = state.job_by_id(ns, job_id)
    return _job_event(
        index, ns, job_id, "JobRegistered",
        {
            "Type": (
                stored.type if stored is not None
                else job_doc.get("type", "")
            ),
            "Version": (
                stored.version if stored is not None
                else job_doc.get("version", 0)
            ),
        },
    )


def _plan_events(state, index: int, payload: dict) -> list:
    from ..events import TOPIC_PLAN_RESULT, Event

    plan = payload.get("plan") or {}
    result = payload.get("result") or {}
    events = []
    n_place = sum(
        len(v) for v in (result.get("node_allocation") or {}).values()
    )
    n_stop = sum(len(v) for v in (result.get("node_update") or {}).values())
    n_preempt = sum(
        len(v) for v in (result.get("node_preemptions") or {}).values()
    )
    events.append(
        Event(
            topic=TOPIC_PLAN_RESULT,
            type="PlanResult",
            key=plan.get("eval_id", ""),
            index=index,
            namespace=(plan.get("job") or {}).get("namespace", "default"),
            payload={
                "EvalID": plan.get("eval_id", ""),
                "JobID": plan.get("job_id", "")
                or (plan.get("job") or {}).get("id", ""),
                "NodeAllocation": n_place,
                "NodeUpdate": n_stop,
                "NodePreemptions": n_preempt,
                "Deployment": (result.get("deployment") or {}).get("id", ""),
            },
            filter_keys=tuple(
                k for k in (
                    plan.get("job_id")
                    or (plan.get("job") or {}).get("id"),
                ) if k
            ),
        )
    )
    for allocs in (result.get("node_allocation") or {}).values():
        for doc in allocs:
            # placements were just upserted: read them back post-apply so
            # the event carries the canonical doc (incl. the usage vector
            # the columnar mirror patches from)
            events.append(
                _alloc_event(
                    index, _alloc_doc(state, doc.get("id", ""), doc),
                    "AllocationUpdated",
                )
            )
    # stops/preemptions travel as id+field diffs when normalized; the
    # full documents live in this replica's (post-apply) state
    for diff_map, etype in (
        (result.get("node_update") or {}, "AllocationStopped"),
        (result.get("node_preemptions") or {}, "AllocationPreempted"),
    ):
        for diffs in diff_map.values():
            for d in diffs:
                events.append(
                    _alloc_event(
                        index, _alloc_doc(state, d.get("id", ""), d), etype
                    )
                )
    deployment = result.get("deployment")
    if deployment:
        events.append(
            _deployment_event(
                state, index, deployment.get("id", ""),
                "DeploymentStatusUpdate", {},
            )
        )
    for update in result.get("deployment_updates") or []:
        events.append(
            _deployment_event(
                state, index, update.get("deployment_id", ""),
                "DeploymentStatusUpdate",
                {"StatusDescription": update.get("status_description", "")},
            )
        )
    events.extend(_eval_events(index, payload.get("preemption_evals")))
    return events


def derive_events(
    state, index: int, msg_type: str, payload: dict, pre: Optional[dict] = None
) -> list:
    """Typed events for one applied log entry (called post-apply; ``pre``
    carries pre-apply snapshots of objects a delete entry removed)."""
    from ..events import TOPIC_NODE_EVENT, Event

    if msg_type == NODE_REGISTER:
        node = payload.get("node") or {}
        return [
            _node_event(
                index, node.get("id", ""), "NodeRegistration",
                {"Name": node.get("name", ""), "Status": node.get("status", "")},
            )
        ]
    if msg_type == NODE_DEREGISTER:
        return [
            _node_event(index, payload.get("node_id", ""),
                        "NodeDeregistration", {})
        ]
    if msg_type == NODE_STATUS_UPDATE:
        return [
            _node_event(
                index, payload.get("node_id", ""), "NodeStatusUpdate",
                {"Status": payload.get("status", "")},
            )
        ]
    if msg_type == NODE_DRAIN_UPDATE:
        return [
            _node_event(
                index, payload.get("node_id", ""), "NodeDrain",
                {"Drain": bool(payload.get("drain"))},
            )
        ]
    if msg_type == NODE_ELIGIBILITY_UPDATE:
        return [
            _node_event(
                index, payload.get("node_id", ""), "NodeEligibility",
                {"Eligibility": payload.get("eligibility", "")},
            )
        ]
    if msg_type == NODE_EVENTS_UPSERT:
        return [
            Event(
                topic=TOPIC_NODE_EVENT,
                type="NodeEvent",
                key=node_id,
                index=index,
                payload={"ID": node_id, "Events": list(node_events)},
            )
            for node_id, node_events in (payload.get("events") or {}).items()
        ]
    if msg_type == JOB_REGISTER:
        return [_job_registered_event(state, index, payload.get("job") or {})]
    if msg_type == JOB_DEREGISTER:
        return [
            _job_event(
                index, payload.get("namespace", "default"),
                payload.get("job_id", ""), "JobDeregistered",
                {"Purge": bool(payload.get("purge"))},
            )
        ]
    if msg_type == JOB_BATCH_DEREGISTER:
        events = [
            _job_event(
                index, item.get("namespace", "default"),
                item.get("job_id", ""), "JobDeregistered",
                {"Purge": bool(item.get("purge"))},
            )
            for item in payload.get("jobs") or []
        ]
        events.extend(_eval_events(index, payload.get("evals")))
        return events
    if msg_type == JOB_STABILITY:
        return [
            _job_event(
                index, payload.get("namespace", "default"),
                payload.get("job_id", ""), "JobStabilityUpdated",
                {
                    "Version": payload.get("version", 0),
                    "Stable": bool(payload.get("stable")),
                },
            )
        ]
    if msg_type == EVAL_UPDATE:
        return _eval_events(index, payload.get("evals"))
    if msg_type == EVAL_DELETE:
        from ..events import TOPIC_EVAL

        events = []
        for eval_id in payload.get("eval_ids") or []:
            stored = (pre or {}).get(eval_id)
            events.append(
                Event(
                    topic=TOPIC_EVAL, type="EvalDeleted", key=eval_id,
                    index=index,
                    namespace=(
                        stored.namespace if stored is not None else "default"
                    ),
                    payload={
                        "ID": eval_id,
                        "JobID": stored.job_id if stored is not None else "",
                    },
                    filter_keys=(
                        (stored.job_id,)
                        if stored is not None and stored.job_id
                        else ()
                    ),
                )
            )
        return events
    if msg_type in (ALLOC_UPDATE, ALLOC_CLIENT_UPDATE):
        etype = (
            "AllocationClientUpdated"
            if msg_type == ALLOC_CLIENT_UPDATE
            else "AllocationUpdated"
        )
        events = [
            _alloc_event(
                index, _alloc_doc(state, doc.get("id", ""), doc), etype
            )
            for doc in payload.get("allocs") or []
        ]
        events.extend(_eval_events(index, payload.get("evals")))
        return events
    if msg_type == ALLOC_DESIRED_TRANSITION:
        events = [
            _alloc_event(
                index, _alloc_doc(state, alloc_id),
                "AllocationDesiredTransition",
            )
            for alloc_id in (payload.get("allocs") or {})
        ]
        events.extend(_eval_events(index, payload.get("evals")))
        return events
    if msg_type == APPLY_PLAN_RESULTS:
        return _plan_events(state, index, payload)
    if msg_type == APPLY_PLAN_RESULTS_BATCH:
        events = []
        for item in payload.get("plans") or []:
            events.extend(_plan_events(state, index, item))
        return events
    if msg_type == DEPLOYMENT_STATUS_UPDATE:
        update = payload.get("update") or {}
        events = [
            _deployment_event(
                state, index, update.get("deployment_id", ""),
                "DeploymentStatusUpdate",
                {"StatusDescription": update.get("status_description", "")},
            )
        ]
        if payload.get("job"):
            events.append(
                _job_registered_event(state, index, payload["job"])
            )
        events.extend(
            _eval_events(index, [payload["eval"]] if payload.get("eval") else [])
        )
        return events
    if msg_type == DEPLOYMENT_PROMOTE:
        events = [
            _deployment_event(
                state, index, payload.get("deployment_id", ""),
                "DeploymentPromotion",
                {"All": bool(payload.get("all")),
                 "Groups": list(payload.get("groups") or [])},
            )
        ]
        events.extend(
            _eval_events(index, [payload["eval"]] if payload.get("eval") else [])
        )
        return events
    if msg_type == DEPLOYMENT_ALLOC_HEALTH:
        events = [
            _deployment_event(
                state, index, payload.get("deployment_id", ""),
                "DeploymentAllocHealth",
                {
                    "Healthy": list(payload.get("healthy_ids") or []),
                    "Unhealthy": list(payload.get("unhealthy_ids") or []),
                },
            )
        ]
        events.extend(
            _eval_events(index, [payload["eval"]] if payload.get("eval") else [])
        )
        return events
    if msg_type == DEPLOYMENT_DELETE:
        return [
            _deployment_event(
                state, index, did, "DeploymentDeleted", {},
                deployment=(pre or {}).get(did),
            )
            for did in payload.get("deployment_ids") or []
        ]
    # config/ACL/vault/periodic-launch entries carry no stream events
    # (ACL/vault payloads are sensitive; the rest are operator plumbing,
    # matching the reference's 7-topic surface)
    return []
