"""HTTP API server: the /v1/* surface (ref command/agent/http.go:150-222).

Blocking queries are supported via ?index=N&wait=DUR on list endpoints, the
same long-poll contract the reference exposes. JSON in/out; the model's
canonical dict encoding is the wire format.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, unquote, urlparse

from .. import metrics
from ..core.overload import (
    DeadlineExceeded,
    ErrOverloaded,
    current_deadline,
    deadline_remaining_s,
    deadline_scope,
    mint_deadline,
    retry_budget,
)
from ..jobspec.hcl import parse_duration
from ..raft import NotLeaderError
from ..structs.model import Allocation, Job
from ..testing import faults as _faults

#: total wall budget a cross-region (or leader) forward may spend
#: retrying through an election/partition before surfacing the error
FORWARD_RETRY_DEADLINE_S = float(
    os.environ.get("NOMAD_TPU_FWD_DEADLINE_S", "5.0")
)
#: error substrings that mean "the target cluster is mid-transition"
#: (election in flight, stale routing) rather than "the request is bad" —
#: the retryable class. Every entry is an EXPLICIT handler refusal: the
#: remote answered without executing, so re-sending cannot double-apply
#: a non-idempotent write (dispatch mints a new child job per call).
#: "timed out" is deliberately absent — a hop that answers "my inner
#: forward timed out" has an indeterminate outcome beyond it.
_TRANSIENT_FORWARD_ERRORS = (
    "not the leader",
    # the inner leader-forward loop's terminal wrapper: with ambiguous
    # outcomes surfaced separately as "forward outcome unknown", this
    # message only ever wraps explicit refusals, so another peer may
    # safely re-fire the request
    "leader forward failed after",
    "forwarding loop",
    "no route to it",
    "no path to region",
    "region link",
)


def _transient_forward_error(message: str) -> bool:
    msg = str(message)
    return any(s in msg for s in _TRANSIENT_FORWARD_ERRORS)


def _pre_send_failure(e: Exception) -> bool:
    """True when the transport error provably happened BEFORE the request
    was sent (dial refused / unreachable), so a retry cannot double-apply.
    Ambiguous failures — timeouts, resets mid-exchange — return False and
    must surface: the remote may have executed the write."""
    import urllib.error

    if isinstance(e, ConnectionRefusedError):
        return True
    if isinstance(e, urllib.error.URLError) and not isinstance(
        e, urllib.error.HTTPError
    ):
        return isinstance(e.reason, ConnectionRefusedError)
    return False

def _request_priority(body):
    """Eval priority the submitted work will run at, when the body carries
    one (job register/dispatch payloads), else None — the admission
    controller's priority-aware shedding classifies on it (system >
    service > batch, core/overload.py)."""
    if isinstance(body, dict):
        job = body.get("Job")
        if isinstance(job, dict):
            # the wire format is snake_case (Job.to_dict); "Priority" is
            # accepted too for reference-API-shaped clients
            pri = job.get("priority", job.get("Priority"))
            if pri is not None:
                try:
                    return int(pri)
                except (TypeError, ValueError):
                    pass
    return None


_ROUTES: list[tuple[str, re.Pattern, str, object]] = []

# route ACL specs (ref nomad/acl.go per-endpoint checks; http routes carry
# the capability they require): "anonymous" = open, "ns:<capability>" =
# namespace capability from the request's namespace, "node:read|write",
# "agent:read|write", "operator:read|write"; None = management-only (the
# safe default for unannotated routes when ACLs are enabled)


def _acl_allows(acl, spec, query) -> bool:
    if spec == "anonymous":
        return True
    if acl is None:
        return False
    if acl.management:
        return True
    if spec is None:
        return False
    if callable(spec):
        return bool(spec(acl, query))
    if spec.startswith("ns:"):
        ns = query.get("namespace", "default")
        if ns == "*":
            # wildcard lists: allowed when any namespace grants the
            # capability; handlers filter the results per object
            return acl.allow_capability_any_namespace(spec[3:])
        return acl.allow_namespace_operation(ns, spec[3:])
    domain, _, level = spec.partition(":")
    checks = {
        ("node", "read"): lambda: acl.allow_node_read(),
        ("node", "write"): lambda: acl.allow_node_write(),
        ("agent", "read"): lambda: acl.allow_agent_read(),
        ("agent", "write"): lambda: acl.allow_agent_write(),
        ("operator", "read"): lambda: acl.allow_operator_read(),
        ("operator", "write"): lambda: acl.allow_operator_write(),
    }
    check = checks.get((domain, level))
    return bool(check and check())


class RawResponse:
    """A handler result served verbatim instead of as JSON (the metrics
    endpoint's prometheus exposition, http.go's formatted responses)."""

    def __init__(self, content_type: str, body: bytes):
        self.content_type = content_type
        self.body = body


class _DecodedMatch:
    """Percent-decodes captured path segments so derived child job IDs
    (``<id>/periodic-<ts>``, ``<id>/dispatch-<ts>-<uuid>``) resolve when
    clients encode the embedded '/' (ref http.go uses mux vars similarly)."""

    def __init__(self, match: re.Match):
        self._match = match

    def group(self, *args):
        g = self._match.group(*args)
        if isinstance(g, tuple):
            return tuple(unquote(x) if x else x for x in g)
        return unquote(g) if g else g

    def __getitem__(self, key):
        g = self._match[key]
        return unquote(g) if g else g


def route(method: str, pattern: str, acl=None):
    compiled = re.compile("^" + pattern + "$")

    def deco(fn):
        _ROUTES.append((method, compiled, fn.__name__, acl))
        return fn

    return deco


class HTTPServer:
    """Wraps a Server (and optionally clients) with the HTTP surface."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 4646, agent=None):
        self.server = server
        self.agent = agent
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        #: shared fan-out pump for chunked event streams (events/mux.py);
        #: created on the first stream, stopped with the server. The lock
        #: guards the lazy init: two first-ever streams racing (exactly
        #: the fan-out ramp pattern) must not each build a mux — the
        #: loser's pump thread and adopted sockets would escape stop()
        self._stream_mux = None
        self._stream_mux_lock = threading.Lock()
        #: sockets handed to the stream mux: the per-request teardown
        #: (shutdown_request) must leave them alone — the mux owns their
        #: lifecycle now. Weak so a mux-closed socket drops out by itself.
        import weakref

        self._detached_socks = weakref.WeakSet()

    def _mint_request_deadline(self, headers, query) -> int:
        """Mint the request's wall-clock deadline (unix ns; 0 = none).

        Precedence: an explicit ``X-Nomad-Deadline: <seconds>`` header
        always wins (honored even without an overload stanza — it is an
        explicit per-request opt-in). With the overload plane configured,
        ``?wait=<dur>`` doubles as the deadline (a blocking caller gone
        after its wait is work nobody collects), then the stanza's
        ``default_deadline_s``. Without the stanza those two mint nothing
        — the A/B contract keeps pre-overload behavior byte-identical."""
        hdr = headers.get("X-Nomad-Deadline")
        if hdr:
            try:
                ttl = float(hdr)
                if ttl > 0:
                    return mint_deadline(ttl)
            except ValueError:
                pass
        ov = getattr(self.server, "overload", None) if self.server else None
        if ov is None:
            return 0
        if query.get("wait"):
            try:
                ttl = parse_duration(query["wait"]) / 1e9
                if ttl > 0:
                    return mint_deadline(ttl)
            except (ValueError, TypeError):
                pass
        if ov.default_deadline_s > 0:
            return mint_deadline(ov.default_deadline_s)
        return 0

    def start(self):
        from ..util import LogBuffer

        LogBuffer.install()  # capture logs from agent start for /monitor
        api = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _dispatch(self, method):
                parsed = urlparse(self.path)
                # the web UI (ref command/agent/http.go:211 serving /ui/)
                if method == "GET" and parsed.path in ("/", "/ui", "/ui/"):
                    from ..ui import INDEX_HTML

                    data = INDEX_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                # cluster event stream: long-lived push connection, served
                # outside the request/response route table (ref
                # command/agent/event_endpoint.go). Handles both chunked
                # HTTP and a websocket upgrade on the same path.
                if method == "GET" and parsed.path == "/v1/event/stream":
                    self.close_connection = True
                    try:
                        api._serve_event_stream(self, parsed, query)
                    except OSError:
                        pass
                    except Exception as e:
                        try:
                            self._respond(500, {"error": str(e)}, None)
                        except OSError:
                            pass
                    return
                # websocket upgrade: the interactive exec surface
                # (ref command/agent/alloc_endpoint.go execStream)
                if (
                    method == "GET"
                    and "websocket"
                    in self.headers.get("Upgrade", "").lower()
                ):
                    ws_m = re.match(
                        r"^/v1/client/allocation/([^/]+)/exec$", parsed.path
                    )
                    if ws_m:
                        server = api.server
                        if server is not None and server.acl_enabled():
                            # browsers can't set headers on a ws dial;
                            # accept the token as a query param too
                            secret = self.headers.get(
                                "X-Nomad-Token", ""
                            ) or query.get("token", "")
                            try:
                                acl_obj = server.resolve_token(secret)
                            except PermissionError as e:
                                self._respond(403, {"error": str(e)}, None)
                                return
                            except NotLeaderError as e:
                                # ws dials can't be proxied here; surface
                                # a retryable error, not a false 403
                                self._respond(
                                    500,
                                    {"error": f"not the leader ({e})"},
                                    None,
                                )
                                return
                            if not _acl_allows(
                                acl_obj, "ns:alloc-exec", query
                            ):
                                self._respond(
                                    403, {"error": "Permission denied"}, None
                                )
                                return
                            query["__acl__"] = acl_obj
                        self.close_connection = True
                        try:
                            api._serve_exec_ws(self, ws_m.group(1), query)
                        except KeyError as e:
                            try:
                                self._respond(404, {"error": str(e)}, None)
                            except OSError:
                                pass
                        except ValueError as e:
                            try:
                                self._respond(400, {"error": str(e)}, None)
                            except OSError:
                                pass
                        except PermissionError as e:
                            # the fine-grained per-resource namespace check
                            # (the coarse gate above used caller-chosen
                            # ?namespace=) — still a clean 403
                            try:
                                self._respond(403, {"error": str(e)}, None)
                            except OSError:
                                pass
                        except OSError:
                            pass
                        except Exception as e:
                            # RpcError (hosting node unreachable) and
                            # friends: a diagnosable 502, not a traceback
                            try:
                                self._respond(502, {"error": str(e)}, None)
                            except OSError:
                                pass
                        return
                body = None
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    raw = self.rfile.read(length)
                    try:
                        body = json.loads(raw)
                    except json.JSONDecodeError:
                        body = raw.decode()
                # region forwarding (ref rpc.go forward() + region tables):
                # a request naming another region proxies to a server there
                region = query.get("region")
                if (
                    region
                    and api.server is not None
                    and region != getattr(api.server, "region", region)
                ):
                    self._forward_region(method, region, parsed, query, body)
                    return
                for m, pattern, name, acl_spec in _ROUTES:
                    if m != method:
                        continue
                    match = pattern.match(parsed.path)
                    if match:
                        server = api.server
                        acl_obj = None
                        if server is not None and server.acl_enabled():
                            secret = self.headers.get("X-Nomad-Token", "")
                            try:
                                acl_obj = server.resolve_token(secret)
                            except PermissionError as e:
                                self._respond(403, {"error": str(e)}, None)
                                return
                            except NotLeaderError as e:
                                # a token miss on a follower is not
                                # authoritative (its table may lag a
                                # restart or replication round): the
                                # leader re-resolves and serves
                                self._forward_leader(
                                    method, e, parsed, query, body
                                )
                                return
                            if not _acl_allows(acl_obj, acl_spec, query):
                                self._respond(
                                    403, {"error": "Permission denied"}, None
                                )
                                return
                        # reserved key: handlers needing finer-grained
                        # checks (search's per-context filtering) read it
                        query["__acl__"] = acl_obj
                        query["__secret__"] = self.headers.get(
                            "X-Nomad-Token", ""
                        )
                        # bounded accept (the overload plane): mutating
                        # requests pass priority-aware admission BEFORE
                        # any handler work — reject-early with 429 +
                        # Retry-After keeps queues short instead of
                        # metastable. Reads stay open (they are how
                        # operators see an overloaded cluster).
                        if (
                            method != "GET"
                            and server is not None
                            and getattr(server, "overload", None) is not None
                        ):
                            try:
                                server.overload.admit_request(
                                    _request_priority(body)
                                )
                            except ErrOverloaded as e:
                                self._respond_overloaded(e)
                                return
                        try:
                            dl_ns = api._mint_request_deadline(
                                self.headers, query
                            )
                            if dl_ns and time.time_ns() >= dl_ns:
                                raise DeadlineExceeded(
                                    "request deadline exceeded before "
                                    "dispatch",
                                    where="http",
                                )
                            trace_hdr = self.headers.get("X-Nomad-Trace")
                            # the deadline scope makes the deadline
                            # visible to everything downstream of the
                            # handler — eval creation stamps it, and
                            # ConnPool forwards it on any remote hop
                            with deadline_scope(dl_ns):
                                if trace_hdr:
                                    # forwarded-request propagation: the
                                    # proxying hop's span context rides
                                    # the header so this handler's spans
                                    # join the submitter's tree (cross-
                                    # region critical paths are one
                                    # retained trace)
                                    from ..trace import tracer

                                    ctx = None
                                    try:
                                        ctx = tracer.ctx_from_annotation(
                                            json.loads(trace_hdr)
                                        )
                                    except Exception:
                                        pass
                                    with tracer.activate(ctx):
                                        result, index = getattr(api, name)(
                                            _DecodedMatch(match), query, body
                                        )
                                else:
                                    result, index = getattr(api, name)(
                                        _DecodedMatch(match), query, body
                                    )
                            if isinstance(result, RawResponse):
                                data = result.body
                                self.send_response(200)
                                self.send_header("Content-Type", result.content_type)
                                self.send_header("Content-Length", str(len(data)))
                                self.end_headers()
                                self.wfile.write(data)
                                return
                            self._respond(200, result, index)
                        except ErrOverloaded as e:
                            # an in-process handler (or the RPC tier under
                            # it) shed the work mid-flight
                            self._respond_overloaded(e)
                        except DeadlineExceeded as e:
                            # loud terminal outcome, never a silent drop:
                            # 504 carries the refusing stage in the body
                            self._respond(
                                504,
                                {
                                    "error": str(e),
                                    "code": "deadline_exceeded",
                                    "where": getattr(e, "where", "")
                                    or "http",
                                },
                                None,
                            )
                        except KeyError as e:
                            self._respond(404, {"error": str(e)}, None)
                        except PermissionError as e:
                            self._respond(403, {"error": str(e)}, None)
                        except ValueError as e:
                            self._respond(400, {"error": str(e)}, None)
                        except NotLeaderError as e:
                            # a write landed on a follower: proxy to the
                            # leader's HTTP surface (the reference forwards
                            # the RPC internally, rpc.go forward())
                            self._forward_leader(
                                method, e, parsed, query, body
                            )
                        except Exception as e:
                            self._respond(500, {"error": str(e)}, None)
                        return
                self._respond(404, {"error": f"no handler for {parsed.path}"}, None)

            def _forward_leader(self, method, err, parsed, query, body):
                """Proxy the request to the raft leader's HTTP address (ref
                nomad/rpc.go:280-340 forward()). The address resolves from
                gossip tags or static config when present, else over the
                server RPC tier (Status.HTTPAddr at the leader's raft
                address, which every voter knows) — so forwarding works in
                voters-only topologies with no gossip configured."""
                # bounded hop count: leadership can move while a forward
                # is in flight (old leader forwards onward), but a cycle
                # must terminate (the reference bounds forwardLeader the
                # same way)
                try:
                    ttl = int(self.headers.get("X-Nomad-Forward-TTL") or 2)
                except ValueError:
                    ttl = 0
                if ttl <= 0:
                    self._respond(
                        500,
                        {"error": f"forwarding loop: not the leader ({err})"},
                        None,
                    )
                    return
                from .client import APIError, ApiClient

                path = parsed.path + (
                    "?" + parsed.query if parsed.query else ""
                )
                # retry-with-backoff through the election: the leader
                # hint is only trusted on the first attempt (it may name
                # the peer that just died); later attempts re-resolve
                # from live raft state, so the re-elected leader is found
                # as soon as a quorum knows it. Writes on this surface
                # are idempotent upserts, so a retry after a flushed-but-
                # failed hop cannot double-apply.
                deadline = time.monotonic() + FORWARD_RETRY_DEADLINE_S
                backoff = 0.05
                attempt = 0
                last_err = str(err)
                while True:
                    if attempt == 0:
                        leader_id = getattr(err, "leader_id", None) or getattr(
                            api.server.raft, "leader_id", None
                        )
                        leader_rpc = getattr(err, "leader_addr", None) or (
                            api.server.raft.leader_address()
                        )
                    else:
                        leader_id = getattr(api.server.raft, "leader_id", None)
                        leader_rpc = api.server.raft.leader_address()
                    target = (
                        api.server.resolve_server_http_addr(
                            leader_id, leader_rpc
                        )
                        if leader_rpc or leader_id
                        else None
                    )
                    if target:
                        proxy = ApiClient(
                            address=target,
                            token=self.headers.get("X-Nomad-Token") or "",
                        )
                        try:
                            payload, index = proxy._request(
                                method, path, body=body,
                                headers=self._forward_headers(ttl - 1),
                            )
                            self._respond(200, payload, index)
                            return
                        except APIError as e:
                            if not _transient_forward_error(str(e)):
                                self._respond(e.status, {"error": str(e)}, None)
                                return
                            last_err = str(e)
                        except Exception as e:
                            # a stale address (peer restarted onto a new
                            # HTTP port) must not wedge forwarding forever
                            # — quarantine it so the next resolution
                            # consults the live sources
                            api.server.forget_server_http_addr(
                                leader_rpc, target
                            )
                            if not _pre_send_failure(e):
                                # ambiguous transport failure: the hop may
                                # have executed the write — surfacing is
                                # the only double-apply-safe answer
                                self._respond(
                                    500,
                                    {
                                        "error": "leader forward outcome "
                                        f"unknown: {e}"
                                    },
                                    None,
                                )
                                return
                            last_err = f"{type(e).__name__}: {e}"
                    else:
                        last_err = f"no route to leader ({err})"
                    attempt += 1
                    if time.monotonic() + backoff > deadline:
                        break
                    # forward retries ride the process-wide retry budget
                    # (core/overload.py) with the rpc ladders: when the
                    # bucket is dry, fail fast instead of amplifying
                    if not retry_budget().try_acquire():
                        metrics.incr("http.leader_forward.budget_exhausted")
                        break
                    metrics.incr("http.leader_forward.retry")
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 0.5)
                metrics.incr("http.leader_forward.failed")
                self._respond(
                    500,
                    {
                        "error": "leader forward failed after "
                        f"{attempt + 1} attempts: {last_err}"
                    },
                    None,
                )

            def _forward_headers(self, ttl: int) -> dict:
                """Headers every proxy hop carries: the loop-bounding TTL
                plus the active trace context (when sampled), so the
                remote handler's spans — job.submit included — parent
                under this hop and the cross-region critical path is ONE
                retained tree."""
                headers = {"X-Nomad-Forward-TTL": str(ttl)}
                try:
                    from ..trace import tracer

                    ctx = tracer.current()
                    if ctx is not None and ctx.sampled:
                        headers["X-Nomad-Trace"] = json.dumps(ctx.to_dict())
                except Exception:
                    pass
                # deadline propagation across proxy hops: carry the
                # REMAINING budget (the header's unit is seconds-from-now)
                # so the remote hop re-mints the same absolute deadline
                dl = current_deadline()
                if dl:
                    rem = deadline_remaining_s(dl)
                    if rem is not None and rem > 0:
                        headers["X-Nomad-Deadline"] = f"{rem:.3f}"
                return headers

            def _forward_region(self, method, region, parsed, query, body):
                """Proxy the request to a server in ``region`` (ref
                rpc.go forward() + region tables), retrying with backoff
                through remote elections and stale routing: losing the
                remote leader mid-call must not surface a transient
                error to the submitter. Each attempt re-reads the gossip
                forwarding table and rotates peers; only the recognized
                transient error class retries (writes on this surface
                are idempotent upserts, so a retried hop cannot
                double-apply). The inter-region fault seam
                (testing/faults.py region scope) gates every attempt —
                a partitioned link fails here exactly like a dead WAN."""
                from .client import APIError, ApiClient

                self_region = getattr(api.server, "region", "global")
                path = parsed.path + ("?" + parsed.query if parsed.query else "")
                span_cm = None
                try:
                    from ..trace import tracer

                    # the forward hop is the trace ROOT when the request
                    # arrived untraced (the cross-region submit surface),
                    # a child span when a context is already active
                    opener = (
                        tracer.span if tracer.current() is not None
                        else tracer.root
                    )
                    span_cm = opener(
                        "http.region_forward",
                        tags={"src": self_region, "dst": region},
                    )
                    span_cm.__enter__()
                except Exception:
                    span_cm = None
                try:
                    self._forward_region_inner(
                        method, region, self_region, path, body,
                        ApiClient, APIError,
                    )
                finally:
                    if span_cm is not None:
                        span_cm.__exit__(None, None, None)

            def _forward_region_inner(
                self, method, region, self_region, path, body,
                ApiClient, APIError,
            ):
                deadline = time.monotonic() + FORWARD_RETRY_DEADLINE_S
                backoff = 0.05
                attempt = 0
                last_err = f"no path to region {region!r}"
                while True:
                    severed = _faults.region_link(
                        self_region, region, "http.forward"
                    ) in ("drop", "sever")
                    if severed:
                        last_err = (
                            f"region link {self_region}->{region} severed"
                        )
                        metrics.incr("http.region_forward.severed")
                    else:
                        peers = api.server.region_http_servers(region)
                        if peers:
                            proxy = ApiClient(
                                address=peers[attempt % len(peers)],
                                token=self.headers.get("X-Nomad-Token") or "",
                            )
                            try:
                                payload, index = proxy._request(
                                    method, path, body=body,
                                    headers=self._forward_headers(2),
                                )
                                metrics.incr("http.region_forward.ok")
                                self._respond(200, payload, index)
                                return
                            except APIError as e:
                                if not _transient_forward_error(str(e)):
                                    self._respond(
                                        e.status, {"error": str(e)}, None
                                    )
                                    return
                                last_err = str(e)
                            except Exception as e:
                                if not _pre_send_failure(e):
                                    # ambiguous transport failure: the
                                    # remote may have executed the write
                                    # (dispatch mints a child per call) —
                                    # only a provably-unsent request is
                                    # safe to re-fire
                                    self._respond(
                                        500,
                                        {
                                            "error": "region forward to "
                                            f"{region!r} outcome "
                                            f"unknown: {e}"
                                        },
                                        None,
                                    )
                                    return
                                last_err = f"{type(e).__name__}: {e}"
                        else:
                            last_err = f"no path to region {region!r}"
                    attempt += 1
                    if time.monotonic() + backoff > deadline:
                        break
                    # same shared retry budget as the leader-forward loop
                    # and the rpc client ladders: bounded amplification
                    if not retry_budget().try_acquire():
                        metrics.incr("http.region_forward.budget_exhausted")
                        break
                    metrics.incr("http.region_forward.retry")
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 0.5)
                metrics.incr("http.region_forward.failed")
                self._respond(
                    500,
                    {
                        "error": f"region forward to {region!r} failed "
                        f"after {attempt + 1} attempts: {last_err}"
                    },
                    None,
                )

            def _respond_overloaded(self, e):
                """429 + Retry-After: the shed-work contract. The body
                carries the machine-readable code and the same hint so
                non-header-aware clients can pace themselves too."""
                retry_after = float(getattr(e, "retry_after", 1.0) or 1.0)
                data = json.dumps(
                    {
                        "error": str(e),
                        "code": "overloaded",
                        "retry_after": retry_after,
                    }
                ).encode()
                self.send_response(429)
                self.send_header("Content-Type", "application/json")
                self.send_header(
                    "Retry-After", str(max(1, int(retry_after)))
                )
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _respond(self, code, payload, index):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                if index is not None:
                    self.send_header("X-Nomad-Index", str(index))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._dispatch("GET")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_POST(self):
                self._dispatch("PUT")  # POST == PUT (ref http.go)

            def do_DELETE(self):
                self._dispatch("DELETE")

        class _Httpd(ThreadingHTTPServer):
            # production fan-out ramps thousands of stream dials in
            # bursts; the default listen backlog of 5 sheds them
            request_queue_size = 512

            def shutdown_request(self, request):
                # an event-stream socket adopted by the mux outlives its
                # request: the handler thread returns but the connection
                # keeps streaming. One-shot — after the skip the mux is
                # the only owner.
                if request in api._detached_socks:
                    api._detached_socks.discard(request)
                    return
                super().shutdown_request(request)

        self._httpd = _Httpd((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="http-serve"
        )
        self._thread.start()
        if self.server is not None and hasattr(self.server, "advertise_http"):
            # publish our HTTP address for cross-region forwarding
            self.server.advertise_http(self.address)

    def stop(self):
        if self._stream_mux is not None:
            self._stream_mux.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        pool = getattr(self, "_fs_pool", None)
        if pool is not None:
            pool.close()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------------------------
    def _ns_visible(self, query, obj_ns: str, capability: str) -> bool:
        """List-endpoint namespace scoping: exact match normally, or — for
        ?namespace=* wildcard lists — every namespace the token holds the
        capability in (ref the reference's wildcard namespace handling)."""
        ns = query.get("namespace", "default")
        if ns != "*":
            return obj_ns == ns
        acl = query.get("__acl__")
        return acl is None or acl.allow_namespace_operation(obj_ns, capability)

    # ------------------------------------------------------------------
    def _blocking(self, query, run):
        """Shared blocking-query plumbing (?index=N&wait=D).

        Deadline-aware park: a minted request deadline shorter than
        ``?wait=`` clamps the park so the query un-parks AT the deadline
        with a terminal ``deadline_exceeded (blocking_query)`` instead of
        holding the connection past it. Without an active deadline the
        park is exactly the pre-overload ``?wait=`` behavior."""
        min_index = int(query.get("index", 0))
        if min_index:
            wait = parse_duration(query.get("wait", "5m")) / 1e9
            dl = current_deadline()
            clamped = False
            if dl:
                rem = deadline_remaining_s(dl)
                if rem is not None and rem < wait:
                    wait = max(rem, 0.0)
                    clamped = True
            result, index = self.server.state.blocking_query(
                run, min_index=min_index, timeout=wait
            )
            if clamped and index <= min_index:
                # the park was cut short by the deadline, not woken by
                # data: loud terminal outcome, attributed to this stage
                metrics.incr("overload.deadline_exceeded.blocking_query")
                ov = getattr(self.server, "overload", None)
                if ov is not None:
                    ov.note_deadline_exceeded("blocking_query")
                raise DeadlineExceeded(
                    "deadline expired while blocked on index "
                    f"{min_index}",
                    where="blocking_query",
                )
            return result, index
        snap = self.server.state.snapshot()
        return run(snap), snap.latest_index()

    # -- jobs ----------------------------------------------------------
    @route("GET", r"/v1/jobs", acl="ns:list-jobs")
    def list_jobs(self, m, query, body):
        prefix = query.get("prefix", "")

        def run(snap):
            return [
                {
                    "ID": j.id,
                    "Name": j.name,
                    "Type": j.type,
                    "Priority": j.priority,
                    "Status": j.status,
                    "JobModifyIndex": j.job_modify_index,
                }
                for j in snap.jobs()
                if j.id.startswith(prefix)
                and self._ns_visible(query, j.namespace, "list-jobs")
            ]

        return self._blocking(query, run)

    @route("PUT", r"/v1/jobs", acl="ns:submit-job")
    def register_job(self, m, query, body):
        from ..trace import tracer

        if not isinstance(body, dict) or "Job" not in body:
            raise ValueError("request must contain a Job")
        job = Job.from_dict(body["Job"])
        self._apply_request_ns(query, job)
        self._check_ns(query, job.namespace, "submit-job")
        # mint the trace at HTTP submit: the created eval adopts this
        # context (Server._adopt_eval_trace), so the retained tree runs
        # submit → broker → worker → device → plan → fsm. A
        # request forwarded from another region arrives with an active
        # context (X-Nomad-Trace) — then job.submit is a child span and
        # the cross-region hop stays one tree
        opener = (
            tracer.span if tracer.current() is not None else tracer.root
        )
        with opener("job.submit", tags={"job": job.id}):
            eval_id = self.server.job_register(job)
        return {"EvalID": eval_id, "JobModifyIndex": self.server.state.latest_index()}, None

    @route("GET", r"/v1/job/(?P<job_id>[^/]+)", acl="ns:read-job")
    def get_job(self, m, query, body):
        def run(snap):
            job = snap.job_by_id(query.get("namespace", "default"), m["job_id"])
            if job is None:
                raise KeyError(f"job not found: {m['job_id']}")
            return job.to_dict()

        return self._blocking(query, run)

    @route("DELETE", r"/v1/job/(?P<job_id>[^/]+)", acl="ns:submit-job")
    def deregister_job(self, m, query, body):
        purge = query.get("purge", "false") == "true"
        eval_id = self.server.job_deregister(
            query.get("namespace", "default"), m["job_id"], purge=purge
        )
        return {"EvalID": eval_id}, None

    @route("PUT", r"/v1/job/(?P<job_id>[^/]+)/plan", acl="ns:submit-job")
    def plan_job(self, m, query, body):
        """Dry-run: annotated placement plan + structural diff, no state
        mutation (ref job_endpoint.go Plan, command/job_plan.go)."""
        if not isinstance(body, dict) or "Job" not in body:
            raise ValueError("request must contain a Job")
        job = Job.from_dict(body["Job"])
        self._apply_request_ns(query, job)
        self._check_ns(query, job.namespace, "submit-job")
        result = self.server.job_plan(job, diff=bool(body.get("Diff", True)))
        return {
            "Annotations": result["annotations"],
            "FailedTGAllocs": result["failed_tg_allocs"],
            "Diff": result["diff"],
            "JobModifyIndex": result["job_modify_index"],
        }, None

    @route("GET", r"/v1/job/(?P<job_id>[^/]+)/allocations", acl="ns:read-job")
    def job_allocations(self, m, query, body):
        def run(snap):
            return [
                _alloc_stub(a)
                for a in snap.allocs_by_job(
                    query.get("namespace", "default"), m["job_id"]
                )
            ]

        return self._blocking(query, run)

    @route("GET", r"/v1/job/(?P<job_id>[^/]+)/evaluations", acl="ns:read-job")
    def job_evaluations(self, m, query, body):
        def run(snap):
            return [
                e.to_dict()
                for e in snap.evals_by_job(
                    query.get("namespace", "default"), m["job_id"]
                )
            ]

        return self._blocking(query, run)

    @route("GET", r"/v1/job/(?P<job_id>[^/]+)/summary", acl="ns:read-job")
    def job_summary(self, m, query, body):
        def run(snap):
            s = snap.job_summary_by_id(query.get("namespace", "default"), m["job_id"])
            if s is None:
                raise KeyError(f"job summary not found: {m['job_id']}")
            return s.to_dict()

        return self._blocking(query, run)

    @route("GET", r"/v1/job/(?P<job_id>[^/]+)/deployments", acl="ns:read-job")
    def job_deployments(self, m, query, body):
        def run(snap):
            return [
                d.to_dict()
                for d in snap.deployments_by_job(
                    query.get("namespace", "default"), m["job_id"]
                )
            ]

        return self._blocking(query, run)

    @route("GET", r"/v1/job/(?P<job_id>[^/]+)/versions", acl="ns:read-job")
    def job_versions(self, m, query, body):
        def run(snap):
            return [
                j.to_dict()
                for j in snap.job_versions(
                    query.get("namespace", "default"), m["job_id"]
                )
            ]

        return self._blocking(query, run)

    # -- nodes ----------------------------------------------------------
    @route("GET", r"/v1/nodes", acl="node:read")
    def list_nodes(self, m, query, body):
        def run(snap):
            return [
                {
                    "ID": n.id,
                    "Name": n.name,
                    "Datacenter": n.datacenter,
                    "NodeClass": n.node_class,
                    "Status": n.status,
                    "Drain": n.drain,
                    "SchedulingEligibility": n.scheduling_eligibility,
                }
                for n in snap.nodes()
            ]

        return self._blocking(query, run)

    @route("GET", r"/v1/node/(?P<node_id>[^/]+)", acl="node:read")
    def get_node(self, m, query, body):
        def run(snap):
            node = snap.node_by_id(m["node_id"]) or next(
                iter(snap.node_by_prefix(m["node_id"])), None
            )
            if node is None:
                raise KeyError(f"node not found: {m['node_id']}")
            doc = node.to_dict()
            # the node secret authenticates its client RPC; never serve it
            # (the reference redacts SecretID from API responses)
            doc.pop("secret_id", None)
            return doc

        return self._blocking(query, run)

    @route("GET", r"/v1/node/(?P<node_id>[^/]+)/allocations", acl="node:read")
    def node_allocations(self, m, query, body):
        def run(snap):
            return [_alloc_stub(a) for a in snap.allocs_by_node(m["node_id"])]

        return self._blocking(query, run)

    @route("PUT", r"/v1/node/(?P<node_id>[^/]+)/drain", acl="node:write")
    def node_drain(self, m, query, body):
        body = body or {}
        spec = body.get("DrainSpec")
        # a present-but-empty spec means enable-with-defaults (the
        # reference distinguishes nil vs non-nil DrainSpec)
        if spec is not None:
            self.server.node_drain(
                m["node_id"],
                True,
                deadline_ns=int(spec.get("Deadline", 0)),
                ignore_system_jobs=bool(spec.get("IgnoreSystemJobs", False)),
            )
        else:
            self.server.node_drain(
                m["node_id"], False, mark_eligible=body.get("MarkEligible")
            )
        return {"NodeModifyIndex": self.server.state.latest_index()}, None

    @route("PUT", r"/v1/node/(?P<node_id>[^/]+)/eligibility", acl="node:write")
    def node_eligibility(self, m, query, body):
        elig = (body or {}).get("Eligibility", "eligible")
        self.server.node_update_eligibility(m["node_id"], elig)
        return {"NodeModifyIndex": self.server.state.latest_index()}, None

    # -- allocations -----------------------------------------------------
    @route("GET", r"/v1/allocations", acl="ns:read-job")
    def list_allocations(self, m, query, body):
        prefix = query.get("prefix", "")

        def run(snap):
            return [
                _alloc_stub(a)
                for a in snap.allocs()
                if a.id.startswith(prefix)
                and self._ns_visible(query, a.namespace, "read-job")
            ]

        return self._blocking(query, run)

    @route("GET", r"/v1/allocation/(?P<alloc_id>[^/]+)", acl="ns:read-job")
    def get_allocation(self, m, query, body):
        def run(snap):
            alloc = snap.alloc_by_id(m["alloc_id"])
            if alloc is None:
                matches = [
                    a for a in snap.allocs() if a.id.startswith(m["alloc_id"])
                ]
                alloc = matches[0] if len(matches) == 1 else None
            if alloc is None:
                raise KeyError(f"alloc not found: {m['alloc_id']}")
            self._check_ns(query, alloc.namespace, "read-job")
            return alloc.to_dict()

        return self._blocking(query, run)

    # -- evaluations -----------------------------------------------------
    @route("GET", r"/v1/evaluations", acl="ns:read-job")
    def list_evaluations(self, m, query, body):
        def run(snap):
            return [
                e.to_dict()
                for e in snap.evals()
                if self._ns_visible(query, e.namespace, "read-job")
            ]

        return self._blocking(query, run)

    @route("GET", r"/v1/evaluation/(?P<eval_id>[^/]+)", acl="ns:read-job")
    def get_evaluation(self, m, query, body):
        def run(snap):
            ev = snap.eval_by_id(m["eval_id"])
            if ev is None:
                matches = [
                    e for e in snap.evals() if e.id.startswith(m["eval_id"])
                ]
                ev = matches[0] if len(matches) == 1 else None
            if ev is None:
                raise KeyError(f"eval not found: {m['eval_id']}")
            self._check_ns(query, ev.namespace, "read-job")
            return ev.to_dict()

        return self._blocking(query, run)

    @route("GET", r"/v1/deployments", acl="ns:read-job")
    def list_deployments(self, m, query, body):
        def run(snap):
            return [
                d.to_dict()
                for d in snap.deployments()
                if self._ns_visible(query, d.namespace, "read-job")
            ]

        return self._blocking(query, run)

    @route("GET", r"/v1/deployment/(?P<deploy_id>[^/]+)", acl="ns:read-job")
    def get_deployment(self, m, query, body):
        def run(snap):
            d = snap.deployment_by_id(m["deploy_id"])
            if d is None:
                # prefix match, like the reference's prefix-tolerant lookups
                matches = [
                    x for x in snap.deployments()
                    if x.id.startswith(m["deploy_id"])
                ]
                if len(matches) == 1:
                    d = matches[0]
            if d is None:
                raise KeyError(f"deployment not found: {m['deploy_id']}")
            self._check_ns(query, d.namespace, "read-job")
            return d.to_dict()

        return self._blocking(query, run)

    @route("GET", r"/v1/deployment/allocations/(?P<deploy_id>[^/]+)", acl="ns:read-job")
    def deployment_allocations(self, m, query, body):
        def run(snap):
            d = snap.deployment_by_id(m["deploy_id"])
            if d is not None:
                self._check_ns(query, d.namespace, "read-job")
            return [
                _alloc_stub(a) for a in snap.allocs_by_deployment(m["deploy_id"])
            ]

        return self._blocking(query, run)

    @route("PUT", r"/v1/deployment/promote/(?P<deploy_id>[^/]+)", acl="ns:submit-job")
    def deployment_promote(self, m, query, body):
        body = body or {}
        self._check_deployment_ns(query, m["deploy_id"], "submit-job")
        self.server.deployment_promote(
            m["deploy_id"],
            groups=body.get("Groups"),
            all_groups=body.get("All", not body.get("Groups")),
        )
        return {"DeploymentModifyIndex": self.server.state.latest_index()}, None

    @route("PUT", r"/v1/deployment/fail/(?P<deploy_id>[^/]+)", acl="ns:submit-job")
    def deployment_fail(self, m, query, body):
        self._check_deployment_ns(query, m["deploy_id"], "submit-job")
        self.server.deployment_fail(m["deploy_id"])
        return {"DeploymentModifyIndex": self.server.state.latest_index()}, None

    @route("PUT", r"/v1/deployment/pause/(?P<deploy_id>[^/]+)", acl="ns:submit-job")
    def deployment_pause(self, m, query, body):
        self._check_deployment_ns(query, m["deploy_id"], "submit-job")
        pause = bool((body or {}).get("Pause", True))
        self.server.deployment_pause(m["deploy_id"], pause)
        return {"DeploymentModifyIndex": self.server.state.latest_index()}, None

    @route("PUT", r"/v1/deployment/allocation-health/(?P<deploy_id>[^/]+)", acl="ns:submit-job")
    def deployment_alloc_health(self, m, query, body):
        self._check_deployment_ns(query, m["deploy_id"], "submit-job")
        body = body or {}
        self.server.deployment_set_alloc_health(
            m["deploy_id"],
            healthy_ids=body.get("HealthyAllocationIDs", []),
            unhealthy_ids=body.get("UnhealthyAllocationIDs", []),
        )
        return {"DeploymentModifyIndex": self.server.state.latest_index()}, None

    @route("PUT", r"/v1/job/(?P<job_id>[^/]+)/dispatch", acl="ns:dispatch-job")
    def job_dispatch(self, m, query, body):
        body = body or {}
        import base64 as _b64

        payload = body.get("Payload", "")
        if payload:
            try:
                payload = _b64.b64decode(payload).decode()
            except Exception:
                pass  # accept raw strings too
        out = self.server.job_dispatch(
            query.get("namespace", "default"),
            m["job_id"],
            payload=payload,
            meta=body.get("Meta") or {},
        )
        return out, None

    @route("PUT", r"/v1/job/(?P<job_id>[^/]+)/periodic/force", acl="ns:submit-job")
    def job_periodic_force(self, m, query, body):
        child_id = self.server.periodic_force(
            query.get("namespace", "default"), m["job_id"]
        )
        return {"DispatchedJobID": child_id}, None

    @route("PUT", r"/v1/job/(?P<job_id>[^/]+)/revert", acl="ns:submit-job")
    def job_revert(self, m, query, body):
        body = body or {}
        eval_id = self.server.job_revert(
            query.get("namespace", "default"),
            m["job_id"],
            int(body.get("JobVersion", 0)),
            enforce_prior_version=body.get("EnforcePriorVersion"),
        )
        return {"EvalID": eval_id}, None

    # -- agent / status --------------------------------------------------
    @route("GET", r"/v1/agent/self", acl="agent:read")
    def agent_self(self, m, query, body):
        clients = []
        if self.agent is not None:
            clients = [c.node.id for c in getattr(self.agent, "clients", [])]

        def jsonable(v):
            try:
                json.dumps(v)
                return True
            except (TypeError, ValueError):
                return False

        return (
            {
                # live wiring (raft transport/log-store handles) rides in
                # config in networked mode — serve only the plain values
                "config": {
                    k: v
                    for k, v in self.server.config.items()
                    if k != "raft" and jsonable(v)
                },
                "stats": {
                    "broker": self.server.eval_broker.stats(),
                    "blocked_evals": self.server.blocked_evals.stats(),
                },
                "member": {
                    "Name": self.server.raft.node_id,
                    "Status": "alive",
                    "rpc_addr": self.server.raft.address,
                    "is_leader": self.server.raft.is_leader(),
                },
                "clients": clients,
            },
            None,
        )

    # -- services (a nomad-native service catalog: the reference registers
    # workload services into Consul, command/agent/consul/ — here the same
    # service/check data is served straight from cluster state) ----------
    def _service_entries(self, snap, query, name_filter=None):
        out = []
        for alloc in snap.allocs():
            if alloc.terminal_status() or not self._ns_visible(
                query, alloc.namespace, "read-job"
            ):
                continue
            # Connect sidecar listeners published by the owning client
            for svc_name, ep in (alloc.connect_proxies or {}).items():
                sidecar_name = f"{svc_name}-sidecar-proxy"
                if name_filter and sidecar_name != name_filter:
                    continue
                out.append(
                    {
                        "ServiceName": sidecar_name,
                        "Tags": ["connect-proxy"],
                        "AllocID": alloc.id,
                        "JobID": alloc.job_id,
                        "NodeID": alloc.node_id,
                        "Address": ep.get("ip", ""),
                        "Port": int(ep.get("port", 0)),
                        "Status": "passing",
                        "Checks": {},
                    }
                )
            job = alloc.job
            tg = job.lookup_task_group(alloc.task_group) if job else None
            if tg is None:
                continue
            for task in tg.tasks:
                state = alloc.task_states.get(task.name)
                healthy = state is not None and state.state == "running"
                # check results published by the client's check runner
                # override the coarse is-it-running signal
                checks = dict(state.check_status) if state is not None else {}
                if healthy and any(v != "passing" for v in checks.values()):
                    healthy = False
                for svc in task.services:
                    if name_filter and svc.name != name_filter:
                        continue
                    address, port = "", 0
                    resources = alloc.allocated_resources
                    tr = (
                        resources.tasks.get(task.name)
                        if resources is not None
                        else None
                    )
                    if tr is not None and svc.port_label:
                        for net in tr.networks:
                            for p in list(net.reserved_ports) + list(
                                net.dynamic_ports
                            ):
                                if p.label == svc.port_label:
                                    address, port = net.ip, p.value
                    out.append(
                        {
                            "ServiceName": svc.name,
                            "Tags": list(svc.tags),
                            "AllocID": alloc.id,
                            "JobID": alloc.job_id,
                            "NodeID": alloc.node_id,
                            "Address": address,
                            "Port": port,
                            "Status": "passing" if healthy else "critical",
                            "Checks": checks,
                        }
                    )
        return out

    @route("GET", r"/v1/services", acl="ns:read-job")
    def list_services(self, m, query, body):
        def run(snap):
            return self._service_entries(snap, query)

        return self._blocking(query, run)

    @route("GET", r"/v1/service/(?P<name>[^/]+)", acl="ns:read-job")
    def get_service(self, m, query, body):
        def run(snap):
            entries = self._service_entries(snap, query, name_filter=m["name"])
            if not entries:
                raise KeyError(f"service not found: {m['name']}")
            return entries

        return self._blocking(query, run)

    @route("GET", r"/v1/regions", acl="anonymous")
    def list_regions(self, m, query, body):
        """ref nomad/regions_endpoint.go List"""
        return self.server.regions(), None

    @route("GET", r"/v1/status/leader", acl="anonymous")
    def status_leader(self, m, query, body):
        """ref status_endpoint.go Leader: the raft leader's RPC address
        (NOT this agent's HTTP address — any member answers with the same
        cluster-wide value)."""
        return self.server.leader_address() or "", None

    @route("GET", r"/v1/status/peers", acl="anonymous")
    def status_peers(self, m, query, body):
        """ref status_endpoint.go Peers"""
        return sorted(self.server.raft.voters_snapshot().values()), None

    @route("GET", r"/v1/agent/members", acl="agent:read")
    def agent_members(self, m, query, body):
        """ref agent_endpoint.go AgentMembersRequest"""
        return {
            "ServerName": self.server.raft.node_id,
            "ServerRegion": self.server.region,
            "Members": self.server.members(),
        }, None

    @route("PUT", r"/v1/agent/join", acl="agent:write")
    def agent_join(self, m, query, body):
        """ref agent_endpoint.go AgentJoinRequest"""
        addresses = []
        if query.get("address"):
            addresses.append(query["address"])
        if isinstance(body, dict) and body.get("Addresses"):
            addresses.extend(body["Addresses"])
        if not addresses:
            raise ValueError("missing address to join")
        joined = self.server.gossip_join(addresses)
        return {"num_joined": joined}, None

    @route("PUT", r"/v1/agent/force-leave", acl="agent:write")
    def agent_force_leave(self, m, query, body):
        """ref agent_endpoint.go AgentForceLeaveRequest"""
        node = query.get("node") or (body or {}).get("Node")
        if not node:
            raise ValueError("missing node to force leave")
        if not self.server.gossip_force_leave(node):
            raise KeyError(f"unknown member: {node}")
        return {}, None

    @route("GET", r"/v1/agent/servers", acl="agent:read")
    def agent_servers(self, m, query, body):
        """ref agent_endpoint.go AgentServersRequest"""
        return sorted(self.server.raft.voters_snapshot().values()), None

    @route("PUT", r"/v1/agent/keyring/(?P<op>install|use|remove|list)", acl="agent:write")
    def agent_keyring(self, m, query, body):
        """Gossip keyring management (ref agent keyring API + serf
        keyring): install/use/remove a base64 key, or list the ring."""
        gossip = getattr(self.server, "gossip", None)
        keyring = getattr(gossip, "keyring", None) if gossip else None
        if keyring is None:
            raise ValueError("gossip encryption is not enabled on this agent")
        op = m["op"]
        if op == "list":
            return keyring.list_keys(), None
        key = (body or {}).get("Key", "")
        if not key:
            raise ValueError("missing Key")
        if op == "install":
            keyring.install(key)
        elif op == "use":
            keyring.use(key)
        elif op == "remove":
            keyring.remove(key)
        return keyring.list_keys(), None

    @route("GET", r"/v1/agent/health", acl="anonymous")
    def agent_health(self, m, query, body):
        """ref agent_endpoint.go HealthRequest"""
        out = {}
        if self.server is not None:
            leader = self.server.leader_address() is not None
            out["server"] = {
                "ok": True,
                "message": "leader elected" if leader else "no leader",
            }
        clients = getattr(self.agent, "clients", []) if self.agent else []
        if clients:
            out["client"] = {"ok": True, "message": f"{len(clients)} client(s)"}
        return out, None

    @route("PUT", r"/v1/job/(?P<job_id>[^/]+)/evaluate", acl="ns:read-job")
    def job_evaluate(self, m, query, body):
        """ref job_endpoint.go Evaluate / api PUT /v1/job/:id/evaluate"""
        body = body or {}
        opts = body.get("EvalOptions") or {}
        eval_id = self.server.job_evaluate(
            query.get("namespace", "default"),
            m["job_id"],
            force_reschedule=bool(opts.get("ForceReschedule")),
        )
        return {"EvalID": eval_id}, None

    @route("GET", r"/v1/agent/monitor", acl="agent:read")
    def agent_monitor(self, m, query, body):
        """Recent agent log records after ?index= (poll-follow analog of
        the reference's streaming monitor endpoint)."""
        from ..util import LogBuffer

        buf = LogBuffer.install()
        entries, index = buf.since(int(query.get("index", 0)))
        level = query.get("log_level", "").upper()
        if level:
            order = ["DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL"]
            if level in order:
                keep = set(order[order.index(level):])
                entries = [e for e in entries if e["level"] in keep]
        return {"Entries": entries, "Index": index}, None

    @route("PUT", r"/v1/jobs/parse", acl="anonymous")
    def jobs_parse(self, m, query, body):
        """HCL jobspec → canonical job document (ref command/agent
        job_endpoint.go JobsParseRequest): lets non-HCL clients submit
        specs they got from users."""
        from ..jobspec import parse_job

        body = body or {}
        hcl = body.get("JobHCL", "")
        if not hcl:
            raise ValueError("request must contain JobHCL")
        return parse_job(hcl).to_dict(), None

    @route("PUT", r"/v1/client/gc", acl="node:write")
    def client_gc(self, m, query, body):
        """Force the local client's alloc-dir GC (ref client_endpoint.go
        GarbageCollect): reclaims every retained terminal alloc dir."""
        clients = []
        if self.agent is not None:
            clients = getattr(self.agent, "clients", None) or [
                getattr(self.agent, "client", None)
            ]
        reclaimed = 0
        for client in clients:
            if client is None:
                continue
            retained, client._terminal_alloc_dirs = (
                client._terminal_alloc_dirs,
                [],
            )
            for alloc_id in retained:
                client._reclaim_alloc_dir(alloc_id)
                reclaimed += 1
        if not clients:
            raise KeyError("this agent runs no client")
        return {"Reclaimed": reclaimed}, None

    def _check_debug_enabled(self):
        if not self.server.config.get("enable_debug"):
            raise PermissionError("debug endpoints are disabled (enable_debug)")

    @route("GET", r"/debug/pprof/(?P<profile>[a-z]*)", acl="agent:read")
    def debug_pprof(self, m, query, body):
        """Runtime introspection (the Go pprof handlers' role,
        http.go:218-222), gated on enable_debug exactly like the
        reference. ``/debug/pprof/`` (and any non-``profile`` name)
        keeps the original one-shot thread-stacks+gc shape;
        ``/debug/pprof/profile?seconds=N`` runs the debug plane's
        sampling wall-clock profiler (Go CPU-profile parity) and
        returns its folded-stack report."""
        self._check_debug_enabled()
        from ..debug import profiler as dbg_profiler

        if m["profile"] == "profile":
            seconds = min(max(float(query.get("seconds", "1")), 0.05), 30.0)
            hz = min(max(float(query.get("hz", "100")), 1.0), 1000.0)
            return dbg_profiler.profile(seconds, hz=hz), None
        return dbg_profiler.thread_dump(), None

    @route("GET", r"/v1/debug/bundle", acl="agent:read")
    def debug_bundle(self, m, query, body):
        """`nomad operator debug` over HTTP: capture a full debug
        bundle (profiles, flight-recorder dump, slowest traces,
        metrics, redacted config, findings) and stream it back as a
        gzip tarball (default) or inline JSON (?format=json). Gated on
        enable_debug like the pprof routes."""
        self._check_debug_enabled()
        import json as json_mod
        import os
        import tempfile

        from ..debug import bundle as bundle_mod

        seconds = min(max(float(query.get("seconds", "1")), 0.0), 30.0)
        with tempfile.TemporaryDirectory(prefix="nomad-tpu-debug-") as tmp:
            dest = os.path.join(tmp, "bundle")
            manifest = bundle_mod.capture_bundle(
                self.server, dest, profile_seconds=seconds, reason="http"
            )
            if query.get("format") == "json":
                files = {}
                for fn in manifest["files"]:
                    with open(os.path.join(dest, fn), encoding="utf-8") as f:
                        raw = f.read()
                    files[fn] = (
                        json_mod.loads(raw) if fn.endswith(".json") else raw
                    )
                return {"manifest": manifest, "files": files}, None
            tar_path = os.path.join(tmp, "bundle.tar.gz")
            bundle_mod.make_tarball(dest, tar_path)
            with open(tar_path, "rb") as f:
                data = f.read()
        return RawResponse("application/gzip", data), None

    @route("PUT", r"/v1/validate/job", acl="ns:submit-job")
    def validate_job(self, m, query, body):
        """Dry validation without registering (ref job_endpoint.go
        Validate / command/agent/job_endpoint.go ValidateJobRequest)."""
        if not isinstance(body, dict) or "Job" not in body:
            raise ValueError("request must contain a Job")
        errors = []
        warnings = []
        try:
            job = Job.from_dict(body["Job"])
            self._apply_request_ns(query, job)
            self.server._validate_job(job)
        except (ValueError, KeyError, TypeError) as e:
            errors.append(str(e))
        return {
            "DriverConfigValidated": True,
            "ValidationErrors": errors,
            "Warnings": "; ".join(warnings),
            "Error": errors[0] if errors else "",
        }, None

    @route("PUT", r"/v1/system/reconcile/summaries", acl="operator:write")
    def system_reconcile_summaries(self, m, query, body):
        """ref system_endpoint.go ReconcileJobSummaries"""
        self.server.reconcile_summaries()
        return {}, None

    @route("PUT", r"/v1/node/(?P<node_id>[^/]+)/purge", acl="node:write")
    def node_purge(self, m, query, body):
        """ref node_endpoint.go Deregister (purge)"""
        eval_ids = self.server.node_purge(m["node_id"])
        return {
            "EvalIDs": eval_ids,
            "NodeModifyIndex": self.server.state.latest_index(),
        }, None

    @route("GET", r"/v1/evaluation/(?P<eval_id>[^/]+)/allocations", acl="ns:read-job")
    def eval_allocations(self, m, query, body):
        """ref eval_endpoint.go Allocations"""
        def run(snap):
            return [
                a.to_dict()
                for a in snap.allocs_by_eval(m["eval_id"])
                if self._ns_visible(query, a.namespace, "read-job")
            ]

        return self._blocking(query, run)

    # -- operator raft / autopilot (ref operator_endpoint.go) ------------
    @route("GET", r"/v1/operator/raft/configuration", acl="operator:read")
    def operator_raft_configuration(self, m, query, body):
        return self.server.raft_configuration(), None

    @route("DELETE", r"/v1/operator/raft/peer", acl="operator:write")
    def operator_raft_remove_peer(self, m, query, body):
        peer = query.get("id") or query.get("address")
        if not peer:
            raise ValueError("missing peer id")
        # accept either a node id or its raft address
        voters = self.server.raft.voters_snapshot()
        if peer not in voters:
            by_addr = [
                nid for nid, addr in voters.items() if addr == peer
            ]
            if len(by_addr) == 1:
                peer = by_addr[0]
        self.server.raft_remove_peer(peer)
        return {}, None

    @route("GET", r"/v1/operator/autopilot/configuration", acl="operator:read")
    def operator_autopilot_get(self, m, query, body):
        return self.server.autopilot_config(), None

    @route("PUT", r"/v1/operator/autopilot/configuration", acl="operator:write")
    def operator_autopilot_set(self, m, query, body):
        overrides = dict(self.server.state.autopilot_config() or {})
        overrides.update(body or {})
        self.server.set_autopilot_config(overrides)
        return {"Updated": True}, None

    @route("GET", r"/v1/operator/autopilot/health", acl="operator:read")
    def operator_autopilot_health(self, m, query, body):
        return self.server.autopilot_health(), None

    # -- trace plane (OBSERVABILITY.md): per-eval span trees + the
    # critical-path attribution of eval.e2e. critical-path registers
    # BEFORE the <trace_id> route — matching is first-registered-wins --
    @route("GET", r"/v1/trace/critical-path", acl="agent:read")
    def trace_critical_path(self, m, query, body):
        from ..trace import attribute, tracer

        tail = float(query.get("tail", "0.99"))
        return attribute(tracer.store.records(), tail_pct=tail), None

    @route("GET", r"/v1/trace", acl="agent:read")
    def trace_list(self, m, query, body):
        from ..trace import tracer

        limit = min(int(query.get("limit", "50")), 500)
        return {
            "traces": tracer.store.list(
                limit=limit,
                slowest=query.get("slowest") in ("1", "true"),
                errors=query.get("errors") in ("1", "true"),
            ),
            "stats": tracer.stats(),
        }, None

    @route("GET", r"/v1/trace/(?P<trace_id>[^/]+)", acl="agent:read")
    def trace_get(self, m, query, body):
        from ..trace import orphan_count, tracer

        record = tracer.store.get(m["trace_id"])
        if record is None:
            raise KeyError(f"trace not found: {m['trace_id']}")
        record["orphans"] = orphan_count(record)
        return record, None

    @route("GET", r"/v1/metrics", acl="agent:read")
    def metrics(self, m, query, body):
        from ..tpu import batch_sched
        from ..tpu import drain as drain_mod

        from .. import metrics as metrics_mod
        from ..trace import tracer as _tracer

        # job-summary gauges (ref leader.go:602 publishJobSummaryMetrics)
        summaries = {}
        for s in self.server.state.job_summaries():
            rollup = {}
            for tg_name, tg in s.summary.items():
                rollup[tg_name] = {
                    "queued": tg.queued,
                    "running": tg.running,
                    "starting": tg.starting,
                    "complete": tg.complete,
                    "failed": tg.failed,
                    "lost": tg.lost,
                }
            summaries[s.job_id] = rollup

        payload = {
            "broker": self.server.eval_broker.stats(),
            "blocked_evals": self.server.blocked_evals.stats(),
            "event_broker": (
                self.server.event_broker.stats()
                if self.server.event_broker is not None
                else {}
            ),
            "plan_queue_depth": self.server.planner.queue.depth(),
            "state_index": self.server.state.latest_index(),
            # per-stage timers + counters (the go-metrics MeasureSince role)
            "stages": metrics_mod.snapshot(),
            "job_summary": summaries,
            # kernel-vs-oracle routing (VERDICT r1 weak #10): how many
            # evals rode the TPU path, by mode, and why the rest didn't
            "tpu_scheduler": batch_sched.counters_snapshot(),
            "drain": dict(drain_mod.DRAIN_COUNTERS),
            # committed-plane mirror view (tpu/mirror.py): sync hits and
            # node-axis view refreshes; rebuilds are structurally 0 —
            # the planes are patched by the store's own write commits
            "tpu_mirror": (
                self.server.columnar_mirror.stats()
                if getattr(self.server, "columnar_mirror", None) is not None
                else {}
            ),
            # trace plane retention/sampling state (nomad_tpu/trace)
            "trace": _tracer.stats(),
            # overload control plane (core/overload.py): load signal,
            # admitted/shed by class, deadline_exceeded ledger by stage,
            # brownout level — {} when the stanza is off
            "overload": (
                self.server.overload.stats()
                if getattr(self.server, "overload", None) is not None
                else {}
            ),
        }
        # device plane (debug/devprof.py): compile ledger + collective
        # census + transfer totals + round counters. jax-free reads —
        # resolving pending round scalars is is_ready-gated, so a
        # metrics poll can never stall behind an in-flight kernel.
        try:
            from ..debug import devprof as _devprof

            payload["tpu_devprof"] = _devprof.snapshot()
        except Exception:
            payload["tpu_devprof"] = {}
        # debug plane health (nomad_tpu/debug): flight-recorder depth +
        # watchdog trip counts — the operator's "is the tape running"
        recorder = getattr(self.server, "flight_recorder", None)
        watchdog = getattr(self.server, "watchdog", None)
        payload["debug"] = {
            "flight_recorded": (
                recorder.depth() if recorder is not None else 0
            ),
            "watchdog_trips": (
                watchdog.trip_count if watchdog is not None else 0
            ),
        }
        if query.get("format") == "prometheus":
            # text exposition (the reference's prometheus telemetry sink,
            # config.go:500-577 / /v1/metrics?format=prometheus)
            lines = []

            def emit(prefix, value):
                if isinstance(value, dict):
                    for k, v in value.items():
                        key = str(k).replace("-", "_").replace(".", "_")
                        emit(f"{prefix}_{key}", v)
                elif isinstance(value, (int, float)) and not isinstance(value, bool):
                    lines.append(f"# TYPE {prefix} gauge")
                    lines.append(f"{prefix} {value}")

            emit("nomad_tpu", payload)
            return RawResponse(
                "text/plain; version=0.0.4",
                ("\n".join(lines) + "\n").encode(),
            ), None
        return payload, None

    @route("PUT", r"/v1/system/gc", acl="operator:write")
    def system_gc(self, m, query, body):
        """Force-GC all eligible terminal objects
        (ref system_endpoint.go GarbageCollect)."""
        self.server.system_gc()
        return {}, None

    # -- client fs/logs/exec (ref command/agent/fs_endpoint.go +
    # client_fs_endpoint.go; served by the agent holding the alloc — the
    # in-process analog of the server→client streaming-RPC forwarding) ---
    def _alloc_dir(self, alloc_id: str) -> str:
        import os

        clients = []
        if self.agent is not None:
            clients = getattr(self.agent, "clients", None) or [
                getattr(self.agent, "client", None)
            ]
        for client in clients:
            if client is None:
                continue
            d = os.path.join(client.data_dir, "allocs", alloc_id)
            if os.path.isdir(d):
                return d
        raise KeyError(f"alloc dir not found for {alloc_id}")

    @staticmethod
    def _apply_request_ns(query, job):
        """A job spec that doesn't name a namespace registers into the
        request's (?namespace= / CLI -namespace); an explicit spec
        namespace wins and is ACL-re-checked either way."""
        ns = query.get("namespace", "default")
        if job.namespace == "default" and ns not in ("default", "*"):
            job.namespace = ns

    def _check_deployment_ns(self, query, deploy_id: str, capability: str):
        d = self.server.state.deployment_by_id(deploy_id) if self.server else None
        if d is not None:
            self._check_ns(query, d.namespace, capability)

    def _check_ns(self, query, namespace: str, capability: str):
        """Re-check the capability against the RESOURCE's namespace: the
        route gate used the caller-chosen ?namespace=, and trusting it
        would let a token scoped to one namespace act on another's
        resources (the cross-namespace escalation class)."""
        acl = query.get("__acl__")
        if acl is None:
            return
        if not acl.allow_namespace_operation(namespace, capability):
            raise PermissionError("Permission denied")

    def _check_alloc_ns(self, query, alloc_id: str, capability: str):
        alloc = self.server.state.alloc_by_id(alloc_id) if self.server else None
        if alloc is not None:
            self._check_ns(query, alloc.namespace, capability)

    def _forward_client_fs(self, alloc_id: str, method: str, payload: dict):
        """The alloc lives on a remote node: forward over the node's
        advertised client RPC listener (client_fs_endpoint.go's
        server→client path)."""
        server = self.server
        alloc = server.state.alloc_by_id(alloc_id) if server else None
        if alloc is None:
            raise KeyError(f"alloc not found: {alloc_id}")
        node = server.state.node_by_id(alloc.node_id)
        return self._forward_client_node(
            node, method, dict(payload, alloc_id=alloc_id)
        )

    def _forward_client_node(self, node, method: str, payload: dict):
        """Forward an RPC to a specific node's client listener (the
        node-addressed variant used by client stats)."""
        addr = (
            node.attributes.get("unique.advertise.client_rpc")
            if node is not None
            else None
        )
        if not addr:
            raise KeyError(
                "target node has no advertised client RPC address"
            )
        from ..rpc import ConnPool, RpcError

        pool = getattr(self, "_fs_pool", None)
        if pool is None:
            # mTLS rides along when the cluster runs with TLS
            pool = self._fs_pool = ConnPool(
                tls_context=getattr(self.server, "tls_client_context", None)
            )
        # the node secret authenticates us to the client's RPC listener
        payload = dict(payload, secret=node.secret_id)
        # socket timeout must outlast the operation's own timeout
        timeout = float(payload.get("timeout", 0) or 0) + 15.0
        try:
            return pool.call(addr, method, payload, timeout=timeout)
        except RpcError as e:
            # preserve status semantics across the forwarding boundary
            if e.code == "not_found":
                raise KeyError(e.message) from e
            if e.code == "invalid":
                raise ValueError(e.message) from e
            raise

    @route("GET", r"/v1/client/fs/ls/(?P<alloc_id>[^/]+)", acl="ns:read-fs")
    def fs_ls(self, m, query, body):
        from ..client import fs

        self._check_alloc_ns(query, m["alloc_id"], "read-fs")
        path = query.get("path", "/")
        try:
            base = self._alloc_dir(m["alloc_id"])
        except KeyError:
            return self._forward_client_fs(
                m["alloc_id"], "ClientFS.List", {"path": path}
            ), None
        return fs.list_dir(base, path), None

    @route("GET", r"/v1/client/fs/cat/(?P<alloc_id>[^/]+)", acl="ns:read-fs")
    def fs_cat(self, m, query, body):
        from ..client import fs

        self._check_alloc_ns(query, m["alloc_id"], "read-fs")
        params = {
            "path": query.get("path", "/"),
            "offset": int(query.get("offset", 0)),
            "limit": int(query.get("limit", 1 << 20)),
        }
        try:
            base = self._alloc_dir(m["alloc_id"])
        except KeyError:
            return self._forward_client_fs(
                m["alloc_id"], "ClientFS.Cat", params
            ), None
        return fs.cat(base, **params), None

    @route("GET", r"/v1/client/fs/logs/(?P<alloc_id>[^/]+)", acl="ns:read-logs")
    def fs_logs(self, m, query, body):
        """Task log window: ?task=&type=stdout|stderr&offset=&origin=
        (the non-streaming core of fs_endpoint.go Logs; clients follow by
        polling with the returned offset)."""
        from ..client import fs

        task = query.get("task", "")
        if not task:
            raise ValueError("task is required")
        self._check_alloc_ns(query, m["alloc_id"], "read-logs")
        kind = query.get("type", "stdout")
        offset = int(query.get("offset", 0))
        origin = query.get("origin", "start")
        limit = int(query.get("limit", 1 << 20))
        try:
            base = self._alloc_dir(m["alloc_id"])
        except KeyError:
            return self._forward_client_fs(
                m["alloc_id"],
                "ClientFS.Logs",
                {
                    "task": task, "type": kind, "offset": offset,
                    "origin": origin, "limit": limit,
                },
            ), None
        return fs.logs(
            base, task, kind, offset=offset, origin=origin, limit=limit
        ), None

    @route("PUT", r"/v1/client/exec/(?P<alloc_id>[^/]+)", acl="ns:alloc-exec")
    def alloc_exec(self, m, query, body):
        """One-shot command in the task's working directory
        (ref alloc exec; the reference's interactive streaming session is
        served here as a run-to-completion exec with captured output)."""
        from ..client import fs

        body = body or {}
        task = body.get("Task", "")
        cmd = body.get("Cmd") or []
        if not task or not cmd:
            raise ValueError("Task and Cmd are required")
        self._check_alloc_ns(query, m["alloc_id"], "alloc-exec")
        timeout = float(body.get("Timeout", 30.0))
        try:
            base = self._alloc_dir(m["alloc_id"])
        except KeyError:
            return self._forward_client_fs(
                m["alloc_id"],
                "ClientFS.Exec",
                {"task": task, "cmd": cmd, "timeout": timeout},
            ), None
        return fs.exec_in(base, task, cmd, timeout=timeout), None

    def _serve_exec_ws(self, handler, alloc_id: str, query: dict):
        """Interactive exec over a websocket (ref command/agent/
        alloc_endpoint.go execStream; api/allocations.go Exec): JSON
        frames — {"stdin":{"data":b64}} / {"stdin":{"close":true}} /
        {"tty_size":{"height":H,"width":W}} up, {"stdout"/"stderr":
        {"data":b64}} and {"exited":true,"result":{"exit_code":N}} down.
        Local allocs bridge straight to the driver; remote allocs ride the
        server's duplex RPC forward to the hosting node."""
        import base64
        import threading as threading_mod

        from ..rpc.mux import StreamClosed, StreamError, pipe_streams
        from . import ws as ws_mod

        task = query.get("task", "")
        try:
            cmd = json.loads(query.get("command", "[]"))
        except json.JSONDecodeError:
            raise ValueError("command must be a JSON array")
        if not isinstance(cmd, list) or not cmd:
            raise ValueError("command is required")
        tty = str(query.get("tty", "false")).lower() in ("true", "1")
        self._check_alloc_ns(query, alloc_id, "alloc-exec")

        # resolve the exec source BEFORE upgrading, so failures are
        # ordinary HTTP errors rather than a dead websocket
        client = self._local_client_with_alloc(alloc_id)
        if client is not None:
            from ..client.execstream import bridge_exec

            proc = client.exec_session(alloc_id, task, cmd, tty=tty)
            stream, remote = pipe_streams()
            threading_mod.Thread(
                target=bridge_exec, args=(proc, remote), daemon=True,
                name="exec-ws-bridge",
            ).start()
        else:
            stream = self.server.open_client_exec(
                alloc_id, {"task": task, "cmd": cmd, "tty": tty}
            )

        sock = ws_mod.server_handshake(handler)

        def down():
            try:
                for frame in stream:
                    if frame.get("stdout"):
                        ws_mod.send_message(sock, json.dumps({
                            "stdout": {
                                "data": base64.b64encode(
                                    frame["stdout"]
                                ).decode()
                            }
                        }))
                    if frame.get("stderr"):
                        ws_mod.send_message(sock, json.dumps({
                            "stderr": {
                                "data": base64.b64encode(
                                    frame["stderr"]
                                ).decode()
                            }
                        }))
                    if "exit" in frame:
                        ws_mod.send_message(sock, json.dumps({
                            "exited": True,
                            "result": {"exit_code": frame["exit"]},
                        }))
            except StreamError as e:
                try:
                    ws_mod.send_message(
                        sock, json.dumps({"error": str(e)})
                    )
                except OSError:
                    pass
            except OSError:
                pass
            finally:
                ws_mod.send_close(sock)

        dt = threading_mod.Thread(target=down, daemon=True, name="exec-ws-down")
        dt.start()
        try:
            while True:
                try:
                    _, payload = ws_mod.read_message(sock)
                except (ws_mod.WsClosed, OSError):
                    break
                try:
                    obj = json.loads(payload.decode())
                except (json.JSONDecodeError, UnicodeDecodeError):
                    continue
                try:
                    stdin = obj.get("stdin") or {}
                    if stdin.get("data"):
                        stream.send(
                            {"stdin": base64.b64decode(stdin["data"])}
                        )
                    if stdin.get("close"):
                        stream.send({"eof": True})
                    size = obj.get("tty_size") or {}
                    if size:
                        stream.send({
                            "resize": [
                                int(size.get("height", 24)),
                                int(size.get("width", 80)),
                            ]
                        })
                except StreamClosed:
                    break
        finally:
            # the websocket is gone (or the session ended): tear the exec
            # down fully — a half-close would leave an orphaned process
            # pumping output nowhere
            if hasattr(stream, "abort"):
                stream.abort()  # local pipe: kills the process via bridge
            else:
                stream.close(
                    {"code": "connection", "message": "websocket closed"}
                )
            dt.join(timeout=5.0)

    # -- alloc lifecycle (ref alloc_endpoint.go Stop +
    # client_alloc_endpoint.go Restart/Signal) ---------------------------
    def _local_client_with_alloc(self, alloc_id: str):
        clients = []
        if self.agent is not None:
            clients = getattr(self.agent, "clients", None) or [
                getattr(self.agent, "client", None)
            ]
        for client in clients:
            if client is not None and alloc_id in getattr(
                client, "alloc_runners", {}
            ):
                return client
        return None

    @route("PUT", r"/v1/allocation/(?P<alloc_id>[^/]+)/stop", acl="ns:alloc-lifecycle")
    def alloc_stop(self, m, query, body):
        self._check_alloc_ns(query, m["alloc_id"], "alloc-lifecycle")
        eval_id = self.server.alloc_stop(m["alloc_id"])
        return {
            "EvalID": eval_id,
            "Index": self.server.state.latest_index(),
        }, None

    @route(
        "PUT",
        r"/v1/client/allocation/(?P<alloc_id>[^/]+)/restart",
        acl="ns:alloc-lifecycle",
    )
    def alloc_restart(self, m, query, body):
        self._check_alloc_ns(query, m["alloc_id"], "alloc-lifecycle")
        task = (body or {}).get("TaskName", "") or query.get("task", "")
        client = self._local_client_with_alloc(m["alloc_id"])
        if client is not None:
            return {"tasks": client.alloc_restart(m["alloc_id"], task)}, None
        return self._forward_client_fs(
            m["alloc_id"], "ClientAllocations.Restart", {"task": task}
        ), None

    @route(
        "PUT",
        r"/v1/client/allocation/(?P<alloc_id>[^/]+)/signal",
        acl="ns:alloc-lifecycle",
    )
    def alloc_signal(self, m, query, body):
        self._check_alloc_ns(query, m["alloc_id"], "alloc-lifecycle")
        body = body or {}
        signal = body.get("Signal", "") or query.get("signal", "SIGINT")
        task = body.get("TaskName", "") or query.get("task", "")
        client = self._local_client_with_alloc(m["alloc_id"])
        if client is not None:
            return {
                "tasks": client.alloc_signal(m["alloc_id"], signal, task)
            }, None
        return self._forward_client_fs(
            m["alloc_id"],
            "ClientAllocations.Signal",
            {"signal": signal, "task": task},
        ), None

    # -- client / alloc stats (ref client_stats_endpoint.go +
    # client_alloc_endpoint.go Stats) ------------------------------------
    @route("GET", r"/v1/client/stats", acl="node:read")
    def client_stats(self, m, query, body):
        """Host stats of the local client, or of ?node_id= via forwarding."""
        node_id = query.get("node_id", "")
        clients = []
        if self.agent is not None:
            clients = getattr(self.agent, "clients", None) or [
                getattr(self.agent, "client", None)
            ]
        for client in clients:
            if client is None:
                continue
            if not node_id or client.node.id.startswith(node_id):
                return client.host_stats(), None
        if not node_id:
            raise KeyError("this agent runs no client")
        nodes = self.server.state.node_by_prefix(node_id)
        if len(nodes) != 1:
            raise KeyError(f"node not found: {node_id}")
        return self._forward_client_node(nodes[0], "ClientStats.Stats", {}), None

    @route(
        "GET",
        r"/v1/client/allocation/(?P<alloc_id>[^/]+)/stats",
        acl="ns:read-job",
    )
    def alloc_stats(self, m, query, body):
        self._check_alloc_ns(query, m["alloc_id"], "read-job")
        client = self._local_client_with_alloc(m["alloc_id"])
        if client is not None:
            return client.alloc_stats(m["alloc_id"]), None
        return self._forward_client_fs(
            m["alloc_id"], "ClientAllocations.Stats", {}
        ), None

    # -- cluster event stream (ref command/agent/event_endpoint.go +
    # nomad/stream/): newline-delimited JSON frames over chunked HTTP or
    # the same frames over a websocket upgrade. Frames:
    #   {"Index": N, "Events": [...]}    — one raft apply's events
    #   {}                               — heartbeat (idle keep-alive)
    #   {"Snapshot": true, "Index": N, "Events": [...]}
    #                                    — snapshot-on-subscribe batch:
    #                                      state objects at raft index N
    #   {"SnapshotDone": true, "Index": N}
    #                                    — snapshot complete; deltas with
    #                                      index > N follow
    #   {"LostGap": true, "Index": N}    — ring overwrote events ≤ N
    #                                      (only when snapshots are off)
    #   {"Error": msg, "ResumeIndex": N} — closed (slow consumer /
    #                                      restore / shutdown); reconnect
    #                                      with index=N
    # Every frame's JSON is encoded exactly once in the broker and shared
    # across subscribers; this layer only moves bytes. Chunked streams are
    # served by the shared StreamMux pump (events/mux.py) — the handler
    # thread detaches the socket and returns; websockets keep a thread
    # (they need a reader for pings) but ride the same wire path.
    # --------------------------------------------------------------------
    EVENT_STREAM_HEARTBEAT = 10.0

    def _serve_event_stream(self, handler, parsed, query):
        from ..events import ALL_TOPICS, BrokerLimitError, required_capability

        broker = getattr(self.server, "event_broker", None)
        if broker is None:
            handler._respond(
                400, {"error": "event broker is disabled on this agent"}, None
            )
            return
        topics: dict[str, set] = {}
        # parse_qs already percent-decoded each spec; a second unquote
        # would corrupt keys legitimately containing %xx sequences
        for spec in parse_qs(parsed.query).get("topic", []) or ["*"]:
            topic, _, key = spec.partition(":")
            if topic != "*" and topic not in ALL_TOPICS:
                handler._respond(
                    400, {"error": f"unknown event topic {topic!r}"}, None
                )
                return
            topics.setdefault(topic, set()).add(key or "*")
        try:
            from_index = int(query.get("index", 0))
        except ValueError:
            handler._respond(400, {"error": "index must be an integer"}, None)
            return
        heartbeat = self.EVENT_STREAM_HEARTBEAT
        if query.get("heartbeat"):
            try:
                heartbeat = float(query["heartbeat"])
            except ValueError:
                try:
                    heartbeat = parse_duration(query["heartbeat"]) / 1e9
                except Exception:
                    handler._respond(
                        400,
                        {"error": f"bad heartbeat {query['heartbeat']!r}"},
                        None,
                    )
                    return
        # a non-positive heartbeat would turn the frame loop into a
        # client-controlled busy-spin on a server thread
        heartbeat = max(heartbeat, 0.1)
        # brownout shed class for this stream: batch hangs up first,
        # service next, system never (core/overload.py ladder). Explicit
        # ?admission_class= wins; a numeric ?priority= maps through the
        # same bands as eval shedding; default is service. Without an
        # overload{} stanza nothing ever sheds — the knob is inert.
        from ..core.overload import CLASSES as _ADM_CLASSES
        from ..core.overload import CLASS_SERVICE, classify_priority

        adm_class = (query.get("admission_class") or "").strip().lower()
        if adm_class and adm_class not in _ADM_CLASSES:
            handler._respond(
                400,
                {"error": f"unknown admission_class {adm_class!r}"},
                None,
            )
            return
        if not adm_class:
            if query.get("priority"):
                try:
                    adm_class = classify_priority(int(query["priority"]))
                except ValueError:
                    handler._respond(
                        400,
                        {"error": "priority must be an integer"},
                        None,
                    )
                    return
            else:
                adm_class = CLASS_SERVICE
        # the stream spans all namespaces the token can read unless the
        # caller narrows it; the subscribe-time gate below must evaluate
        # against the SAME scope the subscription will cover, so the
        # wildcard is the shared default (per-event filtering still
        # re-checks each event's own namespace at delivery)
        namespace = query.get("namespace", "*")
        query["namespace"] = namespace
        acl_obj = None
        if self.server is not None and self.server.acl_enabled():
            # browsers can't set headers on EventSource/ws dials; accept
            # the token as a query param too (same rule as the exec ws)
            secret = handler.headers.get("X-Nomad-Token", "") or query.get(
                "token", ""
            )
            try:
                acl_obj = self.server.resolve_token(secret)
            except PermissionError as e:
                handler._respond(403, {"error": str(e)}, None)
                return
            except NotLeaderError as e:
                # streams aren't proxied; retryable error, not a false 403
                handler._respond(
                    500, {"error": f"not the leader ({e})"}, None
                )
                return
            # subscribe-time gate per requested topic; each delivered
            # event is re-filtered against ITS namespace. The wildcard
            # topic spans node-scoped + namespaced events, so it needs
            # the union of both capabilities.
            for topic in topics:
                wanted = ALL_TOPICS if topic == "*" else (topic,)
                for t in wanted:
                    if not _acl_allows(
                        acl_obj, required_capability(t), query
                    ):
                        handler._respond(
                            403, {"error": "Permission denied"}, None
                        )
                        return
        # snapshot-on-subscribe: explicit ?snapshot= wins; otherwise the
        # broker's configured default (event_broker{snapshot_on_subscribe},
        # on unless disabled). The broker only actually snapshots when it
        # helps — a cold subscribe or a resume past the ring's retention;
        # an in-retention resume stays a plain replay either way.
        snap_q = (query.get("snapshot") or "").strip().lower()
        if snap_q:
            want_snapshot = snap_q in ("1", "true", "yes")
        else:
            want_snapshot = broker.snapshot_on_subscribe
        try:
            sub = broker.subscribe(
                topics,
                from_index=from_index,
                acl=acl_obj,
                namespace=namespace,
                snapshot=want_snapshot,
            )
        except BrokerLimitError as e:
            handler._respond(503, {"error": str(e)}, None)
            return
        if "websocket" in handler.headers.get("Upgrade", "").lower():
            try:
                self._event_stream_ws(handler, sub, heartbeat)
            finally:
                sub.close()
            return
        # chunked tier: write the headers here, then hand the socket to
        # the shared mux and return — ownership (socket AND subscription)
        # transfers; the per-request teardown skips the detached socket
        try:
            wfile = handler.wfile
            handler.send_response(200)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Transfer-Encoding", "chunked")
            handler.send_header(
                "X-Nomad-Index", str(self.server.state.latest_index())
            )
            handler.end_headers()
            wfile.flush()
            self._detached_socks.add(handler.connection)
            self._event_mux().serve(
                handler.connection,
                sub,
                heartbeat,
                admission_class=adm_class,
            )
        except Exception:
            self._detached_socks.discard(handler.connection)
            sub.close()
            raise

    def _event_mux(self):
        """The shared chunked-stream pump, created on first use with the
        broker's frame_batch knob."""
        with self._stream_mux_lock:
            mux = self._stream_mux
            if mux is None:
                from ..events.mux import StreamMux

                broker = getattr(self.server, "event_broker", None)
                mux = self._stream_mux = StreamMux(
                    frame_batch=getattr(broker, "frame_batch", 64)
                )
                # hand the mux's shed switch to the server's brownout
                # ladder; registration replays any already-degraded
                # stream levels so a mux created mid-brownout sheds too
                srv = self.server
                if srv is not None and hasattr(
                    srv, "add_stream_shed_hook"
                ):
                    srv.add_stream_shed_hook(mux.set_class_shed)
        return mux

    def _event_stream_ws(self, handler, sub, heartbeat):
        import threading as threading_mod

        from . import ws as ws_mod

        sock = ws_mod.server_handshake(handler)

        def reader():
            # drain client frames (answers pings inside read_message);
            # a close/EOF tears the subscription down so the send loop
            # exits at its next frame instead of writing into a dead pipe
            try:
                while True:
                    ws_mod.read_message(sock)
            except (ws_mod.WsClosed, OSError):
                pass
            finally:
                sub.close()

        threading_mod.Thread(
            target=reader, daemon=True, name="event-stream-ws-reader"
        ).start()
        try:
            while True:
                # encode-once wire lines straight from the broker; one ws
                # message per NDJSON line, batched per wake
                lines, done = sub.next_wires(timeout=heartbeat)
                if not lines and not done:
                    ws_mod.send_message(sock, b"{}")  # heartbeat
                    continue
                for line in lines:
                    ws_mod.send_message(sock, line)
                if done:
                    break
        except OSError:
            pass
        finally:
            ws_mod.send_close(sock)

    # -- acl (ref acl_endpoint.go + command/agent/acl_endpoint.go) -------
    @route("PUT", r"/v1/acl/bootstrap", acl="anonymous")
    def acl_bootstrap(self, m, query, body):
        token = self.server.acl_bootstrap()
        return _acl_token_dict(token), None

    @route("GET", r"/v1/acl/policies")
    def acl_list_policies(self, m, query, body):
        return [
            {"Name": p.name, "Description": p.description}
            for p in self.server.state.acl_policies()
        ], self.server.state.latest_index()

    @route("GET", r"/v1/acl/policy/(?P<name>[^/]+)")
    def acl_get_policy(self, m, query, body):
        p = self.server.state.acl_policy_by_name(m["name"])
        if p is None:
            raise KeyError(f"policy not found: {m['name']}")
        return {
            "Name": p.name,
            "Description": p.description,
            "Rules": p.rules,
        }, None

    @route("PUT", r"/v1/acl/policy/(?P<name>[^/]+)")
    def acl_put_policy(self, m, query, body):
        from ..structs.model import AclPolicy

        body = body or {}
        policy = AclPolicy(
            name=m["name"],
            description=body.get("Description", ""),
            rules=body.get("Rules", ""),
        )
        self.server.acl_upsert_policies([policy])
        return {}, None

    @route("DELETE", r"/v1/acl/policy/(?P<name>[^/]+)")
    def acl_delete_policy(self, m, query, body):
        self.server.acl_delete_policies([m["name"]])
        return {}, None

    @route("GET", r"/v1/acl/tokens")
    def acl_list_tokens(self, m, query, body):
        return [
            {
                "AccessorID": t.accessor_id,
                "Name": t.name,
                "Type": t.type,
                "Policies": list(t.policies),
                "Global": t.global_token,
            }
            for t in self.server.state.acl_tokens()
        ], self.server.state.latest_index()

    @route("PUT", r"/v1/acl/token")
    def acl_create_token(self, m, query, body):
        from ..structs.model import AclToken

        body = body or {}
        token = AclToken(
            name=body.get("Name", ""),
            type=body.get("Type", "client"),
            policies=list(body.get("Policies", [])),
            global_token=bool(body.get("Global", False)),
        )
        token = self.server.acl_create_token(token)
        return _acl_token_dict(token), None

    @route("DELETE", r"/v1/acl/token/(?P<accessor>[^/]+)")
    def acl_delete_token(self, m, query, body):
        self.server.acl_delete_tokens([m["accessor"]])
        return {}, None

    @route("GET", r"/v1/acl/token/self", acl="anonymous")
    def acl_token_self(self, m, query, body):
        """ref acl_endpoint.go GetToken (self); resolves the request's own
        secret, so it needs no management capability."""
        secret = query.get("__secret__", "")
        token = self.server.state.acl_token_by_secret(secret)
        if token is None:
            raise KeyError("token not found for provided secret")
        return _acl_token_dict(token), None

    @route("GET", r"/v1/acl/token/(?P<accessor>[^/]+)")
    def acl_get_token(self, m, query, body):
        token = self.server.state.acl_token_by_accessor(m["accessor"])
        if token is None:
            raise KeyError(f"token not found: {m['accessor']}")
        return _acl_token_dict(token), None

    # -- search (ref search_endpoint.go) ---------------------------------
    @route("PUT", r"/v1/search", acl="ns:read-job")
    def search(self, m, query, body):
        body = body or {}
        acl = query.get("__acl__")
        return self.server.search(
            prefix=body.get("Prefix", ""),
            context=(body.get("Context") or "all"),
            namespace=query.get("namespace", "default"),
            include_nodes=acl is None or acl.allow_node_read(),
        ), self.server.state.latest_index()

    @route("GET", r"/v1/operator/scheduler/configuration", acl="operator:read")
    def get_scheduler_config(self, m, query, body):
        return self.server.state.scheduler_config() or {}, None

    @route("PUT", r"/v1/operator/scheduler/configuration", acl="operator:write")
    def set_scheduler_config(self, m, query, body):
        # Must replicate via raft like every other write (ref
        # operator_endpoint.go SchedulerSetConfiguration → raftApply):
        # a direct state write would exist only on the serving server
        # and vanish on failover.
        from ..core import fsm as fsm_mod

        self.server._apply(fsm_mod.SCHEDULER_CONFIG, {"config": body or {}})
        return {"Updated": True}, None


def _acl_token_dict(t) -> dict:
    return {
        "AccessorID": t.accessor_id,
        "SecretID": t.secret_id,
        "Name": t.name,
        "Type": t.type,
        "Policies": list(t.policies),
        "Global": t.global_token,
    }


def _alloc_stub(a: Allocation) -> dict:
    return {
        "ID": a.id,
        "Name": a.name,
        "NodeID": a.node_id,
        "JobID": a.job_id,
        "TaskGroup": a.task_group,
        "DesiredStatus": a.desired_status,
        "ClientStatus": a.client_status,
        "DeploymentStatus": (
            a.deployment_status.to_dict()
            if a.deployment_status is not None
            else None
        ),
        "CreateIndex": a.create_index,
        "ModifyIndex": a.modify_index,
    }
