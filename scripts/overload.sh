#!/usr/bin/env sh
# Overload storm entry point (nomad_tpu/loadgen/overload.py; README
# "Overload control plane" + OBSERVABILITY.md "The overload plane").
# Drives one live server PAST saturation — a capacity stage, a burst at
# OVERLOAD_BURST_X times that rate, then a recovery probe — and scores
# the overload control plane: goodput must hold past the knee, every op
# must be accounted (ok / shed / deadline_exceeded, zero real failures),
# admitted work must keep its latency budget, and recovery must complete
# inside the SLO window; exit 0 = every SLO passed.
#
#   scripts/overload.sh                          # -> OVERLOAD_r01.json
#   OVERLOAD_BURST_X=5 scripts/overload.sh       # harder burst
#   OVERLOAD_DEPTH_LIMIT=64 scripts/overload.sh  # earlier knee
#   OVERLOAD_DEADLINE_S=4 scripts/overload.sh    # tighter deadlines
#
# Scale knobs (env): OVERLOAD_NODES, OVERLOAD_CAP_RATE, OVERLOAD_CAP_S,
# OVERLOAD_BURST_X, OVERLOAD_BURST_S, OVERLOAD_DEPTH_LIMIT,
# OVERLOAD_DEADLINE_S, OVERLOAD_RECOVERY_SLO_S,
# OVERLOAD_GOODPUT_DROP_SLO, OVERLOAD_ADMITTED_P99_SLO_MS. Numbers are
# only comparable A/B on the same box (see PERF.md).
set -eu

cd "$(dirname "$0")/.."

out=""
for arg in "$@"; do
  case "$arg" in
    --out|--out=*) out="explicit" ;;
  esac
done
if [ -z "$out" ]; then
  n=1
  while [ -e "$(printf 'OVERLOAD_r%02d.json' "$n")" ]; do n=$((n + 1)); done
  set -- --out "$(printf 'OVERLOAD_r%02d.json' "$n")" "$@"
fi

exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
  python -m nomad_tpu.loadgen --overload "$@"
