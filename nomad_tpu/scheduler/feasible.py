"""Feasibility iterators and checkers (ref scheduler/feasible.go).

Constraint operand semantics are reproduced exactly (feasible.go:533-564):
``= == is != not < <= > >= version regexp set_contains{,_all,_any} is_set
is_not_set`` with lexical string comparison, cached regex/version-constraint
compilation, and the computed-node-class memoization wrapper.
"""

from __future__ import annotations

import re
from typing import Optional

from ..structs.attribute import Attribute, parse_attribute
from ..structs.model import (
    CONSTRAINT_ATTRIBUTE_IS_NOT_SET,
    CONSTRAINT_ATTRIBUTE_IS_SET,
    CONSTRAINT_DISTINCT_HOSTS,
    CONSTRAINT_DISTINCT_PROPERTY,
    CONSTRAINT_REGEX,
    CONSTRAINT_SET_CONTAINS,
    CONSTRAINT_SET_CONTAINS_ALL,
    CONSTRAINT_SET_CONTAINS_ANY,
    CONSTRAINT_VERSION,
    VOLUME_TYPE_HOST,
    Constraint,
    Job,
    Node,
    NodeDeviceResource,
    RequestedDevice,
    TaskGroup,
    VolumeRequest,
)
from .context import (
    EVAL_COMPUTED_CLASS_ELIGIBLE,
    EVAL_COMPUTED_CLASS_ESCAPED,
    EVAL_COMPUTED_CLASS_INELIGIBLE,
    EVAL_COMPUTED_CLASS_UNKNOWN,
    EvalContext,
)
from .version import Constraints, Version


# ---------------------------------------------------------------------------
# Target resolution + operand checks
# ---------------------------------------------------------------------------

def resolve_target(target: str, node: Node) -> tuple[Optional[str], bool]:
    """Resolve a constraint target against a node (ref feasible.go:496-529)."""
    if not target.startswith("${"):
        return target, True
    if target == "${node.unique.id}":
        return node.id, True
    if target == "${node.datacenter}":
        return node.datacenter, True
    if target == "${node.unique.name}":
        return node.name, True
    if target == "${node.class}":
        return node.node_class, True
    if target.startswith("${attr."):
        attr = target[len("${attr.") : -1]
        if attr in node.attributes:
            return node.attributes[attr], True
        return None, False
    if target.startswith("${meta."):
        meta = target[len("${meta.") : -1]
        if meta in node.meta:
            return node.meta[meta], True
        return None, False
    return None, False


def check_lexical_order(op: str, l_val, r_val) -> bool:
    if not isinstance(l_val, str) or not isinstance(r_val, str):
        return False
    if op == "<":
        return l_val < r_val
    if op == "<=":
        return l_val <= r_val
    if op == ">":
        return l_val > r_val
    if op == ">=":
        return l_val >= r_val
    return False


def check_version_match(ctx: EvalContext, l_val, r_val) -> bool:
    """ref feasible.go:604-643"""
    if isinstance(l_val, int):
        version_str = str(l_val)
    elif isinstance(l_val, str):
        version_str = l_val
    else:
        return False
    vers = Version.parse(version_str)
    if vers is None:
        return False
    if not isinstance(r_val, str):
        return False
    constraints = ctx.version_constraint_cache.get(r_val)
    if constraints is None:
        constraints = Constraints.parse(r_val)
        if constraints is None:
            return False
        ctx.version_constraint_cache[r_val] = constraints
    return constraints.check(vers)


def check_regexp_match(ctx: EvalContext, l_val, r_val) -> bool:
    """ref feasible.go:689-718"""
    if not isinstance(l_val, str) or not isinstance(r_val, str):
        return False
    pattern = ctx.regexp_cache.get(r_val)
    if pattern is None:
        try:
            pattern = re.compile(r_val)
        except re.error:
            return False
        ctx.regexp_cache[r_val] = pattern
    return pattern.search(l_val) is not None


def _split_set(s: str) -> set[str]:
    return {part.strip() for part in s.split(",")}


def check_set_contains_all(l_val, r_val) -> bool:
    if not isinstance(l_val, str) or not isinstance(r_val, str):
        return False
    return _split_set(r_val) <= _split_set(l_val)


def check_set_contains_any(l_val, r_val) -> bool:
    if not isinstance(l_val, str) or not isinstance(r_val, str):
        return False
    return bool(_split_set(r_val) & _split_set(l_val))


def check_constraint(
    ctx: EvalContext, operand: str, l_val, r_val, l_found: bool, r_found: bool
) -> bool:
    """ref feasible.go:533-564"""
    if operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
        return True
    if operand in ("=", "==", "is"):
        return l_found and r_found and l_val == r_val
    if operand in ("!=", "not"):
        return l_val != r_val
    if operand in ("<", "<=", ">", ">="):
        return l_found and r_found and check_lexical_order(operand, l_val, r_val)
    if operand == CONSTRAINT_ATTRIBUTE_IS_SET:
        return l_found
    if operand == CONSTRAINT_ATTRIBUTE_IS_NOT_SET:
        return not l_found
    if operand == CONSTRAINT_VERSION:
        return l_found and r_found and check_version_match(ctx, l_val, r_val)
    if operand == CONSTRAINT_REGEX:
        return l_found and r_found and check_regexp_match(ctx, l_val, r_val)
    if operand in (CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL):
        return l_found and r_found and check_set_contains_all(l_val, r_val)
    if operand == CONSTRAINT_SET_CONTAINS_ANY:
        return l_found and r_found and check_set_contains_any(l_val, r_val)
    return False


def check_affinity(ctx, operand, l_val, r_val, l_found, r_found) -> bool:
    return check_constraint(ctx, operand, l_val, r_val, l_found, r_found)


# ---------------------------------------------------------------------------
# Device attribute constraints (ref feasible.go:1007-1166)
# ---------------------------------------------------------------------------

def resolve_device_target(
    target: str, d: NodeDeviceResource
) -> tuple[Optional[Attribute], bool]:
    """ref feasible.go:1033-1059"""
    if not target.startswith("${"):
        return parse_attribute(target), True
    if target == "${device.model}":
        return Attribute.of_string(d.name), True
    if target == "${device.vendor}":
        return Attribute.of_string(d.vendor), True
    if target == "${device.type}":
        return Attribute.of_string(d.type), True
    if target.startswith("${device.attr."):
        attr = target[len("${device.attr.") : -1]
        if attr in d.attributes:
            return d.attributes[attr], True
        return None, False
    return None, False


def check_attribute_constraint(
    ctx: EvalContext,
    operand: str,
    l_val: Optional[Attribute],
    r_val: Optional[Attribute],
    l_found: bool,
    r_found: bool,
) -> bool:
    """ref feasible.go:1063-1166"""
    if operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
        return True

    if operand in ("!=", "not"):
        if not (l_found or r_found):
            return False
        if l_found != r_found:
            return True
        v, ok = l_val.compare(r_val)
        return ok and v != 0

    if operand in ("<", "<=", ">", ">=", "=", "==", "is"):
        if not (l_found and r_found):
            return False
        v, ok = l_val.compare(r_val)
        if not ok:
            return False
        return {
            "is": v == 0,
            "==": v == 0,
            "=": v == 0,
            "<": v == -1,
            "<=": v != 1,
            ">": v == 1,
            ">=": v != -1,
        }[operand]

    if operand == CONSTRAINT_VERSION:
        if not (l_found and r_found):
            return False
        ls, ok = l_val.get_string()
        if not ok:
            lv, ok2 = l_val.get_int()
            if not ok2:
                return False
            ls = str(lv)
        rs, ok = r_val.get_string()
        if not ok:
            return False
        return check_version_match(ctx, ls, rs)

    if operand == CONSTRAINT_REGEX:
        if not (l_found and r_found):
            return False
        ls, ok1 = l_val.get_string()
        rs, ok2 = r_val.get_string()
        return ok1 and ok2 and check_regexp_match(ctx, ls, rs)

    if operand in (CONSTRAINT_SET_CONTAINS, CONSTRAINT_SET_CONTAINS_ALL):
        if not (l_found and r_found):
            return False
        ls, ok1 = l_val.get_string()
        rs, ok2 = r_val.get_string()
        return ok1 and ok2 and check_set_contains_all(ls, rs)

    if operand == CONSTRAINT_SET_CONTAINS_ANY:
        if not (l_found and r_found):
            return False
        ls, ok1 = l_val.get_string()
        rs, ok2 = r_val.get_string()
        return ok1 and ok2 and check_set_contains_any(ls, rs)

    if operand == CONSTRAINT_ATTRIBUTE_IS_SET:
        return l_found
    if operand == CONSTRAINT_ATTRIBUTE_IS_NOT_SET:
        return not l_found
    return False


def check_attribute_affinity(ctx, operand, l_val, r_val, l_found, r_found) -> bool:
    return check_attribute_constraint(ctx, operand, l_val, r_val, l_found, r_found)


def node_device_matches(
    ctx: EvalContext, d: NodeDeviceResource, req: RequestedDevice
) -> bool:
    """ref feasible.go:1007-1029"""
    if not d.device_id().matches(req.device_id()):
        return False
    for c in req.constraints:
        l_val, l_ok = resolve_device_target(c.l_target, d)
        r_val, r_ok = resolve_device_target(c.r_target, d)
        if not check_attribute_constraint(ctx, c.operand, l_val, r_val, l_ok, r_ok):
            return False
    return True


# ---------------------------------------------------------------------------
# Source iterators
# ---------------------------------------------------------------------------

class StaticIterator:
    """Yields nodes in fixed order (ref feasible.go:43-97)."""

    def __init__(self, ctx: EvalContext, nodes: Optional[list[Node]]):
        self.ctx = ctx
        self.nodes = nodes or []
        self.offset = 0
        self.seen = 0

    def next(self) -> Optional[Node]:
        n = len(self.nodes)
        if self.offset == n or self.seen == n:
            if self.seen != n:
                self.offset = 0
            else:
                return None
        offset = self.offset
        self.offset += 1
        self.seen += 1
        self.ctx.metrics.evaluate_node()
        return self.nodes[offset]

    def reset(self):
        self.seen = 0

    def set_nodes(self, nodes: list[Node]):
        self.nodes = nodes
        self.offset = 0
        self.seen = 0


def new_random_iterator(ctx: EvalContext, nodes: list[Node]) -> StaticIterator:
    shuffle_nodes(ctx, nodes)
    return StaticIterator(ctx, nodes)


def shuffle_nodes(ctx: EvalContext, nodes: list[Node]):
    """In-place Fisher-Yates with the context's seeded rng
    (ref scheduler/util.go:329)."""
    for i in range(len(nodes) - 1, 0, -1):
        j = ctx.rng.randrange(i + 1)
        nodes[i], nodes[j] = nodes[j], nodes[i]


# ---------------------------------------------------------------------------
# Checkers
# ---------------------------------------------------------------------------

class HostVolumeChecker:
    """ref feasible.go:99-177"""

    def __init__(self, ctx: EvalContext):
        self.ctx = ctx
        self.volumes: dict[str, list[VolumeRequest]] = {}

    def set_volumes(self, volumes: dict[str, VolumeRequest]):
        lookup: dict[str, list[VolumeRequest]] = {}
        for req in volumes.values():
            if req.type != VOLUME_TYPE_HOST:
                continue
            lookup.setdefault(req.source, []).append(req)
        self.volumes = lookup

    def feasible(self, candidate: Node) -> bool:
        if self._has_volumes(candidate):
            return True
        self.ctx.metrics.filter_node(candidate, "missing compatible host volumes")
        return False

    def _has_volumes(self, n: Node) -> bool:
        if not self.volumes:
            return True
        if len(self.volumes) > len(n.host_volumes):
            return False
        for source, requests in self.volumes.items():
            node_volume = n.host_volumes.get(source)
            if node_volume is None:
                return False
            if not node_volume.read_only:
                continue
            for req in requests:
                if not req.read_only:
                    return False
        return True


class DriverChecker:
    """ref feasible.go:179-248"""

    def __init__(self, ctx: EvalContext, drivers: Optional[set[str]] = None):
        self.ctx = ctx
        self.drivers = drivers or set()

    def set_drivers(self, drivers: set[str]):
        self.drivers = drivers

    def feasible(self, option: Node) -> bool:
        if self._has_drivers(option):
            return True
        self.ctx.metrics.filter_node(option, "missing drivers")
        return False

    def _has_drivers(self, option: Node) -> bool:
        for driver in self.drivers:
            info = option.drivers.get(driver)
            if info is not None:
                if info.detected and info.healthy:
                    continue
                return False
            value = option.attributes.get(f"driver.{driver}")
            if value is None:
                return False
            if value.strip().lower() not in ("1", "true", "t"):
                return False
        return True


class ConstraintChecker:
    """ref feasible.go:454-493"""

    def __init__(self, ctx: EvalContext, constraints: Optional[list[Constraint]] = None):
        self.ctx = ctx
        self.constraints = constraints or []

    def set_constraints(self, constraints: list[Constraint]):
        self.constraints = constraints

    def feasible(self, option: Node) -> bool:
        for constraint in self.constraints:
            if not self._meets_constraint(constraint, option):
                self.ctx.metrics.filter_node(option, str(constraint))
                return False
        return True

    def _meets_constraint(self, constraint: Constraint, option: Node) -> bool:
        l_val, l_ok = resolve_target(constraint.l_target, option)
        r_val, r_ok = resolve_target(constraint.r_target, option)
        return check_constraint(
            self.ctx, constraint.operand, l_val, r_val, l_ok, r_ok
        )


class DeviceChecker:
    """ref feasible.go:900-1003"""

    def __init__(self, ctx: EvalContext):
        self.ctx = ctx
        self.required: list[RequestedDevice] = []
        self.requires_devices = False

    def set_task_group(self, tg: TaskGroup):
        self.required = []
        for task in tg.tasks:
            self.required.extend(task.resources.devices)
        self.requires_devices = bool(self.required)

    def feasible(self, option: Node) -> bool:
        if self._has_devices(option):
            return True
        self.ctx.metrics.filter_node(option, "missing devices")
        return False

    def _has_devices(self, option: Node) -> bool:
        if not self.requires_devices:
            return True
        if option.node_resources is None:
            return False
        node_devs = option.node_resources.devices
        if not node_devs:
            return False

        available: dict[int, tuple[NodeDeviceResource, int]] = {}
        for i, d in enumerate(node_devs):
            healthy = sum(1 for inst in d.instances if inst.healthy)
            if healthy:
                available[i] = (d, healthy)

        for req in self.required:
            desired = req.count
            matched = False
            for i, (d, unused) in available.items():
                if unused == 0 or unused < desired:
                    continue
                if node_device_matches(self.ctx, d, req):
                    available[i] = (d, unused - desired)
                    matched = True
                    break
            if not matched:
                return False
        return True


# ---------------------------------------------------------------------------
# Distinct-hosts / distinct-property iterators
# ---------------------------------------------------------------------------

class DistinctHostsIterator:
    """ref feasible.go:250-347"""

    def __init__(self, ctx: EvalContext, source):
        self.ctx = ctx
        self.source = source
        self.tg: Optional[TaskGroup] = None
        self.job: Optional[Job] = None
        self.tg_distinct_hosts = False
        self.job_distinct_hosts = False

    @staticmethod
    def _has_distinct_hosts(constraints: list[Constraint]) -> bool:
        return any(c.operand == CONSTRAINT_DISTINCT_HOSTS for c in constraints)

    def set_task_group(self, tg: TaskGroup):
        self.tg = tg
        self.tg_distinct_hosts = self._has_distinct_hosts(tg.constraints)

    def set_job(self, job: Job):
        self.job = job
        self.job_distinct_hosts = self._has_distinct_hosts(job.constraints)

    def next(self) -> Optional[Node]:
        while True:
            option = self.source.next()
            if option is None or not (
                self.job_distinct_hosts or self.tg_distinct_hosts
            ):
                return option
            if not self._satisfies(option):
                self.ctx.metrics.filter_node(option, CONSTRAINT_DISTINCT_HOSTS)
                continue
            return option

    def _satisfies(self, option: Node) -> bool:
        proposed = self.ctx.proposed_allocs(option.id)
        for alloc in proposed:
            job_collision = alloc.job_id == self.job.id
            task_collision = alloc.task_group == self.tg.name
            if (self.job_distinct_hosts and job_collision) or (
                job_collision and task_collision
            ):
                return False
        return True

    def reset(self):
        self.source.reset()


class DistinctPropertyIterator:
    """ref feasible.go:349-452"""

    def __init__(self, ctx: EvalContext, source):
        from .propertyset import PropertySet

        self.ctx = ctx
        self.source = source
        self.tg: Optional[TaskGroup] = None
        self.job: Optional[Job] = None
        self.has_distinct_property = False
        self.job_property_sets: list = []
        self.group_property_sets: dict[str, list] = {}
        self._pset_cls = PropertySet

    def set_task_group(self, tg: TaskGroup):
        self.tg = tg
        if tg.name not in self.group_property_sets:
            sets = []
            for c in tg.constraints:
                if c.operand != CONSTRAINT_DISTINCT_PROPERTY:
                    continue
                pset = self._pset_cls(self.ctx, self.job)
                pset.set_tg_constraint(c, tg.name)
                sets.append(pset)
            self.group_property_sets[tg.name] = sets
        self.has_distinct_property = bool(self.job_property_sets) or bool(
            self.group_property_sets[tg.name]
        )

    def set_job(self, job: Job):
        self.job = job
        for c in job.constraints:
            if c.operand != CONSTRAINT_DISTINCT_PROPERTY:
                continue
            pset = self._pset_cls(self.ctx, job)
            pset.set_job_constraint(c)
            self.job_property_sets.append(pset)

    def next(self) -> Optional[Node]:
        while True:
            option = self.source.next()
            if option is None or not self.has_distinct_property:
                return option
            if not self._satisfies_properties(option, self.job_property_sets):
                continue
            if not self._satisfies_properties(
                option, self.group_property_sets.get(self.tg.name, [])
            ):
                continue
            return option

    def _satisfies_properties(self, option: Node, sets: list) -> bool:
        for ps in sets:
            satisfies, reason = ps.satisfies_distinct_properties(option, self.tg.name)
            if not satisfies:
                self.ctx.metrics.filter_node(option, reason)
                return False
        return True

    def reset(self):
        self.source.reset()
        for ps in self.job_property_sets:
            ps.populate_proposed()
        for sets in self.group_property_sets.values():
            for ps in sets:
                ps.populate_proposed()


# ---------------------------------------------------------------------------
# Class-memoized feasibility wrapper
# ---------------------------------------------------------------------------

class FeasibilityWrapper:
    """Runs job/task-group checkers only when the computed node class hasn't
    already been decided (ref feasible.go:784-898)."""

    def __init__(self, ctx: EvalContext, source, job_checkers, tg_checkers):
        self.ctx = ctx
        self.source = source
        self.job_checkers = job_checkers
        self.tg_checkers = tg_checkers
        self.tg = ""

    def set_task_group(self, tg: str):
        self.tg = tg

    def reset(self):
        self.source.reset()

    def next(self) -> Optional[Node]:
        elig = self.ctx.get_eligibility()
        metrics = self.ctx.metrics

        while True:
            option = self.source.next()
            if option is None:
                return None

            job_escaped = job_unknown = False
            status = elig.job_status(option.computed_class)
            if status == EVAL_COMPUTED_CLASS_INELIGIBLE:
                metrics.filter_node(option, "computed class ineligible")
                continue
            elif status == EVAL_COMPUTED_CLASS_ESCAPED:
                job_escaped = True
            elif status == EVAL_COMPUTED_CLASS_UNKNOWN:
                job_unknown = True

            failed_job = False
            for check in self.job_checkers:
                if not check.feasible(option):
                    if not job_escaped:
                        elig.set_job_eligibility(False, option.computed_class)
                    failed_job = True
                    break
            if failed_job:
                continue

            if not job_escaped and job_unknown:
                elig.set_job_eligibility(True, option.computed_class)

            tg_escaped = tg_unknown = False
            status = elig.task_group_status(self.tg, option.computed_class)
            if status == EVAL_COMPUTED_CLASS_INELIGIBLE:
                metrics.filter_node(option, "computed class ineligible")
                continue
            elif status == EVAL_COMPUTED_CLASS_ELIGIBLE:
                return option
            elif status == EVAL_COMPUTED_CLASS_ESCAPED:
                tg_escaped = True
            elif status == EVAL_COMPUTED_CLASS_UNKNOWN:
                tg_unknown = True

            failed_tg = False
            for check in self.tg_checkers:
                if not check.feasible(option):
                    if not tg_escaped:
                        elig.set_task_group_eligibility(
                            False, self.tg, option.computed_class
                        )
                    failed_tg = True
                    break
            if failed_tg:
                continue

            if not tg_escaped and tg_unknown:
                elig.set_task_group_eligibility(True, self.tg, option.computed_class)

            return option


class QuotaIterator:
    """OSS no-op quota iterator (ref scheduler/quota.go OSS stub)."""

    def __init__(self, ctx: EvalContext, source):
        self.source = source

    def next(self):
        return self.source.next()

    def reset(self):
        self.source.reset()
