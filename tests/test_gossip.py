"""Gossip membership + server auto-discovery
(ref nomad/serf.go, vendored serf/memberlist, autopilot dead-server
cleanup). A cluster forms from ONE join address, dead servers are reaped
out of raft, and new servers auto-join."""

import time

from nomad_tpu.core.server import Server
from nomad_tpu.gossip import Gossip
from nomad_tpu.raft import InmemTransport, RaftConfig


def wait_until(fn, timeout=15.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class TestGossipLayer:
    def test_three_agents_converge_from_one_seed(self):
        agents = [Gossip(name=f"g{i}") for i in range(3)]
        try:
            for g in agents:
                g.start()
            assert agents[1].join(agents[0].addr)
            assert agents[2].join(agents[0].addr)
            wait_until(
                lambda: all(len(g.alive_members()) == 3 for g in agents),
                msg="full membership on every agent",
            )
        finally:
            for g in agents:
                g.stop()

    def test_dead_member_detected_and_reaped(self):
        agents = [Gossip(name=f"d{i}") for i in range(3)]
        events = []
        agents[0].on_event = lambda e, m: events.append((e, m.name))
        try:
            for g in agents:
                g.start()
            agents[1].join(agents[0].addr)
            agents[2].join(agents[0].addr)
            wait_until(
                lambda: all(len(g.alive_members()) == 3 for g in agents),
                msg="membership",
            )
            # crash d2: stop without leave
            agents[2].stop()
            wait_until(
                lambda: ("dead", "d2") in events,
                msg="d2 detected dead",
            )
            # generous margin: suspect (1.5s) + reap (3s) is ~5s on an
            # idle box, but the full tier-1 suite can starve the probe
            # loop for long stretches — the assertion is THAT reap
            # happens, not how fast
            wait_until(
                lambda: "d2" not in agents[0].members,
                timeout=45.0,
                msg="d2 reaped",
            )
        finally:
            for g in (agents[0], agents[1]):
                g.stop()

    def test_restarted_member_refutes_its_leave_tombstone(self):
        """A restarted process rejoins at incarnation 0 while the
        cluster still holds its own leave tombstone at N+1; the rejoiner
        must refute (bump past the tombstone) or it stays permanently
        invisible — the bug that split a region's voter map under a
        rolling restart (federation plane, PR 12)."""
        a = Gossip(name="r0")
        b = Gossip(name="r1")
        b2 = None
        try:
            a.start()
            b.start()
            assert b.join(a.addr)
            wait_until(lambda: len(a.alive_members()) == 2, msg="joined")
            b.leave()
            b.stop()
            wait_until(
                lambda: a.members["r1"].status == "left",
                msg="tombstone recorded",
            )
            tombstone_inc = a.members["r1"].incarnation
            # same name, fresh process: incarnation restarts at 0
            b2 = Gossip(name="r1")
            b2.start()
            assert b2.join(a.addr)
            wait_until(
                lambda: a.members["r1"].status == "alive",
                msg="rejoiner visible again",
            )
            assert b2._me.incarnation > tombstone_inc
        finally:
            a.stop()
            b.stop()
            if b2 is not None:
                b2.stop()

    def test_leave_is_distinct_from_death(self):
        a, b = Gossip(name="l0"), Gossip(name="l1")
        events = []
        a.on_event = lambda e, m: events.append((e, m.name))
        try:
            a.start()
            b.start()
            b.join(a.addr)
            wait_until(lambda: len(a.alive_members()) == 2, msg="joined")
            b.leave()
            wait_until(lambda: ("leave", "l1") in events, msg="leave event")
            assert ("dead", "l1") not in events
        finally:
            a.stop()
            b.stop()

    def test_refutation(self):
        """A falsely-suspected member bumps incarnation and stays alive."""
        a, b = Gossip(name="r0", suspect_timeout=5.0), Gossip(name="r1")
        try:
            a.start()
            b.start()
            b.join(a.addr)
            wait_until(lambda: len(a.alive_members()) == 2, msg="joined")
            a._mark_suspect("r1")
            # the next probe carries the suspicion; r1 refutes
            wait_until(
                lambda: a.members["r1"].status == "alive"
                and a.members["r1"].incarnation > 0,
                msg="refutation",
            )
        finally:
            a.stop()
            b.stop()


def make_gossip_server(i, transport, seeds=None, bootstrap=False):
    cfg = {
        "seed": 42,
        "heartbeat_ttl": 600.0,
        "bootstrap": bootstrap,
        "gossip": {
            "bind": ("127.0.0.1", 0),
            "join": seeds or [],
            "suspect_timeout": 1.0,
            "reap_timeout": 2.0,
        },
        "raft": {
            "node_id": f"gs{i}",
            "address": f"graft{i}",
            "transport": transport,
            "config": RaftConfig(
                heartbeat_interval=0.02,
                election_timeout_min=0.1,
                election_timeout_max=0.2,
            ),
        },
    }
    s = Server(cfg)
    s.start(num_workers=1, wait_for_leader=0.0)
    return s


class TestServerAutoDiscovery:
    def test_cluster_forms_kills_reap_and_rejoin(self):
        """The VERDICT's done-criteria in one flow: 3 servers form from one
        join address; a killed server is reaped from raft; a new server
        auto-joins."""
        transport = InmemTransport()
        s0 = make_gossip_server(0, transport, bootstrap=True)
        servers = [s0]
        try:
            wait_until(lambda: s0.is_leader(), msg="bootstrap leader")
            seed = [list(s0.gossip.addr)]
            s1 = make_gossip_server(1, transport, seeds=seed)
            s2 = make_gossip_server(2, transport, seeds=seed)
            servers += [s1, s2]

            wait_until(
                lambda: set(s0.raft.voters) == {"gs0", "gs1", "gs2"},
                msg="all three servers in raft membership",
            )
            # followers converge to the same voter map via CONFIG entries
            wait_until(
                lambda: set(s1.raft.voters) == {"gs0", "gs1", "gs2"}
                and set(s2.raft.voters) == {"gs0", "gs1", "gs2"},
                msg="voter map replicated",
            )

            # scheduling works across the discovered cluster
            import nomad_tpu.mock as mock

            leader = next(s for s in servers if s.is_leader())
            for _ in range(2):
                leader.node_register(mock.node())
            job = mock.job()
            job.task_groups[0].count = 2
            job.task_groups[0].tasks[0].resources.networks = []
            leader.job_register(job)
            wait_until(
                lambda: len(leader.state.allocs_by_job(job.namespace, job.id)) == 2,
                msg="job placed on discovered cluster",
            )

            # crash s2 (no leave): gossip detects, leader reaps the voter
            s2.gossip._stop.set()
            s2.gossip._sock.close()
            s2.raft.shutdown()
            wait_until(
                lambda: "gs2" not in s0.raft.voters,
                timeout=20.0,
                msg="dead server removed from raft",
            )

            # a new server auto-joins through the same seed
            s3 = make_gossip_server(3, transport, seeds=seed)
            servers.append(s3)
            wait_until(
                lambda: "gs3" in s0.raft.voters,
                msg="new server auto-joined raft",
            )
        finally:
            for s in servers:
                try:
                    s.stop()
                except Exception:
                    pass
