"""mTLS for the RPC tier (ref helper/tlsutil/: CA-pinned mutual TLS
wrapping the server RPC listener and every outbound connection).

Both directions require certificates signed by the cluster CA
(CERT_REQUIRED): a peer without a CA-signed cert can neither serve nor
call. Hostname checking is disabled in favor of CA pinning — the
reference likewise verifies region-role names against its own CA rather
than public-PKI hostnames. ``generate_dev_certs`` shells out to openssl
to mint a throwaway CA + node certificate for dev clusters and tests;
production brings its own PKI."""

from __future__ import annotations

import os
import ssl
import subprocess


class TLSError(RuntimeError):
    pass


def server_context(ca: str, cert: str, key: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_cert_chain(cert, key)
    ctx.load_verify_locations(ca)
    ctx.verify_mode = ssl.CERT_REQUIRED  # mutual: clients must present
    return ctx


def client_context(ca: str, cert: str, key: str) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_cert_chain(cert, key)
    ctx.load_verify_locations(ca)
    ctx.check_hostname = False  # CA-pinned, not public-PKI hostnames
    ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def contexts_from_config(tls_config: dict):
    """(server_ctx, client_ctx) from a {ca, cert, key} config block, or
    (None, None) when TLS is not configured."""
    if not tls_config:
        return None, None
    ca = tls_config.get("ca")
    cert = tls_config.get("cert")
    key = tls_config.get("key")
    if not (ca and cert and key):
        raise TLSError("tls config requires ca, cert, and key paths")
    return server_context(ca, cert, key), client_context(ca, cert, key)


def generate_dev_certs(directory: str, name: str = "node") -> dict:
    """Mint a throwaway CA + a CA-signed cert for 127.0.0.1 via openssl;
    returns the {ca, cert, key} config block."""
    os.makedirs(directory, exist_ok=True)
    ca_key = os.path.join(directory, "ca.key")
    ca_crt = os.path.join(directory, "ca.crt")
    key = os.path.join(directory, f"{name}.key")
    csr = os.path.join(directory, f"{name}.csr")
    crt = os.path.join(directory, f"{name}.crt")
    ext = os.path.join(directory, f"{name}.ext")

    def run(*args):
        proc = subprocess.run(args, capture_output=True, text=True)
        if proc.returncode != 0:
            raise TLSError(f"openssl failed: {proc.stderr}")

    if not os.path.exists(ca_crt):
        run(
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", ca_key, "-out", ca_crt, "-days", "30",
            "-subj", "/CN=nomad-tpu-dev-ca",
        )
    run(
        "openssl", "req", "-newkey", "rsa:2048", "-nodes",
        "-keyout", key, "-out", csr, "-subj", f"/CN={name}",
    )
    with open(ext, "w") as f:
        f.write("subjectAltName=IP:127.0.0.1,DNS:localhost\n")
    run(
        "openssl", "x509", "-req", "-in", csr, "-CA", ca_crt,
        "-CAkey", ca_key, "-CAcreateserial", "-out", crt,
        "-days", "30", "-extfile", ext,
    )
    return {"ca": ca_crt, "cert": crt, "key": key}
