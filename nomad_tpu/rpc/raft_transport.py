"""Raft transport over the shared RPC listener (ref: the reference's raft
rides the same TCP mux behind the RpcRaft first byte, rpc.go:195-200).
"""

from __future__ import annotations

import itertools
import socket
import threading
from typing import Callable, Optional

from ..raft.transport import Transport
from ..testing import faults as _faults
from .codec import RPC_RAFT, ConnectionClosed, read_frame, write_frame


class _RaftConn:
    def __init__(self, addr: str, timeout: float, tls_context=None):
        host, port = addr.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if tls_context is not None:
            self.sock = tls_context.wrap_socket(self.sock)
        self.sock.sendall(bytes([RPC_RAFT]))
        self.lock = threading.Lock()
        self.seq = itertools.count(1)

    def call(self, method: str, payload):
        with self.lock:
            seq = next(self.seq)
            write_frame(self.sock, [seq, method, payload])
            # nta: ignore[lock-held-blocking-call] — the per-conn lock IS
            # the request/response framing: one RPC in flight per socket,
            # concurrent callers use their own conns (transport pool)
            rseq, error, result = read_frame(self.sock)
            if error is not None:
                raise ConnectionError(f"raft rpc error: {error}")
            return result

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class TcpRaftTransport(Transport):
    """Dials peers' RPC listeners with the raft protocol byte. The local
    node's handlers are registered onto its RpcServer (register())."""

    def __init__(self, rpc_server=None, timeout: float = 5.0, tls_context=None):
        self.rpc_server = rpc_server
        self.timeout = timeout
        self.tls_context = tls_context
        self._conns: dict[str, _RaftConn] = {}
        self._lock = threading.Lock()

    def register(self, address: str, handlers: dict[str, Callable]):
        if self.rpc_server is not None:
            self.rpc_server.register_raft(handlers)

    def _conn(self, target: str) -> _RaftConn:
        with self._lock:
            c = self._conns.get(target)
            if c is not None:
                return c
            c = _RaftConn(target, self.timeout, tls_context=self.tls_context)
            self._conns[target] = c
            return c

    def _call(self, target: str, method: str, req: dict):
        plane = _faults.ACTIVE
        if plane is not None:
            act = plane.on_raft(req.get("_from") or "", target, method)
            if act in ("drop", "sever"):
                if act == "sever":
                    with self._lock:
                        c = self._conns.pop(target, None)
                    if c is not None:
                        c.close()
                raise ConnectionError(f"injected {act}: {target} {method}")
        req = {k: v for k, v in req.items() if k != "_from"}
        try:
            return self._conn(target).call(method, req)
        except (ConnectionClosed, ConnectionError, OSError) as e:
            with self._lock:
                c = self._conns.pop(target, None)
            if c is not None:
                c.close()
            raise ConnectionError(f"raft rpc to {target} failed: {e}")

    def request_vote(self, target: str, req: dict) -> dict:
        return self._call(target, "request_vote", req)

    def append_entries(self, target: str, req: dict) -> dict:
        return self._call(target, "append_entries", req)

    def install_snapshot(self, target: str, req: dict) -> dict:
        return self._call(target, "install_snapshot", req)

    def close(self):
        with self._lock:
            for c in self._conns.values():
                c.close()
            self._conns.clear()
