"""Log rotation (ref client/logmon + logging/logrotator: rotated
<task>.<stream>.<n> files bounded by LogConfig)."""

import os
import time

import nomad_tpu.mock as mock
from nomad_tpu.agent import DevAgent
from nomad_tpu.client.logmon import RotatingWriter
from nomad_tpu.structs.model import LogConfig


def wait_until(fn, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class TestRotatingWriter:
    def test_rotates_and_reaps(self, tmp_path):
        w = RotatingWriter(str(tmp_path), "t", "stdout",
                           max_files=3, max_file_size_mb=1)
        chunk = b"x" * (512 * 1024)
        for _ in range(10):  # 5 MiB total → indexes advance, old reaped
            w.write(chunk)
        w.close()
        files = sorted(
            f for f in os.listdir(tmp_path) if f.startswith("t.stdout.")
        )
        assert len(files) <= 3
        indexes = sorted(int(f.rsplit(".", 1)[1]) for f in files)
        assert indexes[-1] >= 3  # rotation actually happened
        # contiguous newest window
        assert indexes == list(range(indexes[0], indexes[-1] + 1))

    def test_resumes_at_newest_index(self, tmp_path):
        w = RotatingWriter(str(tmp_path), "t", "stdout",
                           max_files=5, max_file_size_mb=1)
        w.write(b"y" * (1024 * 1024 + 1))
        w.write(b"z")  # forces rotation to .1
        w.close()
        resumed = RotatingWriter(str(tmp_path), "t", "stdout",
                                 max_files=5, max_file_size_mb=1)
        assert resumed.index == 1
        resumed.close()


class TestLogicalStream:
    def test_follow_cursor_survives_rotation(self, tmp_path):
        """fs.logs serves surviving rotated files as one logical stream:
        a follower's offset cursor crosses the .0→.1 boundary without
        losing the old file's tail."""
        from nomad_tpu.client import fs

        alloc_dir = tmp_path
        log_dir = alloc_dir / "web" / "logs"
        w = RotatingWriter(str(log_dir), "web", "stdout",
                           max_files=5, max_file_size_mb=1)
        first = b"A" * (1024 * 1024 - 10)  # nearly fills .0
        w.write(first)

        # follower reads everything so far
        out = fs.logs(str(alloc_dir), "web", "stdout", offset=0, limit=1 << 22)
        cursor = out["Offset"]
        collected = out["Data"]
        assert cursor == len(first)

        # rotation happens between polls
        second = b"B" * 64
        w.write(b"C" * 20)   # overflows → rotates to .1 mid-stream
        w.write(second)
        w.close()

        out = fs.logs(
            str(alloc_dir), "web", "stdout", offset=cursor, limit=1 << 22
        )
        collected += out["Data"]
        assert collected == (first + b"C" * 20 + second).decode()


class TestTaskLogRotation:
    def test_raw_exec_logs_rotate_and_serve_newest(self, tmp_path):
        agent = DevAgent(num_clients=1, server_config={"seed": 113})
        agent.start()
        try:
            job = mock.batch_job()
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.log_config = LogConfig(max_files=2, max_file_size_mb=1)
            # ~3 MiB of output forces at least two rotations
            task.config = {
                "command": "/bin/sh",
                "args": [
                    "-c",
                    "i=0; while [ $i -lt 48 ]; do "
                    "head -c 65536 /dev/zero | tr '\\0' 'a'; "
                    "i=$((i+1)); done; echo END-MARKER",
                ],
            }
            task.resources.networks = []
            agent.server.job_register(job)
            wait_until(
                lambda: [
                    a.client_status
                    for a in agent.server.state.allocs_by_job(
                        job.namespace, job.id
                    )
                ]
                == ["complete"],
                msg="writer task complete",
            )
            (alloc,) = agent.server.state.allocs_by_job(job.namespace, job.id)
            log_dir = os.path.join(
                agent.clients[0].data_dir, "allocs", alloc.id, "web", "logs"
            )
            files = [
                f for f in os.listdir(log_dir) if f.startswith("web.stdout.")
            ]
            assert len(files) <= 2, files
            assert all(
                os.path.getsize(os.path.join(log_dir, f)) <= 1024 * 1024 + 65536
                for f in files
            )
            # the fs/logs surface serves the newest index (END-MARKER tail)
            from nomad_tpu.client import fs

            out = fs.logs(
                os.path.dirname(log_dir).rsplit("/web", 1)[0],
                "web",
                "stdout",
                origin="end",
                offset=64,
            )
            assert "END-MARKER" in out["Data"]
        finally:
            agent.stop()
