"""Seeded, deterministic workload grammar.

A :class:`Scenario` is a list of :class:`Phase` definitions; compiling it
with a seed produces an :class:`OpStream` — a time-ordered list of
:class:`Op` whose canonical encoding is byte-identical for the same
(scenario, seed), which is the determinism contract the smoke soak pins.

Every random draw comes from a *named* RNG stream
(``named_rng(seed, scenario, phase, stream)``): adding a new op kind or
reordering unrelated draws cannot perturb the draws of existing streams,
so scenarios stay replayable across edits that don't touch their phases.

Compilation walks a :class:`World` — the grammar's model of which job
slots/nodes exist and their current counts/versions — so the emitted
stream is coherent (no scaling a job that was never submitted, no
draining an unregistered node). The driver re-derives the same world at
fire time purely from op args; nothing about the stream depends on the
cluster's responses.
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import dataclass, field
from typing import Optional

#: op kinds, in one place so driver/score can enumerate them
OP_KINDS = (
    "node.register",
    "node.down",
    "node.up",
    "node.drain",
    "node.drain_off",
    "job.submit",
    "job.scale",
    "job.update",
    "job.stop",
    "job.dispatch_register",
    "job.dispatch",
    "job.evaluate",
    "system.gc",
)


def named_rng(seed: int, *names: str) -> random.Random:
    """One independent deterministic stream per (seed, *names): the name
    path is hashed (not Python ``hash()``, which is salted per process)
    into the Random seed."""
    key = ("%d/" % seed + "/".join(names)).encode()
    return random.Random(int.from_bytes(hashlib.blake2b(key, digest_size=8).digest(), "big"))


@dataclass(frozen=True)
class Op:
    """One scheduled operation. ``t`` is seconds from storm start; ``seq``
    breaks ties so ordering is total and stable. ``args`` must be
    JSON-serializable with deterministic content."""

    t: float
    seq: int
    kind: str
    args: dict

    def encode(self) -> str:
        return "%010.4f %06d %s %s" % (
            self.t,
            self.seq,
            self.kind,
            json.dumps(self.args, sort_keys=True, separators=(",", ":")),
        )


class OpStream:
    """The compiled, time-ordered storm."""

    def __init__(self, scenario_name: str, seed: int, ops: list[Op]):
        self.scenario_name = scenario_name
        self.seed = seed
        self.ops = sorted(ops, key=lambda o: (o.t, o.seq))

    def encode(self) -> bytes:
        header = f"# loadgen stream scenario={self.scenario_name} seed={self.seed} ops={len(self.ops)}\n"
        return (header + "\n".join(op.encode() for op in self.ops) + "\n").encode()

    def digest(self) -> str:
        return hashlib.sha256(self.encode()).hexdigest()

    def duration(self) -> float:
        return self.ops[-1].t if self.ops else 0.0

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for op in self.ops:
            out[op.kind] = out.get(op.kind, 0) + 1
        return out


# ---------------------------------------------------------------------------
# the compile-time world
# ---------------------------------------------------------------------------


@dataclass
class JobSlot:
    slot: int
    category: str  # "svc" | "bat" | "dsp"
    live: bool = False
    count: int = 0
    version: int = 0
    cpu: int = 100
    memory_mb: int = 64


class World:
    """Entity registry shared by compile time (here) and fire time (the
    driver): both sides derive identical state from the op stream alone."""

    def __init__(self):
        self.jobs: dict[int, JobSlot] = {}
        #: node slot -> status ("ready" | "down" | "draining")
        self.nodes: dict[int, str] = {}
        #: first slot that might be unregistered — slots never leave
        #: ``nodes``, so this cursor only moves forward and the register
        #: scan is O(1) amortized instead of O(fleet) per op (which made
        #: a 100K-node ramp compile O(fleet^2))
        self._next_node_slot = 0

    # -- helpers used by phase compilation -------------------------------
    def live_jobs(self, category: Optional[str] = None) -> list[JobSlot]:
        return [
            s
            for s in self.jobs.values()
            if s.live and (category is None or s.category == category)
        ]

    def apply(self, op: Op):
        """Advance the world by one op (also used by the driver)."""
        a = op.args
        if op.kind == "node.register":
            self.nodes[a["node"]] = "ready"
        elif op.kind == "node.down":
            self.nodes[a["node"]] = "down"
        elif op.kind == "node.up":
            self.nodes[a["node"]] = "ready"
        elif op.kind == "node.drain":
            self.nodes[a["node"]] = "draining"
        elif op.kind == "node.drain_off":
            self.nodes[a["node"]] = "ready"
        elif op.kind in ("job.submit", "job.dispatch_register"):
            slot = self.jobs.setdefault(
                a["slot"], JobSlot(slot=a["slot"], category=a["category"])
            )
            slot.category = a["category"]
            slot.live = True
            slot.count = a.get("count", 0)
            slot.version = a.get("version", 0)
            slot.cpu = a.get("cpu", 100)
            slot.memory_mb = a.get("memory_mb", 64)
        elif op.kind == "job.scale":
            s = self.jobs.get(a["slot"])
            if s is not None:
                s.count = a["count"]
        elif op.kind == "job.update":
            s = self.jobs.get(a["slot"])
            if s is not None:
                s.version = a["version"]
        elif op.kind == "job.stop":
            s = self.jobs.get(a["slot"])
            if s is not None:
                s.live = False
                s.count = 0


# ---------------------------------------------------------------------------
# phases
# ---------------------------------------------------------------------------


@dataclass
class Phase:
    """One storm phase: ``rate`` ops/s for ``duration`` seconds, op kinds
    drawn from ``mix`` (kind -> weight). Arrivals are a seeded Poisson
    process (open-loop arrivals, the production-traffic shape) unless
    ``uniform=True`` (evenly spaced — used by ramps that must finish a
    fixed amount of work inside the phase). ``params`` hold per-phase
    draw ranges (job counts, resources, drain deadlines...)."""

    name: str
    duration: float
    rate: float
    mix: dict[str, float]
    uniform: bool = False
    params: dict = field(default_factory=dict)

    # -- arg synthesis per kind ------------------------------------------
    def _draw_args(self, kind: str, rng: random.Random, world: World) -> Optional[dict]:
        p = self.params
        if kind == "node.register":
            # next unregistered slot (forward-only cursor; see World)
            fleet = p.get("node_fleet", 100)
            i = world._next_node_slot
            while i < fleet and i in world.nodes:
                i += 1
            world._next_node_slot = i
            if i >= fleet:
                return None  # fleet fully registered: skip (no-op)
            return {"node": i}
        if kind in ("node.down", "node.drain"):
            ready = sorted(i for i, st in world.nodes.items() if st == "ready")
            # never take out the whole fleet: keep a floor of ready nodes
            floor = p.get("ready_floor", max(2, len(world.nodes) // 4))
            if len(ready) <= floor:
                return None
            args = {"node": ready[rng.randrange(len(ready))]}
            if kind == "node.drain":
                args["deadline_s"] = round(rng.uniform(*p.get("drain_deadline_s", (5.0, 30.0))), 2)
            return args
        if kind == "node.up":
            down = sorted(i for i, st in world.nodes.items() if st == "down")
            if not down:
                return None
            return {"node": down[rng.randrange(len(down))]}
        if kind == "node.drain_off":
            draining = sorted(i for i, st in world.nodes.items() if st == "draining")
            if not draining:
                return None
            return {"node": draining[rng.randrange(len(draining))]}
        if kind == "job.submit":
            cats = sorted(p.get("job_categories", {"svc": 1.0}).items())
            cat = rng.choices([c for c, _ in cats], weights=[w for _, w in cats])[0]
            slots = p.get("job_slots", 64)
            free = [i for i in range(slots) if i not in world.jobs or not world.jobs[i].live]
            if not free:
                return None
            lo, hi = (
                p.get("count_range_by_category", {}).get(cat)
                or p.get("count_range", (1, 4))
            )
            args = {
                "slot": free[rng.randrange(len(free))],
                "category": cat,
                "type": "batch" if cat == "bat" else "service",
                "count": rng.randint(lo, hi),
                "cpu": rng.choice(p.get("cpu_choices", (50, 100, 250))),
                "memory_mb": rng.choice(p.get("memory_choices", (32, 64, 128))),
                "version": 0,
            }
            # overload storms shed by priority class; the key is only
            # drawn when the param exists so pre-existing scenarios keep
            # their stream digests byte-identical
            pri = p.get("priority_by_category", {}).get(cat)
            if pri is not None:
                args["priority"] = int(pri)
            return args
        if kind == "job.scale":
            live = world.live_jobs()
            live = [s for s in live if s.category != "dsp"]
            if not live:
                return None
            s = live[rng.randrange(len(live))]
            # relative step (so a 10K-count soak job churns hundreds of
            # allocs per scale while a 3-count smoke job steps by 1),
            # biased upward to keep the working set from decaying
            frac = p.get("scale_frac", 0.25)
            delta = max(1, int(s.count * frac * rng.uniform(0.2, 1.0)))
            new = max(1, s.count + (delta if rng.random() < 0.6 else -delta))
            if new == s.count:
                new = s.count + 1
            return {"slot": s.slot, "count": new}
        if kind == "job.update":
            live = [s for s in world.live_jobs("svc")]
            if not live:
                return None
            s = live[rng.randrange(len(live))]
            # version bump drives a rolling deploy (update stanza on svc jobs)
            return {"slot": s.slot, "version": s.version + 1}
        if kind == "job.stop":
            live = world.live_jobs()
            keep_floor = p.get("job_floor", 2)
            if len(live) <= keep_floor:
                return None
            s = live[rng.randrange(len(live))]
            return {"slot": s.slot, "purge": rng.random() < p.get("purge_p", 0.3)}
        if kind == "job.dispatch_register":
            slots = p.get("dispatch_slots", 4)
            free = [
                i for i in range(10_000, 10_000 + slots)
                if i not in world.jobs or not world.jobs[i].live
            ]
            if not free:
                return None
            return {
                "slot": free[0],
                "category": "dsp",
                "type": "batch",
                "count": 1,
                "cpu": 50,
                "memory_mb": 32,
                "version": 0,
            }
        if kind == "job.dispatch":
            live = world.live_jobs("dsp")
            if not live:
                return None
            s = live[rng.randrange(len(live))]
            fan = self.params.get("dispatch_fanout", (1, 4))
            return {"slot": s.slot, "fanout": rng.randint(*fan)}
        if kind == "job.evaluate":
            live = [s for s in world.live_jobs() if s.category != "dsp"]
            if not live:
                return None
            return {"slot": live[rng.randrange(len(live))].slot}
        if kind == "system.gc":
            return {}
        raise ValueError(f"unknown op kind: {kind}")

    def compile(
        self, seed: int, scenario: str, t0: float, seq0: int, world: World
    ) -> list[Op]:
        arrival = named_rng(seed, scenario, self.name, "arrivals")
        kind_rng = named_rng(seed, scenario, self.name, "mix")
        arg_rngs = {
            k: named_rng(seed, scenario, self.name, "args", k) for k in self.mix
        }
        kinds = sorted(self.mix)
        weights = [self.mix[k] for k in kinds]
        ops: list[Op] = []
        n_uniform = max(1, int(self.rate * self.duration))
        t = 0.0
        i = 0
        seq = seq0
        while True:
            if self.uniform:
                if i >= n_uniform:
                    break
                t = (i + 0.5) * self.duration / n_uniform
            else:
                t += arrival.expovariate(self.rate)
                if t >= self.duration:
                    break
            kind = kind_rng.choices(kinds, weights=weights)[0]
            args = self._draw_args(kind, arg_rngs[kind], world)
            i += 1
            if args is None:
                continue  # kind not applicable in this world state: skip
            op = Op(t=round(t0 + t, 4), seq=seq, kind=kind, args=args)
            world.apply(op)
            ops.append(op)
            seq += 1
        return ops


@dataclass
class Scenario:
    """A named storm: the cluster it runs against plus its phases and the
    SLO targets the scorekeeper grades at the end."""

    name: str
    description: str
    phases: list[Phase]
    n_workers: int = 2
    server_config: dict = field(default_factory=dict)
    #: extra seconds the runner waits for evals to quiesce after the storm
    quiesce_timeout: float = 60.0
    #: SLO targets consumed by score.grade(); keys documented there
    slos: dict = field(default_factory=dict)
    #: scorekeeper cadence (seconds between samples)
    sample_interval: float = 1.0
    #: run incremental invariants every N samples
    invariants_every: int = 5
    #: event-stream probe subscribers measuring delivery lag over HTTP
    probes: int = 2


def compile_stream(scenario: Scenario, seed: int) -> OpStream:
    """Compile the scenario's phases, in order, against one shared world."""
    world = World()
    ops: list[Op] = []
    t0 = 0.0
    for phase in scenario.phases:
        ops.extend(phase.compile(seed, scenario.name, t0, len(ops), world))
        t0 += phase.duration
    return OpStream(scenario.name, seed, ops)


# ---------------------------------------------------------------------------
# spec builders: op args -> model objects (used at fire time by the driver,
# and by tests that need the same specs without a cluster)
# ---------------------------------------------------------------------------

JOB_PREFIX = "ldg"
NODE_PREFIX = "ldgnode"


def job_id_for(slot: int, category: str, prefix: str = JOB_PREFIX) -> str:
    """``prefix`` scopes the id space: federated storms run one grammar
    per region against separate raft domains, and the cross-region
    oracle (job present in exactly its home region) is only meaningful
    when region A's slot 3 and region B's slot 3 are different jobs."""
    return f"{prefix}-{category}-{slot:05d}"


def node_id_for(slot: int) -> str:
    # a stable fake-uuid so prefix lookups and store keys behave like
    # production ids; derived only from the slot
    h = hashlib.blake2b(b"ldgnode-%d" % slot, digest_size=16).hexdigest()
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:32]}"


def build_node(slot: int, datacenters: tuple = ("dc1", "dc2"), resources: Optional[dict] = None):
    """Deterministic node for a slot: same id every time so down/up cycles
    re-register the SAME node (the client-restart shape)."""
    from .. import mock
    from ..structs import compute_class

    rng = named_rng(slot, "node-template")
    node = mock.node()
    node.id = node_id_for(slot)
    node.name = f"{NODE_PREFIX}-{slot:05d}"
    node.datacenter = datacenters[slot % len(datacenters)]
    res = resources or {}
    node.node_resources.cpu.cpu_shares = res.get(
        "cpu", rng.choice((4000, 8000, 16000))
    )
    node.node_resources.memory.memory_mb = res.get(
        "memory_mb", rng.choice((8192, 16384, 32768))
    )
    node.node_resources.networks = []
    node.reserved_resources.networks.reserved_host_ports = ""
    compute_class(node)
    return node


def build_job(args: dict, datacenters: tuple = ("dc1", "dc2"),
              prefix: str = JOB_PREFIX):
    """Job object for submit/update args. Everything that varies is drawn
    at compile time and carried in ``args`` — rebuilding from the same
    args yields an equivalent job (ids, counts, resources, version
    nonce)."""
    from .. import mock
    from ..structs.model import ParameterizedJobConfig, UpdateStrategy

    category = args["category"]
    job = mock.batch_job() if args.get("type") == "batch" else mock.job()
    job.id = job_id_for(args["slot"], category, prefix)
    job.name = job.id
    job.datacenters = list(datacenters)
    tg = job.task_groups[0]
    tg.count = args.get("count", 1)
    if args.get("priority") is not None:
        job.priority = int(args["priority"])
    task = tg.tasks[0]
    task.driver = "mock_driver"
    task.resources.cpu = args.get("cpu", 100)
    task.resources.memory_mb = args.get("memory_mb", 64)
    task.resources.networks = []
    tg.ephemeral_disk.size_mb = 10
    version = args.get("version", 0)
    # the version nonce lands in env: an in-place (non-destructive) task
    # update, which is what drives the rolling-deploy path
    task.env = dict(task.env or {})
    task.env["LDG_VERSION"] = str(version)
    if category == "svc":
        # the reconciler keys rolling deploys off the TASK GROUP's update
        # stanza (reconcile.py:540-581); short healthy deadlines keep
        # clientless soak deployments from pinning progress timers
        strategy = UpdateStrategy(
            max_parallel=2, stagger=int(1e9), min_healthy_time=0,
            healthy_deadline=int(5e9), progress_deadline=int(30e9),
        )
        job.update = strategy
        tg.update = strategy
    if category == "dsp":
        job.parameterized_job = ParameterizedJobConfig(
            payload="optional", meta_optional=["wave"]
        )
    job.constraints = []
    job.spreads = []
    return job
