"""Candidate limiting and argmax selection (ref scheduler/select.go)."""

from __future__ import annotations

from typing import Optional

from .context import EvalContext
from .rank import RankedNode


class LimitIterator:
    """Bounded candidate scan: yields up to ``limit`` options, skipping up to
    ``max_skip`` options at or below the score threshold while better options
    remain (ref select.go:5-74)."""

    def __init__(
        self,
        ctx: EvalContext,
        source,
        limit: int,
        score_threshold: float,
        max_skip: int,
    ):
        self.ctx = ctx
        self.source = source
        self.limit = limit
        self.max_skip = max_skip
        self.score_threshold = score_threshold
        self.seen = 0
        self.skipped_nodes: list[RankedNode] = []
        self.skipped_node_index = 0

    def set_limit(self, limit: int):
        self.limit = limit

    def next(self) -> Optional[RankedNode]:
        if self.seen == self.limit:
            return None
        option = self._next_option()
        if option is None:
            return None
        if len(self.skipped_nodes) < self.max_skip:
            while (
                option is not None
                and option.final_score <= self.score_threshold
                and len(self.skipped_nodes) < self.max_skip
            ):
                self.skipped_nodes.append(option)
                option = self.source.next()
        self.seen += 1
        if option is None:
            return self._next_option()
        return option

    def _next_option(self) -> Optional[RankedNode]:
        source_option = self.source.next()
        if source_option is None and self.skipped_node_index < len(self.skipped_nodes):
            skipped = self.skipped_nodes[self.skipped_node_index]
            self.skipped_node_index += 1
            return skipped
        return source_option

    def reset(self):
        self.source.reset()
        self.seen = 0
        self.skipped_nodes = []
        self.skipped_node_index = 0


class MaxScoreIterator:
    """Consumes the source and returns only the max-scoring option
    (ref select.go:79-116)."""

    def __init__(self, ctx: EvalContext, source):
        self.ctx = ctx
        self.source = source
        self.max: Optional[RankedNode] = None

    def next(self) -> Optional[RankedNode]:
        if self.max is not None:
            return None
        while True:
            option = self.source.next()
            if option is None:
                return self.max
            if self.max is None or option.final_score > self.max.final_score:
                self.max = option

    def reset(self):
        self.source.reset()
        self.max = None
