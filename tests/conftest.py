"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

Multi-chip TPU hardware is not available in CI, so all sharding/pjit tests run
against XLA's host-platform device partitioning (8 virtual CPU devices). The
same code paths drive real TPU meshes in production.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_allow_excess_precision" not in flags:
    # bitwise value stability across compilations: the sharded and
    # unsharded planner programs must agree on every float (the mesh
    # parity contract; see nomad_tpu/tpu/__init__._ensure_xla_determinism)
    flags = (flags + " --xla_allow_excess_precision=false").strip()
os.environ["XLA_FLAGS"] = flags

# Tests compile tiny CPU programs quickly; sharing the persistent cache with
# TPU-process runs risks loading XLA:CPU AOT entries whose machine-feature
# flags don't match this process (cpu_aot_loader warns of possible SIGILL).
os.environ.setdefault("NOMAD_TPU_COMPILE_CACHE", "off")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Runtime lockdep witness (nomad_tpu/testing/lockdep.py): installed BEFORE
# jax/nomad_tpu modules create their locks, so every control-plane lock is
# allocation-site tracked and any observed acquisition-order inversion
# fails the test that produced it (see the autouse guard below). Disable
# with NOMAD_TPU_LOCKDEP=0 to bisect witness overhead.
from nomad_tpu.testing import lockdep  # noqa: E402

if os.environ.get("NOMAD_TPU_LOCKDEP", "1") != "0":
    lockdep.install()

# This image pins JAX_PLATFORMS=axon (real TPU); the env var is overridden by
# the platform plugin, so force the CPU backend through the config API.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Runtime data-race witness (nomad_tpu/testing/racedep.py): Eraser
# locksets over a curated set of shared attributes, keyed to lockdep's
# per-thread held stacks — installed AFTER lockdep (it reads lockdep's
# held sites) and after the watched modules import. Disable with
# NOMAD_TPU_RACEDEP=0 to bisect witness overhead.
from nomad_tpu.testing import racedep  # noqa: E402

if os.environ.get("NOMAD_TPU_RACEDEP", "1") != "0":
    racedep.install()


@pytest.fixture(autouse=True)
def _lockdep_guard():
    """Fail the test during which a lock-order inversion was first
    observed (background threads may attribute a violation to the test
    running when they fired — still a run failure, which is the
    contract: tier-1 passes only with zero observed inversions)."""
    before = lockdep.violation_count()
    yield
    now = lockdep.violations()
    assert len(now) == before, "\n".join(now[before:])


@pytest.fixture(autouse=True)
def _racedep_guard():
    """Fail the test during which a data race was first witnessed —
    same contract as the lockdep guard: tier-1 passes only with zero
    observed races on the watched attributes."""
    before = racedep.race_count()
    yield
    now = racedep.races()
    assert len(now) == before, "\n\n".join(now[before:])
