"""CLI: ``python -m nomad_tpu.loadgen --scenario soak --seed 1``.

Exit status: 0 when every SLO check passed, 1 otherwise (the soak is a
gate, not a demo). ``--print-stream`` dumps the compiled op stream and
exits — two runs with the same seed must print byte-identical output,
which is the cheap way to eyeball the determinism contract.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m nomad_tpu.loadgen",
        description="churn-soak load plane over the real server surface",
    )
    parser.add_argument("--scenario", default="smoke")
    parser.add_argument(
        "--fanout", action="store_true",
        help="run the event-plane fan-out bench instead of a storm "
        "scenario (env knobs FANOUT_SUBS / FANOUT_TOPICS / STORM_S; "
        "see scripts/fanout.sh)",
    )
    parser.add_argument(
        "--overload", action="store_true",
        help="run the scored overload storm (capacity / burst / recovery "
        "stages past saturation; env knobs OVERLOAD_CAP_RATE / "
        "OVERLOAD_BURST_X / OVERLOAD_BURST_S / OVERLOAD_DEPTH_LIMIT / "
        "OVERLOAD_DEADLINE_S; see scripts/overload.sh)",
    )
    parser.add_argument(
        "--federation", action="store_true",
        help="run the multi-region federated storm (partition, "
        "failover, rolling restart as scored chaos phases; env knobs "
        "FED_PROFILE / FED_REGIONS / FED_SERVERS / FED_NODES / "
        "FED_CHURN_S / FED_CROSS_P; see scripts/federation.sh)",
    )
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument(
        "--duration", type=float, default=None,
        help="override the churn phase length in seconds (soak scenario "
        "honors SOAK_CHURN_S too)",
    )
    parser.add_argument(
        "--out", default=None, help="write the scored JSON artifact here"
    )
    parser.add_argument(
        "--time-scale", type=float, default=1.0,
        help=">1 stretches the schedule, <1 compresses it",
    )
    parser.add_argument("--driver-workers", type=int, default=8)
    parser.add_argument(
        "--print-stream", action="store_true",
        help="compile and dump the op stream, then exit",
    )
    parser.add_argument(
        "--list", action="store_true", help="list scenarios and exit"
    )
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    from . import compile_stream, get_scenario, list_scenarios

    if args.list:
        for name in list_scenarios():
            print(name)
        return 0

    import logging

    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )

    if args.fanout:
        from .fanout import run_fanout_from_env
        from .fanout import summary_line as fanout_summary

        report = run_fanout_from_env(
            args.seed, out=args.out, driver_workers=args.driver_workers
        )
        print(json.dumps(report["slo"], indent=1))
        print(fanout_summary(report))
        return 0 if report["slo"]["failed"] == 0 else 1

    if args.overload:
        from .overload import run_overload_from_env
        from .overload import summary_line as overload_summary

        report = run_overload_from_env(
            args.seed, out=args.out, driver_workers=args.driver_workers
        )
        print(json.dumps(report["slo"], indent=1))
        print(overload_summary(report))
        return 0 if report["slo"]["failed"] == 0 else 1

    if args.federation:
        from .federation import run_federation_from_env
        from .federation import summary_line as fed_summary

        report = run_federation_from_env(
            args.seed, out=args.out, time_scale=args.time_scale
        )
        print(json.dumps(report["slo"], indent=1))
        print(fed_summary(report))
        return 0 if report["slo"]["failed"] == 0 else 1

    scenario = get_scenario(args.scenario)
    if args.duration is not None:
        # churn_s only ever feeds this one phase (scenarios.py), so the
        # direct patch is the whole override — no env mutation
        for phase in scenario.phases:
            if phase.name == "churn":
                phase.duration = args.duration

    if args.print_stream:
        sys.stdout.buffer.write(compile_stream(scenario, args.seed).encode())
        return 0

    from .runner import run_scenario, summary_line

    report = run_scenario(
        scenario,
        args.seed,
        out=args.out,
        time_scale=args.time_scale,
        driver_workers=args.driver_workers,
    )
    # the artifact carries the full timeline; stdout gets the grading and
    # the one summary line that must survive a truncated log tail
    print(json.dumps(report["slo"], indent=1))
    print(summary_line(report))
    return 0 if report["slo"]["failed"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
