"""Paged node axis (nomad_tpu/tpu/paging.py): the tiled windowed
planner must be BIT-IDENTICAL to the flat windowed scan it decomposes
— same placements, same round count — with the pure-numpy windowed
oracle pinning both from the host side. The suite also pins the
operational surface: the tile bucketing policy, the budget gate, the
TileCache's floor/LRU/dirty-reupload accounting, the per-tile raft
stamps the committed planes carry, the devprof tile ledger, and the
dispatch routing (paged engages only over budget; paging off leaves
the flat path byte-identical — THE A/B contract)."""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from nomad_tpu.state import planes as state_planes
from nomad_tpu.tpu import kernel, paging
from nomad_tpu.tpu.kernel import WindowArgs, deterministic_scope
from nomad_tpu.tpu.paging import TileCache, plan_batch_paged, plan_windowed_np


@pytest.fixture(autouse=True)
def _paging_reset():
    tile_rows_before = state_planes.TILE_ROWS
    yield
    paging.reset()
    state_planes.TILE_ROWS = tile_rows_before


# ---------------------------------------------------------------------------
# problem generator + the three implementations under comparison
# ---------------------------------------------------------------------------


def build_case(seed, n, a, limit, c=4):
    rng = np.random.default_rng(seed)
    capacity = rng.integers(8, 64, size=(n, c)).astype(np.int32)
    usable = np.maximum(capacity[:, :2].astype(np.float32), 1.0)
    feasible = rng.random(n) < 0.9
    demand = rng.integers(1, 4, size=c).astype(np.int32)
    used0 = rng.integers(0, 4, size=(n, c)).astype(np.int32)
    collisions0 = rng.integers(0, 2, size=n).astype(np.int32)
    perm = rng.permutation(n).astype(np.int32)
    group_count = int(rng.integers(1, 8))
    return dict(
        capacity=capacity, usable=usable, feasible=feasible, perm=perm,
        demand=demand, group_count=group_count, limit=int(limit),
        n_allocs=int(a), used0=used0, collisions0=collisions0,
        n_real=int(n), a_pad=int(a),
    )


def run_flat(case):
    """The flat windowed jit — THE decomposition reference."""
    args = WindowArgs(
        capacity=jnp.asarray(case["capacity"]),
        usable=jnp.asarray(case["usable"]),
        feasible=jnp.asarray(case["feasible"]),
        perm=jnp.asarray(case["perm"], jnp.int32),
        demand=jnp.asarray(case["demand"]),
        group_count=jnp.int32(case["group_count"]),
        limit=jnp.int32(case["limit"]),
        n_allocs=jnp.int32(case["n_allocs"]),
    )
    out, _ = kernel._dispatch(
        "windowed", kernel._plan_batch_windowed_jit,
        (args, jnp.asarray(case["used0"]),
         jnp.asarray(case["collisions0"]),
         case["n_real"], case["a_pad"]),
        f"N{case['n_real']}A{case['a_pad']}",
    )
    placements, rounds = out
    return np.asarray(placements), int(rounds)


def run_paged(case):
    placements, rounds, stats = plan_batch_paged(
        case["capacity"], case["usable"], case["feasible"], case["perm"],
        case["demand"], case["group_count"], case["limit"],
        case["n_allocs"], case["used0"], case["collisions0"],
        case["n_real"], case["a_pad"],
    )
    return placements, rounds, stats


def run_oracle(case):
    return plan_windowed_np(
        case["capacity"], case["usable"], case["feasible"], case["perm"],
        case["demand"], case["group_count"], case["limit"],
        case["n_allocs"], case["used0"], case["collisions0"],
        case["n_real"], case["a_pad"],
    )


# ---------------------------------------------------------------------------
# the tile bucketing policy + the budget gate
# ---------------------------------------------------------------------------


class TestPolicy:
    def test_tile_rows_rounds_to_power_of_two(self):
        paging.configure(tile_nodes=100)
        assert paging.tile_rows() == 128
        paging.configure(tile_nodes=64)
        assert paging.tile_rows() == 64
        paging.configure(tile_nodes=1)  # floored at MIN_TILE_NODES
        assert paging.tile_rows() == paging.MIN_TILE_NODES

    def test_configure_pushes_tile_rows_to_planes(self):
        paging.configure(tile_nodes=128)
        assert state_planes.TILE_ROWS == 128

    def test_should_page_requires_enabled_and_over_budget(self):
        paging.reset()
        assert not paging.should_page(10**7)  # disabled by default
        paging.configure(enabled=True, device_node_budget_mb=1)
        # 1MB budget: ~20K nodes fit, a million do not
        assert not paging.should_page(1024)
        assert paging.should_page(10**6)
        paging.configure(enabled=False)
        assert not paging.should_page(10**6)

    def test_plane_bytes_scale_with_columns(self):
        assert paging.plane_bytes(1000, 4) > paging.plane_bytes(1000, 3)


# ---------------------------------------------------------------------------
# TileCache: budget floor, LRU eviction, dirty re-upload accounting
# ---------------------------------------------------------------------------


def _tile_builders(tn=8, c=4):
    def build_static(t):
        return (
            np.full((tn, c), t, np.int32),
            np.ones((tn, 2), np.float32),
            np.ones(tn, bool),
            np.arange(t * tn, (t + 1) * tn, dtype=np.int32),
        )

    def build_dynamic(t):
        return (np.zeros((tn, c), np.int32), np.zeros(tn, np.int32))

    return build_static, build_dynamic


class TestTileCache:
    def test_budget_floored_at_two_tiles(self):
        cache = TileCache(1, *_tile_builders())
        cache.ensure(0)
        st = cache.stats()
        assert st["budget_raised"]
        assert st["limit_bytes"] == 2 * st["tile_bytes"]

    def test_lru_eviction_and_revisit_counts_as_reupload(self):
        bs, bd = _tile_builders()
        tile_bytes = sum(
            np.asarray(x).nbytes for x in (*bs(0), *bd(0))
        )
        cache = TileCache(2 * tile_bytes, bs, bd)
        cache.ensure(0)
        cache.ensure(1)
        assert cache.evictions == 0
        cache.ensure(2)  # evicts tile 0 (LRU)
        assert cache.evictions == 1
        assert cache.reuploads == 0
        cache.ensure(0)  # back in: a budget-driven re-stream
        assert cache.reuploads == 1
        assert cache.reupload_bytes == tile_bytes

    def test_dirty_reuploads_only_dynamic_planes(self):
        bs, bd = _tile_builders()
        dyn_bytes = sum(np.asarray(x).nbytes for x in bd(0))
        cache = TileCache(1 << 20, bs, bd)
        cache.ensure(0)
        before = cache.upload_bytes
        cache.mark_dirty([0])
        cache.ensure(0)
        assert cache.reuploads == 1
        assert cache.upload_bytes - before == dyn_bytes
        cache.ensure(0)  # clean again: a hit, no traffic
        assert cache.hits == 1
        assert cache.upload_bytes - before == dyn_bytes


# ---------------------------------------------------------------------------
# parity: paged == flat == numpy oracle, bit for bit
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_paged_matches_flat_and_oracle_multi_tile(seed):
    """Multi-tile, multi-round, ring-offset-wrapping shapes: the paged
    decomposition must reproduce the flat windowed scan's placements and
    round count exactly, and the numpy oracle must agree with both."""
    paging.configure(enabled=True, tile_nodes=64)
    case = build_case(seed, n=320, a=160, limit=4)
    flat_p, flat_r = run_flat(case)
    paged_p, paged_r, stats = run_paged(case)
    oracle_p, oracle_r = run_oracle(case)
    assert stats["tiles"] == 5
    np.testing.assert_array_equal(flat_p, paged_p)
    np.testing.assert_array_equal(flat_p, oracle_p)
    assert flat_r == paged_r == oracle_r
    assert (paged_p >= 0).sum() == case["n_allocs"]


def test_paged_matches_flat_irregular_tail_tile(seed=11):
    """A node count that leaves the last tile mostly padding."""
    paging.configure(enabled=True, tile_nodes=64)
    case = build_case(seed, n=797, a=96, limit=6)
    flat_p, flat_r = run_flat(case)
    paged_p, paged_r, stats = run_paged(case)
    assert stats["tiles"] == 13
    np.testing.assert_array_equal(flat_p, paged_p)
    assert flat_r == paged_r


def test_paged_matches_flat_single_tile():
    paging.configure(enabled=True, tile_nodes=64)
    case = build_case(5, n=48, a=24, limit=3)
    flat_p, flat_r = run_flat(case)
    paged_p, paged_r, stats = run_paged(case)
    assert stats["tiles"] == 1
    np.testing.assert_array_equal(flat_p, paged_p)
    assert flat_r == paged_r


def test_paged_matches_flat_deterministic_flavor():
    """Under the deterministic compile flavor (the flavor the sharded
    parity pins run in) the decomposition must still be bit-identical."""
    paging.configure(enabled=True, tile_nodes=64)
    case = build_case(7, n=320, a=160, limit=4)
    with deterministic_scope():
        flat_p, flat_r = run_flat(case)
        paged_p, paged_r, _ = run_paged(case)
    np.testing.assert_array_equal(flat_p, paged_p)
    assert flat_r == paged_r


def test_paged_zero_feasible_places_nothing():
    paging.configure(enabled=True, tile_nodes=64)
    case = build_case(3, n=200, a=50, limit=4)
    case["feasible"][:] = False
    paged_p, paged_r, _ = run_paged(case)
    oracle_p, oracle_r = run_oracle(case)
    assert (paged_p == -1).all()
    np.testing.assert_array_equal(paged_p, oracle_p)
    assert paged_r == oracle_r == 1


def test_paged_dispatch_is_recompile_free_across_tiles():
    """Every tile of a shape shares ONE compiled program per sweep: a
    second paged run on a different problem of the same tile shape must
    not grow the compile cache."""
    paging.configure(enabled=True, tile_nodes=64)
    run_paged(build_case(21, n=320, a=64, limit=4))
    before = kernel.compile_cache_size()
    run_paged(build_case(22, n=448, a=64, limit=4))
    assert kernel.compile_cache_size() == before


def test_devprof_counts_tile_traffic():
    """The devprof transfer ledger grows its paged counters during a
    multi-round paged run: uploads for first residency, re-uploads for
    the dirty dynamic planes committed placements touch."""
    from nomad_tpu.debug import devprof

    paging.configure(enabled=True, tile_nodes=64)
    devprof.enable(True)
    devprof.reset()
    _, rounds, stats = run_paged(build_case(9, n=320, a=160, limit=4))
    totals = devprof.totals()
    # the ledger's tile_uploads is TOTAL tile traffic: first admissions
    # plus dirty/evicted re-streams (the thrash rule's numerator)
    assert rounds > 1
    assert stats["uploads"] > 0 and stats["reuploads"] > 0
    assert (
        totals["paged_tile_uploads"]
        == stats["uploads"] + stats["reuploads"]
    )
    assert totals["paged_tile_upload_bytes"] == stats["upload_bytes"] > 0
    assert totals["paged_tile_reuploads"] == stats["reuploads"]
    assert (
        totals["paged_tile_reupload_bytes"] == stats["reupload_bytes"] > 0
    )


# ---------------------------------------------------------------------------
# committed planes: tile-granular raft stamps
# ---------------------------------------------------------------------------


def _mini_store(n_nodes=10):
    from nomad_tpu import mock
    from nomad_tpu.state import StateStore

    state = StateStore()
    nodes = []
    for i in range(n_nodes):
        n = mock.node()
        n.id = f"node-{i:04d}"
        nodes.append(n)
    state.upsert_nodes(1, nodes)
    return state, nodes


def _alloc_on(node_id):
    from nomad_tpu import mock

    a = mock.alloc()
    a.node_id = node_id
    a.desired_status = "run"
    a.client_status = "pending"
    return a


class TestPlaneTileStamps:
    def test_commit_stamps_dirty_tiles_only(self):
        state_planes.TILE_ROWS = 4
        state, nodes = _mini_store(10)  # 3 tiles of 4 rows
        planes = state.planes
        epoch0, tile_rows, stamps = planes.tile_stamps()
        assert tile_rows == 4
        assert list(stamps) == [1, 1, 1]  # fresh axis: full restamp

        row = planes.index[nodes[5].id]
        state.upsert_allocs(7, [_alloc_on(nodes[5].id)])
        epoch1, _, stamps = planes.tile_stamps()
        assert epoch1 == epoch0  # no axis change
        want = [1, 1, 1]
        want[row // 4] = 7
        assert list(stamps) == want
        assert planes.dirty_tiles_since(1) == [row // 4]
        assert planes.dirty_tiles_since(7) == []

    def test_axis_rebuild_restamps_every_tile(self):
        from nomad_tpu import mock

        state_planes.TILE_ROWS = 4
        state, nodes = _mini_store(10)
        state.upsert_allocs(3, [_alloc_on(nodes[0].id)])
        extra = mock.node()
        extra.id = "node-extra"
        state.upsert_node(9, extra)  # axis change: full rebuild
        epoch, tile_rows, stamps = state.planes.tile_stamps()
        assert len(stamps) == 3  # 11 nodes / 4 rows
        assert (stamps == 9).all()
        assert state.planes.dirty_tiles_since(8) == [0, 1, 2]

    def test_dirty_tiles_cleared_after_commit(self):
        state_planes.TILE_ROWS = 4
        state, nodes = _mini_store(8)
        state.upsert_allocs(5, [_alloc_on(nodes[0].id)])
        assert state.planes._dirty_tiles == set()


# ---------------------------------------------------------------------------
# dispatch routing: the A/B contract
# ---------------------------------------------------------------------------


def _sched_problem(seed=9):
    from nomad_tpu import mock
    from nomad_tpu.state import StateStore
    from nomad_tpu.structs import compute_class

    state = StateStore()
    rng = random.Random(seed)
    nodes = []
    for i in range(96):
        n = mock.node()
        n.id = f"node-{i:04d}"
        n.node_resources.cpu.cpu_shares = rng.choice([8000, 16000])
        n.node_resources.memory.memory_mb = rng.choice([16384, 32768])
        n.node_resources.networks = []
        n.reserved_resources.networks.reserved_host_ports = ""
        compute_class(n)
        nodes.append(n)
    state.upsert_nodes(1, nodes)
    job = mock.job()
    job.id = "job-paged-route"
    tg = job.task_groups[0]
    tg.count = 16
    tg.tasks[0].resources.networks = []
    state.upsert_job(2, job)
    return state, job


class _Planner:
    def __init__(self):
        self.plans = []

    def submit_plan(self, plan):
        from nomad_tpu.structs.model import PlanResult

        self.plans.append(plan)
        return PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            node_preemptions=plan.node_preemptions,
            alloc_index=1,
        ), None

    def update_eval(self, ev):
        pass

    def create_eval(self, ev):
        pass


def _run_eval(seed=9):
    from nomad_tpu.structs.model import Evaluation, generate_uuid
    from nomad_tpu.tpu import batch_sched
    from nomad_tpu.tpu.batch_sched import TPUBatchScheduler

    state, job = _sched_problem(seed)
    planner = _Planner()
    sched = TPUBatchScheduler(
        state.snapshot(), planner, rng=random.Random(17)
    )
    ev = Evaluation(
        id=generate_uuid(), namespace=job.namespace,
        priority=job.priority, type=job.type,
        triggered_by="job-register", job_id=job.id,
        status="pending",
    )
    batch_sched.LAST_KERNEL_STATS.clear()
    sched.process(ev)
    mode = batch_sched.LAST_KERNEL_STATS.get("mode")
    stats = dict(batch_sched.LAST_KERNEL_STATS)
    placed = {
        a.name: a.node_id
        for allocs in planner.plans[0].node_allocation.values()
        for a in allocs
    }
    return mode, placed, stats


class TestDispatchRouting:
    def test_over_budget_routes_paged_with_identical_placements(
        self, monkeypatch
    ):
        """With paging ON and the budget too small for the node planes,
        the eval routes through the pager — and places the SAME allocs
        on the SAME nodes as the flat windowed dispatch."""
        paging.reset()
        mode_off, placed_off, _ = _run_eval()
        assert mode_off == "windowed"

        paging.configure(enabled=True, tile_nodes=64)
        monkeypatch.setattr(paging, "budget_mb", lambda: 0)
        mode_on, placed_on, stats = _run_eval()
        assert mode_on == "paged"
        assert stats["paged_tiles"] >= 2
        assert placed_on == placed_off

    def test_enabled_but_budget_fitting_stays_flat(self):
        """The A/B pin: shapes that fit the budget never enter the pager
        — the flat windowed path runs exactly as before the stanza
        existed."""
        paging.configure(enabled=True, device_node_budget_mb=4096)
        mode, placed, stats = _run_eval()
        assert mode == "windowed"
        assert "paged_tiles" not in stats
        assert placed

    def test_paged_kernel_fault_degrades_to_exact_np(self, monkeypatch):
        """The pager honors the tpu.kernel fault point: a faulted device
        tier degrades the eval to the exact-np host oracle, the same
        ladder as every other dispatch mode."""
        from nomad_tpu.testing import faults
        from nomad_tpu.tpu import batch_sched

        paging.configure(enabled=True, tile_nodes=64)
        monkeypatch.setattr(paging, "budget_mb", lambda: 0)
        plane = faults.install(faults.FaultPlane(seed=3))
        plane.rule("point", "error", method="tpu.kernel", count=100)
        try:
            mode, placed, _ = _run_eval()
        finally:
            faults.uninstall()
        assert mode == "exact-np-degraded"
        assert placed, "degraded eval placed nothing"
