"""Tier-1 smoke checks for shipped-but-unparsed code: the SPA's inline
JavaScript (node --check when available, else the tokenizer sanity pass)
and a compileall sweep so an import-time syntax error in ANY module —
including ones no test imports — fails collection (VERDICT r5 weak #5)."""

import pytest

from nomad_tpu.testing import jscheck
from nomad_tpu.ui import INDEX_HTML


class TestSpaJavascript:
    def test_spa_script_parses(self):
        scripts = jscheck.extract_scripts(INDEX_HTML)
        assert scripts, "SPA lost its <script> block"
        for src in scripts:
            checker = jscheck.check_js(src)
        assert checker in ("node", "tokenizer")

    def test_checker_rejects_broken_js(self):
        # the guard must actually guard: a lost brace and an unterminated
        # string both fail, under either backend
        for bad in (
            "function f() { if (x) { return 1; }\n",
            'const s = "unterminated;\n',
            "const t = `tpl ${x;\n",
        ):
            with pytest.raises(jscheck.JsSyntaxError):
                jscheck.check_js(bad)

    def test_tokenizer_handles_spa_idioms(self):
        # regex-vs-division, template nesting, escaped quotes: the exact
        # constructs the SPA uses, checked against the fallback tokenizer
        # explicitly (node may or may not exist in the environment)
        src = (
            "const esc = x => String(x ?? '').replace(/[&<>\"]/g, c => m[c]);\n"
            "const r = h.match(/#\\/(job|node)\\//) || a / b / c;\n"
            "const t = `a ${esc(`${x}`)} b`;\n"
        )
        jscheck.tokenize_check(src)

    def test_compileall_whole_package(self):
        # compileall + the analyzer's import-cycle/dead-module checks
        # (jscheck.check_package): a module that stops being imported —
        # or starts being imported at the top of a cycle — fails the
        # same smoke test that guards syntax
        from nomad_tpu.analysis import repo_root

        errors = jscheck.check_package(repo_root())
        assert not errors, "\n".join(errors)

    def test_check_package_catches_import_regressions(self, tmp_path):
        # the sweep must actually sweep: a seeded cycle and a dead
        # module in a scratch package both surface
        from nomad_tpu.analysis.imports import module_import_errors

        pkg = tmp_path / "nomad_tpu"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("from . import a\n")
        (pkg / "a.py").write_text("from nomad_tpu import b\n")
        (pkg / "b.py").write_text("from nomad_tpu import a\n")
        (pkg / "dead.py").write_text("X = 1\n")
        errors = module_import_errors(str(tmp_path), "nomad_tpu")
        assert any("import-cycle" in e for e in errors), errors
        assert any("dead-module" in e for e in errors), errors
