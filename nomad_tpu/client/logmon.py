"""Task log capture with rotation (ref client/logmon/: the reference runs
a per-task logmon plugin process writing rotated FIFO-fed log files named
``<task>.<stream>.<n>``; here an in-process writer thread drains the
task's stdout/stderr pipes into the same rotated layout, honoring
LogConfig.max_files / max_file_size_mb).

The fs/logs API reads the newest index transparently; older indexes age
out FIFO as rotation proceeds."""

from __future__ import annotations

import os
import threading

CHUNK = 65536


def rotated_indexes(log_dir: str, prefix: str) -> list[int]:
    """Sorted indexes of the rotated files for one stream (single
    definition shared by the writer and the fs/logs reader)."""
    out = []
    try:
        for name in os.listdir(log_dir):
            if name.startswith(prefix) and name[len(prefix):].isdigit():
                out.append(int(name[len(prefix):]))
    except OSError:
        pass
    return sorted(out)


class RotatingWriter:
    """Append-to-current-index writer with size-based rotation."""

    def __init__(self, log_dir: str, task: str, stream: str,
                 max_files: int = 10, max_file_size_mb: int = 10):
        self.log_dir = log_dir
        self.prefix = f"{task}.{stream}."
        self.max_files = max(1, max_files)
        self.max_bytes = max(1, max_file_size_mb) * 1024 * 1024
        os.makedirs(log_dir, exist_ok=True)
        self.index = self._newest_index()
        path = self._path(self.index)
        self._size = os.path.getsize(path) if os.path.exists(path) else 0
        self._fh = open(path, "ab")

    def _path(self, index: int) -> str:
        return os.path.join(self.log_dir, self.prefix + str(index))

    def _newest_index(self) -> int:
        indexes = rotated_indexes(self.log_dir, self.prefix)
        return indexes[-1] if indexes else 0

    def write(self, data: bytes):
        if self._size + len(data) > self.max_bytes and self._size > 0:
            self._rotate()
        self._fh.write(data)
        self._fh.flush()
        self._size += len(data)

    def _rotate(self):
        self._fh.close()
        self.index += 1
        self._fh = open(self._path(self.index), "ab")
        self._size = 0
        # FIFO reap: keep the newest max_files indexes
        floor = self.index - self.max_files + 1
        if floor > 0:
            try:
                for name in os.listdir(self.log_dir):
                    if name.startswith(self.prefix):
                        suffix = name[len(self.prefix):]
                        if suffix.isdigit() and int(suffix) < floor:
                            os.unlink(os.path.join(self.log_dir, name))
            except OSError:
                pass

    def close(self):
        try:
            self._fh.close()
        except OSError:
            pass


def start_copier(fd, writer: RotatingWriter) -> threading.Thread:
    """Drain a pipe fd into the writer until EOF (the logmon copy loop)."""

    def run():
        try:
            while True:
                data = os.read(fd, CHUNK)
                if not data:
                    break
                writer.write(data)
        except OSError:
            pass
        finally:
            try:
                os.close(fd)
            except OSError:
                pass
            writer.close()

    t = threading.Thread(target=run, daemon=True, name="logmon-fifo-pump")
    t.start()
    return t
