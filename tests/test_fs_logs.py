"""Alloc fs/logs/exec surface (ref command/agent/fs_endpoint.go,
client/logmon, command/alloc_{logs,fs,exec}.go)."""

import time

import nomad_tpu.mock as mock
from nomad_tpu.agent import DevAgent
from nomad_tpu.api.client import ApiClient
from nomad_tpu.api.http import HTTPServer


def wait_until(fn, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class TestFsLogsExec:
    def test_logs_fs_exec_roundtrip(self, capsys):
        agent = DevAgent(num_clients=1, server_config={"seed": 3})
        agent.start()
        http = HTTPServer(agent.server, port=0, agent=agent)
        http.start()
        client = ApiClient(address=http.address)
        try:
            job = mock.batch_job()
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": ["-c", "echo hello-stdout; echo hello-stderr >&2; echo data > artifact.txt"],
            }
            task.resources.networks = []
            agent.server.job_register(job)
            wait_until(
                lambda: all(
                    a.client_status == "complete"
                    for a in agent.server.state.allocs_by_job(job.namespace, job.id)
                )
                and len(agent.server.state.allocs_by_job(job.namespace, job.id)) == 1,
                msg="task complete",
            )
            (alloc,) = agent.server.state.allocs_by_job(job.namespace, job.id)

            # logs: stdout and stderr captured by the driver's logmon role
            out = client.get(
                f"/v1/client/fs/logs/{alloc.id}", task="web", type="stdout"
            )[0]
            assert "hello-stdout" in out["Data"]
            err = client.get(
                f"/v1/client/fs/logs/{alloc.id}", task="web", type="stderr"
            )[0]
            assert "hello-stderr" in err["Data"]

            # fs ls + cat
            entries = client.get(f"/v1/client/fs/ls/{alloc.id}", path="web")[0]
            names = {e["Name"] for e in entries}
            assert {"logs", "artifact.txt"} <= names
            cat = client.get(
                f"/v1/client/fs/cat/{alloc.id}", path="web/artifact.txt"
            )[0]
            assert cat["Data"].strip() == "data"

            # path traversal rejected
            from nomad_tpu.api.client import APIError

            try:
                client.get(f"/v1/client/fs/cat/{alloc.id}", path="../../etc/passwd")
                raise AssertionError("traversal must be rejected")
            except APIError as e:
                assert e.status in (400, 404)

            # one-shot exec in the task dir
            resp = client.put(
                f"/v1/client/exec/{alloc.id}",
                body={"Task": "web", "Cmd": ["/bin/cat", "artifact.txt"]},
            )[0]
            assert resp["ExitCode"] == 0 and resp["Stdout"].strip() == "data"

            # CLI: alloc logs + fs + exec
            from nomad_tpu.cli.main import main as cli_main

            rc = cli_main(
                ["-address", http.address, "alloc", "logs", alloc.id, "web"]
            )
            assert rc == 0
            assert "hello-stdout" in capsys.readouterr().out

            rc = cli_main(
                ["-address", http.address, "alloc", "fs", alloc.id, "web"]
            )
            assert rc == 0
            assert "artifact.txt" in capsys.readouterr().out

            rc = cli_main(
                [
                    "-address", http.address, "alloc", "exec",
                    alloc.id, "web", "/bin/cat", "artifact.txt",
                ]
            )
            assert rc == 0
            assert "data" in capsys.readouterr().out

            # logs offset cursor: poll-follow reads only the delta
            first = client.get(
                f"/v1/client/fs/logs/{alloc.id}", task="web", type="stdout"
            )[0]
            again = client.get(
                f"/v1/client/fs/logs/{alloc.id}",
                task="web",
                type="stdout",
                offset=first["Offset"],
            )[0]
            assert again["Data"] == ""
        finally:
            http.stop()
            agent.stop()
