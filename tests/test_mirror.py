"""Committed-plane columnar view (nomad_tpu/tpu/mirror.py + state/planes.py).

The core contract is EXACT equivalence BY CONSTRUCTION: after any sequence
of FSM applies, the planes the store patched in-commit must be array-equal
to a from-scratch ``ColumnarCluster`` rebuild over the same snapshot — the
property test drives hundreds of seeded random event sequences (node
add/remove/update/status flaps, alloc place/stop/fail/resize, plan-result
applies, plan overlays) through a real FSM and compares after every few
events, then round-trips persist→restore and checks the planes blob
byte-identical to a cold rebuild at the same raft index. ``rebuilds`` must
stay literally zero: the subscribe/skew/sever/checksum rebuild machinery
no longer exists to fire.
"""

import random

import numpy as np
import pytest

import nomad_tpu.mock as mock
from nomad_tpu.core import fsm as fsm_mod
from nomad_tpu.core.fsm import FSM
from nomad_tpu.events import EventBroker
from nomad_tpu.state import StateStore
from nomad_tpu.structs.model import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_RUNNING,
    ALLOC_DESIRED_STATUS_RUN,
    AllocatedCpuResources,
    AllocatedMemoryResources,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Allocation,
    Plan,
    PlanResult,
    generate_uuid,
)
from nomad_tpu.tpu.columnar import ColumnarCluster
from nomad_tpu.tpu.mirror import ColumnarMirror, MirrorCluster, usage_vec


def make_alloc(job, node_id, name, cpu=100, mem=64, disk=10, resources=True):
    tg = job.task_groups[0]
    task = tg.tasks[0]
    a = Allocation(
        id=generate_uuid(),
        namespace=job.namespace,
        job_id=job.id,
        task_group=tg.name,
        name=name,
        node_id=node_id,
        desired_status=ALLOC_DESIRED_STATUS_RUN,
        client_status=ALLOC_CLIENT_STATUS_RUNNING,
        # resources=False: a live alloc with allocated_resources=None —
        # contributes nothing to usage but still counts for same-job
        # collisions, exactly like the base scan
        allocated_resources=AllocatedResources(
            tasks={
                task.name: AllocatedTaskResources(
                    cpu=AllocatedCpuResources(cpu_shares=cpu),
                    memory=AllocatedMemoryResources(memory_mb=mem),
                )
            },
            shared=AllocatedSharedResources(disk_mb=disk),
        )
        if resources
        else None,
    )
    a.job = job
    return a


class _Harness:
    """FSM + broker + mirror with a monotonically allocated raft index.
    The broker is wired for external watchers only — the mirror view
    reads the store's committed planes and never subscribes."""

    def __init__(self):
        self.broker = EventBroker()
        self.state = StateStore()
        self.fsm = FSM(state=self.state, event_broker=self.broker)
        self.mirror = ColumnarMirror(self.state)
        self._index = 0

    def apply(self, msg_type, payload):
        self._index += 1
        return self.fsm.apply(self._index, msg_type, payload)


def assert_mirror_equals_rebuild(harness, rng=None):
    """The acceptance oracle: every dense plane the mirror maintains must
    equal the from-scratch recompute over the same snapshot and node
    order — including a random plan-overlay variant of initial_used."""
    snapshot = harness.state.snapshot()
    view = harness.mirror.sync(snapshot)
    assert isinstance(view, MirrorCluster)

    rebuilt = ColumnarCluster(list(view.nodes))
    assert np.array_equal(rebuilt.capacity, view.capacity)
    assert np.array_equal(rebuilt.reserved, view.reserved)
    assert np.array_equal(rebuilt.usable, view.usable)
    assert np.array_equal(rebuilt.single_nic, view.single_nic)
    assert {n.id for n in view.nodes} == {n.id for n in snapshot.nodes()}

    fresh_used = rebuilt.initial_used(snapshot)
    assert np.array_equal(fresh_used, view.mirror_used), (
        np.abs(fresh_used - view.mirror_used).max()
    )
    # the fast path must serve the identical matrix
    assert np.array_equal(view.initial_used(snapshot), fresh_used)

    # collision counts for every live (job, tg) pair
    pairs = {
        (a.job_id, a.task_group)
        for a in snapshot.allocs()
        if not a.terminal_status()
    }
    for job_id, tg in pairs:
        got = view.collision_counts(snapshot, job_id, tg)
        want = ColumnarCluster.collision_counts(rebuilt, snapshot, job_id, tg)
        assert np.array_equal(got, want), (job_id, tg)

    # plan overlay: stop a random subset of live allocs
    if rng is not None:
        live = [a for a in snapshot.allocs() if not a.terminal_status()]
        stops = rng.sample(live, min(len(live), rng.randint(0, 3)))
        if stops:
            plan = Plan()
            for a in stops:
                plan.node_update.setdefault(a.node_id, []).append(a)
            got = view.initial_used(snapshot, plan)
            want = ColumnarCluster.initial_used(rebuilt, snapshot, plan)
            assert np.array_equal(got, want)


def assert_planes_restore_identity(state):
    """The refactor's robustness claim: the persisted planes blob, the
    live planes, and a cold rebuild at the same raft index are all
    byte-identical — and survive a persist→restore round trip into a
    fresh store."""
    from nomad_tpu.state.planes import CommittedPlanes

    blob = state.persist()
    cold = CommittedPlanes.build_blob(state._gen)
    assert blob["planes"] == cold
    dst = StateStore()
    dst.restore(blob)
    assert dst.persist() == blob
    assert dst.planes.gen is dst._gen
    assert CommittedPlanes.build_blob(dst._gen) == blob["planes"]


class TestMirrorProperty:
    N_SEQUENCES = 200

    def _random_sequence(self, seed: int):
        rng = random.Random(seed)
        h = _Harness()
        jobs = []
        for _ in range(rng.randint(1, 3)):
            job = mock.job()
            h.apply(fsm_mod.JOB_REGISTER, {"job": job.to_dict()})
            jobs.append(h.state.job_by_id(job.namespace, job.id))
        for _ in range(rng.randint(3, 8)):
            h.apply(fsm_mod.NODE_REGISTER, {"node": mock.node().to_dict()})
        assert_mirror_equals_rebuild(h, rng)

        live = []
        for step in range(rng.randint(10, 26)):
            nodes = list(h.state.nodes())
            op = rng.random()
            if op < 0.35 and nodes:
                # place a batch of allocs, sometimes via a plan result
                job = rng.choice(jobs)
                allocs = [
                    make_alloc(
                        job,
                        rng.choice(nodes).id,
                        f"w[{step}-{i}]",
                        cpu=rng.choice([50, 100, 250]),
                        mem=rng.choice([32, 64, 128]),
                        disk=rng.choice([0, 10, 20]),
                        resources=rng.random() > 0.1,
                    )
                    for i in range(rng.randint(1, 4))
                ]
                if rng.random() < 0.5:
                    plan = Plan(eval_id=generate_uuid(), job=job)
                    for a in allocs:
                        plan.node_allocation.setdefault(a.node_id, []).append(a)
                    result = PlanResult(
                        node_allocation=plan.node_allocation
                    )
                    h.apply(
                        fsm_mod.APPLY_PLAN_RESULTS,
                        {
                            "plan": plan.to_dict(),
                            "result": result.to_dict(),
                        },
                    )
                else:
                    h.apply(
                        fsm_mod.ALLOC_UPDATE,
                        {"allocs": [a.to_dict() for a in allocs]},
                    )
                live.extend(allocs)
            elif op < 0.55 and live:
                # stop or fail a live alloc (client update path)
                a = live.pop(rng.randrange(len(live)))
                c = a.copy()
                c.client_status = rng.choice(
                    [ALLOC_CLIENT_STATUS_COMPLETE, ALLOC_CLIENT_STATUS_FAILED]
                )
                h.apply(
                    fsm_mod.ALLOC_CLIENT_UPDATE, {"allocs": [c.to_dict()]}
                )
            elif op < 0.72 and live:
                # in-place update: same id, new resources (or resources
                # appearing on a previously resource-less alloc)
                a = rng.choice(live)
                c = a.copy()
                tasks = (
                    a.allocated_resources.tasks
                    if a.allocated_resources is not None
                    else {a.job.task_groups[0].tasks[0].name: None}
                )
                c.allocated_resources = AllocatedResources(
                    tasks={
                        t: AllocatedTaskResources(
                            cpu=AllocatedCpuResources(
                                cpu_shares=rng.choice([60, 120, 300])
                            ),
                            memory=AllocatedMemoryResources(
                                memory_mb=rng.choice([48, 96])
                            ),
                        )
                        for t in tasks
                    },
                    shared=AllocatedSharedResources(
                        disk_mb=rng.choice([0, 15])
                    ),
                )
                h.apply(fsm_mod.ALLOC_UPDATE, {"allocs": [c.to_dict()]})
                a.allocated_resources = c.allocated_resources
            elif op < 0.76:
                h.apply(
                    fsm_mod.NODE_REGISTER, {"node": mock.node().to_dict()}
                )
            elif op < 0.80 and len(nodes) > 2:
                victim = rng.choice(nodes)
                h.apply(
                    fsm_mod.NODE_DEREGISTER, {"node_id": victim.id}
                )
                live = [a for a in live if a.node_id != victim.id]
            elif nodes:
                h.apply(
                    fsm_mod.NODE_STATUS_UPDATE,
                    {
                        "node_id": rng.choice(nodes).id,
                        "status": rng.choice(["down", "ready"]),
                    },
                )
            if rng.random() < 0.3:
                assert_mirror_equals_rebuild(h, rng)
        assert_mirror_equals_rebuild(h, rng)
        assert_planes_restore_identity(h.state)
        return h

    def test_mirror_equals_rebuild_over_random_event_sequences(self):
        """≥200 seeded sequences of node/alloc/plan events: the
        incremental mirror stays array-equal to a from-scratch rebuild at
        every checked point."""
        hits = rebuilds = 0
        for seed in range(self.N_SEQUENCES):
            h = self._random_sequence(seed)
            hits += h.mirror.counters["hits"]
            rebuilds += h.mirror.counters["rebuilds"]
        assert hits > 0
        # the deleted failure class stays deleted: with the planes patched
        # in-commit there is nothing to rebuild FROM — the counter must be
        # structurally zero across every churn sequence
        assert rebuilds == 0


class TestMirrorDegrade:
    def _seeded(self):
        h = _Harness()
        job = mock.job()
        h.apply(fsm_mod.JOB_REGISTER, {"job": job.to_dict()})
        job = h.state.job_by_id(job.namespace, job.id)
        for _ in range(4):
            h.apply(fsm_mod.NODE_REGISTER, {"node": mock.node().to_dict()})
        nodes = list(h.state.nodes())
        allocs = [
            make_alloc(job, nodes[i % len(nodes)].id, f"x[{i}]")
            for i in range(6)
        ]
        h.apply(
            fsm_mod.ALLOC_UPDATE, {"allocs": [a.to_dict() for a in allocs]}
        )
        h.mirror.sync(h.state.snapshot())
        return h, job, nodes, allocs

    def test_planes_fresh_by_construction_no_rebuilds(self):
        """Every FSM apply leaves the committed planes already stamped at
        the new generation — no sync, no frames, no rebuild machinery.
        The old sever/skew/gap/checksum degradations have nothing to
        degrade FROM: rebuild_reasons stays empty forever."""
        h, job, nodes, allocs = self._seeded()
        c = allocs[0].copy()
        c.client_status = ALLOC_CLIENT_STATUS_COMPLETE
        h.apply(fsm_mod.ALLOC_CLIENT_UPDATE, {"allocs": [c.to_dict()]})
        # committed before any reader asks: freshness IS gen identity
        assert h.state.planes.gen is h.state._gen
        assert h.state.planes.version == h.state.latest_index()
        assert_mirror_equals_rebuild(h)
        assert h.mirror.counters["rebuilds"] == 0
        assert h.mirror.counters["rebuild_reasons"] == {}

    def test_stale_snapshot_returns_none(self):
        h, job, nodes, allocs = self._seeded()
        old_snap = h.state.snapshot()
        c = allocs[0].copy()
        c.client_status = ALLOC_CLIENT_STATUS_COMPLETE
        h.apply(fsm_mod.ALLOC_CLIENT_UPDATE, {"allocs": [c.to_dict()]})
        assert h.mirror.sync(h.state.snapshot()) is not None
        # the mirror never runs backwards: an older snapshot gets None and
        # the caller builds a one-off legacy cluster
        assert h.mirror.sync(old_snap) is None
        assert h.mirror.counters["stale"] == 1

    def test_plane_divergence_audit_catches_corruption(self):
        """The watchdog's plane_divergence audit (state/planes.py): a
        clean world audits zero; a corrupted plane row — impossible by
        construction, which is exactly why it is audited — is reported."""
        h, job, nodes, allocs = self._seeded()
        planes = h.state.planes
        gen = h.state._gen
        verdict = planes.audit(gen)
        assert verdict == {"rows": 0, "recs": 0, "version": h.state.latest_index()}
        # rate-limited sampler serves and caches the same verdict
        assert planes.audit_sample(gen, min_interval_s=0.0) == verdict
        planes.used[0, 0] += 7  # corrupt behind the commit path's back
        bad = planes.audit(gen)
        assert bad["rows"] >= 1
        # the sampler re-serves the cached clean verdict inside the
        # interval, then observes the divergence once it re-runs
        assert planes.audit_sample(gen, min_interval_s=3600.0) == verdict
        assert planes.audit_sample(gen, min_interval_s=0.0)["rows"] >= 1
        planes.used[0, 0] -= 7

    def test_usage_vec_matches_sum_alloc_usage(self):
        h, job, nodes, allocs = self._seeded()
        for a in allocs:
            vec = usage_vec(a)
            want = ColumnarCluster.sum_alloc_usage([a])
            assert np.array_equal(np.asarray(vec), want)

    def test_device_state_tracks_host_used(self):
        jax = pytest.importorskip("jax")
        h, job, nodes, allocs = self._seeded()
        snap = h.state.snapshot()
        view = h.mirror.sync(snap)
        gen = getattr(snap, "_gen")
        ds = h.mirror.device_state(8, gen)
        assert ds is not None
        cap_dev, usable_dev, used_dev = ds
        n = len(view.nodes)
        assert np.array_equal(
            np.asarray(used_dev)[:n], view.mirror_used.astype(np.int32)
        )
        assert (np.asarray(used_dev)[n:] == 2**30).all()
        # patch: stop one alloc, re-sync, device rows follow via scatter
        c = allocs[0].copy()
        c.client_status = ALLOC_CLIENT_STATUS_COMPLETE
        h.apply(fsm_mod.ALLOC_CLIENT_UPDATE, {"allocs": [c.to_dict()]})
        snap2 = h.state.snapshot()
        view2 = h.mirror.sync(snap2)
        ds2 = h.mirror.device_state(8, getattr(snap2, "_gen"))
        assert ds2 is not None
        assert np.array_equal(
            np.asarray(ds2[2])[:n], view2.mirror_used.astype(np.int32)
        )
        # a stale generation is refused (caller falls back to host arrays)
        assert h.mirror.device_state(8, gen) is None


class TestSatellites:
    """The smaller riders: plan-fold knob + histogram, warmup buckets, and
    byte-size cluster-cache eviction."""

    def test_plan_apply_batch_size_histogram(self):
        from nomad_tpu import metrics

        metrics.reset()
        try:
            metrics.observe("plan.apply_batch_size", 3)
            metrics.observe("plan.apply_batch_size", 3)
            metrics.observe("plan.apply_batch_size", 16)
            hists = metrics.snapshot()["hists"]
            # base-2 bucketed: 3 lands in the [2,3] bucket keyed by its
            # floor; 16 is its own power-of-two bucket
            assert hists["plan.apply_batch_size"] == {2: 2, 16: 1}
            assert metrics.percentile("plan.apply_batch_size", 0.5) == 3
        finally:
            metrics.reset()

    def test_planner_fold_cap_is_instance_tunable(self):
        from nomad_tpu.core.plan_apply import Planner
        from nomad_tpu.state import StateStore

        p = Planner(StateStore())
        assert p.max_apply_batch == Planner.MAX_APPLY_BATCH == 16
        p.max_apply_batch = 32  # what the server stanza key sets
        assert p.max_apply_batch == 32
        assert Planner.MAX_APPLY_BATCH == 16  # default untouched

    def test_warmup_ladder_matches_production_buckets(self):
        """The prewarm ladder must round through the scheduler's own
        bucketing policy — the old hand-written ladder listed 51200 for
        the 50K-alloc headline while production pads 50K to 50176, so the
        prewarmed program was never the one the headline ran."""
        from nomad_tpu.tpu.batch_sched import _bucket
        from nomad_tpu.tpu.warmup import DEFAULT_SHAPES, bucket_shape

        assert bucket_shape(10000, 50000) == (_bucket(10000), _bucket(50000))
        assert (_bucket(10000), _bucket(50000)) in DEFAULT_SHAPES
        assert _bucket(50000) == 50176  # the regression the ladder had

    def test_shared_cluster_cache_evicts_by_bytes(self):
        from nomad_tpu.tpu import columnar

        saved = list(columnar._SHARED_CLUSTERS)
        saved_budget = columnar._SHARED_CLUSTERS_MAX_BYTES
        columnar._SHARED_CLUSTERS.clear()
        try:
            state = StateStore()
            state.upsert_nodes(1, [mock.node() for _ in range(4)])
            snap = state.snapshot()
            one = ColumnarCluster.shared(snap, list(snap.nodes()))
            # size the budget so ~2 of these clusters fit
            columnar._SHARED_CLUSTERS_MAX_BYTES = (
                columnar._cluster_nbytes(one) * 2
            )
            for i in range(6):
                s2 = StateStore()
                s2.upsert_nodes(1, [mock.node() for _ in range(4)])
                sn = s2.snapshot()
                ColumnarCluster.shared(sn, list(sn.nodes()))
            assert 1 <= len(columnar._SHARED_CLUSTERS) <= 2
        finally:
            columnar._SHARED_CLUSTERS_MAX_BYTES = saved_budget
            columnar._SHARED_CLUSTERS[:] = saved


class TestCommitPathConcurrency:
    def test_no_sync_needed_for_freshness(self):
        """The old mirror needed sync() to chase event frames; the
        committed planes are stamped inside the store's publish, so a
        reader that never calls sync still finds planes at the head
        generation after every write."""
        h = _Harness()
        for _ in range(3):
            h.apply(fsm_mod.NODE_REGISTER, {"node": mock.node().to_dict()})
            assert h.state.planes.gen is h.state._gen
            assert h.state.planes.version == h.state.latest_index()

    def test_concurrent_writes_and_reads_stay_exact(self):
        """Writer thread churns allocs through the FSM while reader
        threads hammer sync/initial_used/stats: every successful view
        must be exact for its snapshot, and no reader may ever observe a
        half-applied write transaction (the invalidate-then-commit
        protocol parks them on the scan fallback instead)."""
        import threading

        h = _Harness()
        job = mock.job()
        h.apply(fsm_mod.JOB_REGISTER, {"job": job.to_dict()})
        job = h.state.job_by_id(job.namespace, job.id)
        for _ in range(4):
            h.apply(fsm_mod.NODE_REGISTER, {"node": mock.node().to_dict()})
        nodes = list(h.state.nodes())

        stop = threading.Event()
        errors = []

        def reader():
            try:
                while not stop.is_set():
                    snap = h.state.snapshot()
                    view = h.mirror.sync(snap)
                    if view is None:
                        continue  # a write landed in between: legit stale
                    fresh = ColumnarCluster(list(view.nodes)).initial_used(snap)
                    got = view.initial_used(snap)
                    if not np.array_equal(got, fresh):
                        errors.append((got, fresh))
                        return
            except Exception as e:  # pragma: no cover - fail loud
                errors.append(e)

        readers = [threading.Thread(target=reader, daemon=True) for _ in range(3)]
        for t in readers:
            t.start()
        live = []
        for step in range(60):
            a = make_alloc(job, nodes[step % len(nodes)].id, f"c[{step}]")
            h.apply(fsm_mod.ALLOC_UPDATE, {"allocs": [a.to_dict()]})
            live.append(a)
            if len(live) > 5:
                c = live.pop(0).copy()
                c.client_status = ALLOC_CLIENT_STATUS_COMPLETE
                h.apply(
                    fsm_mod.ALLOC_CLIENT_UPDATE, {"allocs": [c.to_dict()]}
                )
        stop.set()
        for t in readers:
            t.join(timeout=10.0)
        assert not errors, errors[:1]
        assert h.mirror.counters["rebuilds"] == 0
        assert_mirror_equals_rebuild(h)

    def test_closed_view_refuses_service(self):
        h = _Harness()
        h.apply(fsm_mod.NODE_REGISTER, {"node": mock.node().to_dict()})
        assert isinstance(h.mirror.sync(h.state.snapshot()), MirrorCluster)
        h.mirror.close()
        assert h.mirror.sync(h.state.snapshot()) is None
        gen = h.state._gen
        assert h.mirror.device_state(8, gen) is None
        with h.mirror.locked_cluster(gen) as cluster:
            assert cluster is None


class TestDeviceStateSharded:
    """Mesh-sharded DeviceState (ISSUE 10): the mirror's device planes
    row-shard over the mesh and the dirty-row scatter refresh must keep
    the refreshed ``used`` buffer partitioned exactly like the one it
    replaces (the jitted scatter pins ``out_shardings`` — a replicated
    output would hand the next fused batch a layout the warmup never
    compiled, plus an O(N) gather per drain batch)."""

    def _mesh(self):
        import jax
        from jax.sharding import Mesh

        devices = jax.devices()
        if len(devices) < 8:
            pytest.skip("need 8 virtual devices")
        return Mesh(np.array(devices[:8]), ("nodes",))

    def test_sharded_planes_and_scatter_refresh(self):
        from nomad_tpu.tpu.mirror import DeviceState
        from nomad_tpu.tpu.shard import AXIS

        mesh = self._mesh()
        rng = np.random.default_rng(0)
        n, n_pad = 1000, 1024
        capacity = rng.integers(1000, 64000, (n, 4)).astype(np.int64)
        usable = rng.random((n, 2)).astype(np.float32) * 1000 + 1
        used = rng.integers(0, 900, (n, 4)).astype(np.int64)

        plain = DeviceState(1, n_pad, capacity, usable, used)
        ds = DeviceState(1, n_pad, capacity, usable, used, mesh=mesh)
        spec = ds.used.sharding.spec
        assert spec and spec[0] == AXIS, spec
        assert ds.capacity.sharding.spec[0] == AXIS

        # dirty-row refresh: same values as the unsharded state, and the
        # new buffer keeps the row sharding
        used_host = used.copy()
        used_host[7] += 5
        used_host[999] += 3
        for d in (plain, ds):
            d.pending.update({7, 999})
            d.refresh(used_host)
        got = np.asarray(ds.used)
        want = np.asarray(plain.used)
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(
            got[:n], np.clip(used_host, 0, 2**30).astype(np.int32)
        )
        assert ds.used.sharding.spec[0] == AXIS, (
            "scatter refresh dropped the row sharding"
        )

    def test_mirror_rebuilds_device_state_on_mesh_change(self):
        from nomad_tpu.tpu.mirror import DeviceState

        mesh = self._mesh()
        n, n_pad = 64, 64
        capacity = np.ones((n, 4), dtype=np.int64)
        usable = np.ones((n, 2), dtype=np.float32)
        used = np.zeros((n, 4), dtype=np.int64)
        # the mirror's device_state cache keys by (n_pad, epoch, mesh):
        # a cached unsharded state must never serve a sharded caller
        ds_plain = DeviceState(1, n_pad, capacity, usable, used)
        ds_mesh = DeviceState(1, n_pad, capacity, usable, used, mesh=mesh)
        assert ds_plain.mesh is None and ds_mesh.mesh is mesh
