"""Server core: wires state, broker, plan applier, workers, heartbeats, and
the RPC endpoint surface (ref nomad/server.go, nomad/*_endpoint.go).

This is the single-region control plane. Endpoints are plain methods (the
HTTP/API layer calls them; in-process clients call them directly, the same
way the reference's agent embeds both server and client). Raft replication is
replaced by the serialized state-store write path; multi-server consensus
attaches underneath in a later phase without changing this surface.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from ..state.store import StateStore
from ..structs.model import (
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    EVAL_TRIGGER_RETRY_FAILED_ALLOC,
    JOB_TYPE_CORE,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSTEM,
    NODE_STATUS_DOWN,
    NODE_STATUS_READY,
    Allocation,
    Evaluation,
    Job,
    Node,
    generate_uuid,
    now_ns,
)
from ..structs.node_class import compute_class
from .blocked_evals import BlockedEvals
from .broker import EvalBroker
from .plan_apply import Planner
from .worker import Worker

logger = logging.getLogger("nomad_tpu.server")

DEFAULT_HEARTBEAT_TTL = 30.0


class Server:
    """ref nomad/server.go:91"""

    def __init__(self, config: Optional[dict] = None):
        self.config = config or {}
        self.state = StateStore()
        self.eval_broker = EvalBroker(
            nack_timeout=self.config.get("nack_timeout", 60.0),
            delivery_limit=self.config.get("delivery_limit", 3),
            initial_nack_delay=self.config.get("initial_nack_delay", 1.0),
            subsequent_nack_delay=self.config.get("subsequent_nack_delay", 20.0),
        )
        self.blocked_evals = BlockedEvals(self.eval_broker)
        self.planner = Planner(self.state)
        self.planner.preemption_evals_fn = self._make_preemption_evals
        self.planner.on_preemption_evals = lambda evals: [
            self.eval_broker.enqueue(e) for e in evals if e is not None
        ]
        self.workers: list[Worker] = []
        self.heartbeat_ttl = self.config.get("heartbeat_ttl", DEFAULT_HEARTBEAT_TTL)
        self._heartbeat_timers: dict[str, threading.Timer] = {}
        self._lock = threading.Lock()
        self._running = False

    # ------------------------------------------------------------------
    # lifecycle (ref leader.go:180 establishLeadership)
    # ------------------------------------------------------------------
    def start(self, num_workers: int = 2):
        self.eval_broker.set_enabled(True)
        self.blocked_evals.set_enabled(True)
        self.planner.start()
        for i in range(num_workers):
            w = Worker(self, seed=self.config.get("seed"))
            self.workers.append(w)
            w.start()
        self._running = True
        self._reaper = threading.Thread(target=self._reap_failed_evals, daemon=True)
        self._reaper.start()

    def stop(self):
        self._running = False
        for w in self.workers:
            w.stop()
        self.workers = []
        self.planner.stop()
        self.eval_broker.set_enabled(False)
        self.blocked_evals.set_enabled(False)
        with self._lock:
            for t in self._heartbeat_timers.values():
                t.cancel()
            self._heartbeat_timers.clear()

    def _next_index(self):
        """Index sentinel: writes allocate their index inside the store's
        write transaction (passing None)."""
        return None

    def _reap_failed_evals(self):
        """Drain the _failed queue: mark evals failed and schedule a delayed
        follow-up retry (ref leader.go:505 reapFailedEvaluations)."""
        from .broker import FAILED_QUEUE

        follow_up_wait = self.config.get("failed_eval_followup_wait", 60.0)
        unblock_interval = self.config.get("failed_eval_unblock_interval", 60.0)
        last_unblock = time.monotonic()
        while self._running:
            # periodically retry max-plan-attempt blocked evals
            # (ref leader.go:588 periodicUnblockFailedEvals)
            if time.monotonic() - last_unblock >= unblock_interval:
                last_unblock = time.monotonic()
                self.blocked_evals.unblock_failed()
            ev, token = self.eval_broker.dequeue([FAILED_QUEUE], timeout=0.5)
            if ev is None:
                continue
            try:
                failed = ev.copy()
                failed.status = "failed"
                failed.status_description = (
                    "evaluation reached delivery limit"
                )
                follow_up = failed.create_failed_follow_up_eval(
                    int(follow_up_wait * 1e9)
                )
                self.state.upsert_evals(None, [failed, follow_up])
                self.eval_broker.enqueue(self.state.eval_by_id(follow_up.id))
                self.eval_broker.ack(ev.id, token)
            except Exception:
                logger.exception("failed-eval reaping error for %s", ev.id)

    # ------------------------------------------------------------------
    # Job endpoints (ref nomad/job_endpoint.go:80 Register)
    # ------------------------------------------------------------------
    def job_register(self, job: Job) -> str:
        """Returns the eval id created (empty for periodic/parameterized)."""
        self._validate_job(job)
        self.state.upsert_job(None, job)
        stored = self.state.job_by_id(job.namespace, job.id)

        if stored.is_periodic() or stored.is_parameterized():
            return ""

        ev = Evaluation(
            id=generate_uuid(),
            namespace=job.namespace,
            priority=stored.priority,
            type=stored.type,
            triggered_by=EVAL_TRIGGER_JOB_REGISTER,
            job_id=stored.id,
            job_modify_index=stored.modify_index,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
        self.state.upsert_evals(None, [ev])
        stored_eval = self.state.eval_by_id(ev.id)
        self.eval_broker.enqueue(stored_eval)
        return ev.id

    def job_deregister(self, namespace: str, job_id: str, purge: bool = False) -> str:
        """ref job_endpoint.go Deregister"""
        job = self.state.job_by_id(namespace, job_id)
        if job is None:
            raise KeyError(f"job not found: {job_id}")
        if purge:
            self.state.delete_job(None, namespace, job_id)
        else:
            stopped = job.copy()
            stopped.stop = True
            self.state.upsert_job(None, stopped)
        self.blocked_evals.untrack(namespace, job_id)
        ev = Evaluation(
            id=generate_uuid(),
            namespace=namespace,
            priority=job.priority,
            type=job.type,
            triggered_by=EVAL_TRIGGER_JOB_DEREGISTER,
            job_id=job_id,
            status=EVAL_STATUS_PENDING,
            create_time=now_ns(),
            modify_time=now_ns(),
        )
        self.state.upsert_evals(None, [ev])
        self.eval_broker.enqueue(self.state.eval_by_id(ev.id))
        return ev.id

    @staticmethod
    def _validate_job(job: Job):
        """Minimal admission checks (ref job_endpoint.go validateJob)."""
        if not job.id:
            raise ValueError("missing job ID")
        if not job.task_groups and not job.stop:
            raise ValueError("job requires at least one task group")
        if job.type == JOB_TYPE_CORE:
            raise ValueError("job type cannot be core")
        for tg in job.task_groups:
            if tg.count < 0:
                raise ValueError(f"task group {tg.name} count must be >= 0")
            if not tg.tasks:
                raise ValueError(f"task group {tg.name} requires at least one task")

    # ------------------------------------------------------------------
    # Node endpoints (ref nomad/node_endpoint.go:79 Register, :362
    # UpdateStatus, :894 GetClientAllocs)
    # ------------------------------------------------------------------
    def node_register(self, node: Node) -> dict:
        if not node.computed_class:
            compute_class(node)
        existed = self.state.node_by_id(node.id) is not None
        if not node.status:
            node.status = NODE_STATUS_READY
        self.state.upsert_node(None, node)
        self._reset_heartbeat(node.id)

        # new capacity: unblock matching blocked evals + system-job evals
        if not existed or node.status == NODE_STATUS_READY:
            self.blocked_evals.unblock(node.computed_class, self.state.latest_index())
            self._create_node_evals(node.id)
        return {"heartbeat_ttl": self.heartbeat_ttl}

    def node_update_status(self, node_id: str, status: str) -> dict:
        node = self.state.node_by_id(node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        if node.status != status:
            self.state.update_node_status(
                None, node_id, status, updated_at_ns=now_ns()
            )
            self._create_node_evals(node_id)
            if status == NODE_STATUS_READY:
                node = self.state.node_by_id(node_id)
                self.blocked_evals.unblock(
                    node.computed_class, self.state.latest_index()
                )
        if status != NODE_STATUS_DOWN:
            self._reset_heartbeat(node_id)
        return {"heartbeat_ttl": self.heartbeat_ttl}

    def node_heartbeat(self, node_id: str) -> dict:
        """ref node_endpoint.go UpdateStatus heartbeat path + heartbeat.go"""
        node = self.state.node_by_id(node_id)
        if node is None:
            raise KeyError(f"node not found: {node_id}")
        if node.status == NODE_STATUS_DOWN:
            # heartbeat revives a down node
            return self.node_update_status(node_id, NODE_STATUS_READY)
        self._reset_heartbeat(node_id)
        return {"heartbeat_ttl": self.heartbeat_ttl}

    def node_drain(self, node_id: str, drain: bool):
        """ref node_endpoint.go UpdateDrain"""
        self.state.update_node_drain(None, node_id, drain)
        if drain:
            # mark this node's allocs for migration
            updates = []
            for a in self.state.allocs_by_node_terminal(node_id, False):
                ac = a.copy()
                ac.desired_transition.migrate = True
                updates.append(ac)
            if updates:
                self.state.upsert_allocs(None, updates)
        self._create_node_evals(node_id)

    def node_update_eligibility(self, node_id: str, eligibility: str):
        self.state.update_node_eligibility(None, node_id, eligibility)

    def _reset_heartbeat(self, node_id: str):
        """ref heartbeat.go:33-212 resetHeartbeatTimer"""
        if not self._running:
            return
        with self._lock:
            old = self._heartbeat_timers.pop(node_id, None)
            if old is not None:
                old.cancel()
            t = threading.Timer(
                self.heartbeat_ttl, self._invalidate_heartbeat, args=(node_id,)
            )
            t.daemon = True
            self._heartbeat_timers[node_id] = t
            t.start()

    def _invalidate_heartbeat(self, node_id: str):
        """Heartbeat missed → node down → node evals (ref heartbeat.go:150)."""
        with self._lock:
            self._heartbeat_timers.pop(node_id, None)
        try:
            node = self.state.node_by_id(node_id)
            if node is not None and node.status != NODE_STATUS_DOWN:
                logger.warning("node %s missed heartbeat; marking down", node_id[:8])
                self.node_update_status(node_id, NODE_STATUS_DOWN)
        except Exception:
            logger.exception("heartbeat invalidation failed for %s", node_id)

    def _create_node_evals(self, node_id: str):
        """Create evals for all jobs with allocs on the node + system jobs
        (ref node_endpoint.go:1056 createNodeEvals)."""
        node = self.state.node_by_id(node_id)
        jobs: dict[tuple[str, str], Job] = {}
        for alloc in self.state.allocs_by_node(node_id):
            if alloc.job is not None and not alloc.terminal_status():
                jobs[(alloc.namespace, alloc.job_id)] = alloc.job
        for job in self.state.jobs_by_scheduler(JOB_TYPE_SYSTEM):
            if node is not None and node.datacenter in job.datacenters:
                jobs[(job.namespace, job.id)] = job

        evals = []
        for (ns, job_id), job in jobs.items():
            evals.append(
                Evaluation(
                    id=generate_uuid(),
                    namespace=ns,
                    priority=job.priority,
                    type=job.type,
                    triggered_by=EVAL_TRIGGER_NODE_UPDATE,
                    job_id=job_id,
                    node_id=node_id,
                    status=EVAL_STATUS_PENDING,
                    create_time=now_ns(),
                    modify_time=now_ns(),
                )
            )
        if evals:
            self.state.upsert_evals(None, evals)
            for ev in evals:
                self.eval_broker.enqueue(self.state.eval_by_id(ev.id))

    # ------------------------------------------------------------------
    # Client alloc sync (ref node_endpoint.go:894 GetClientAllocs, :362
    # UpdateAlloc)
    # ------------------------------------------------------------------
    def get_client_allocs(
        self, node_id: str, min_index: int = 0, timeout: float = 30.0
    ) -> tuple[list[Allocation], int]:
        """Blocking query the client long-polls for its allocs."""
        def query(snap):
            return snap.allocs_by_node(node_id)

        return self.state.blocking_query(query, min_index=min_index, timeout=timeout)

    def update_allocs(self, allocs: list[Allocation]):
        """Client-reported alloc status; failed allocs trigger new evals
        (ref node_endpoint.go UpdateAlloc:1006-1053)."""
        self.state.update_allocs_from_client(None, allocs)
        evals = []
        for update in allocs:
            stored = self.state.alloc_by_id(update.id)
            if stored is None or stored.job is None:
                continue
            if (
                stored.client_terminal_status()
                and not stored.server_terminal_status()
            ):
                evals.append(
                    Evaluation(
                        id=generate_uuid(),
                        namespace=stored.namespace,
                        priority=stored.job.priority,
                        type=stored.job.type,
                        triggered_by=EVAL_TRIGGER_RETRY_FAILED_ALLOC,
                        job_id=stored.job_id,
                        status=EVAL_STATUS_PENDING,
                        create_time=now_ns(),
                        modify_time=now_ns(),
                    )
                )
        if evals:
            # dedup per job
            seen = set()
            unique = []
            for ev in evals:
                key = (ev.namespace, ev.job_id)
                if key not in seen:
                    seen.add(key)
                    unique.append(ev)
            self.state.upsert_evals(None, unique)
            for ev in unique:
                self.eval_broker.enqueue(self.state.eval_by_id(ev.id))

    # ------------------------------------------------------------------
    # Eval endpoints (ref nomad/eval_endpoint.go)
    # ------------------------------------------------------------------
    def eval_dequeue(self, schedulers: list[str], timeout: float = 1.0):
        return self.eval_broker.dequeue(schedulers, timeout)

    def eval_ack(self, eval_id: str, token: str):
        self.eval_broker.ack(eval_id, token)

    def eval_nack(self, eval_id: str, token: str):
        self.eval_broker.nack(eval_id, token)

    # ------------------------------------------------------------------
    def _make_preemption_evals(self, result) -> list[Evaluation]:
        """Follow-up evals for jobs whose allocs were preempted
        (ref plan_apply.go preemption eval creation)."""
        jobs = {}
        for allocs in result.node_preemptions.values():
            for alloc in allocs:
                stored = self.state.alloc_by_id(alloc.id)
                job = stored.job if stored is not None else None
                if job is not None:
                    jobs[(alloc.namespace, alloc.job_id)] = job
        evals = []
        for (ns, job_id), job in jobs.items():
            evals.append(
                Evaluation(
                    id=generate_uuid(),
                    namespace=ns,
                    priority=job.priority,
                    type=job.type,
                    triggered_by="preemption",
                    job_id=job_id,
                    status=EVAL_STATUS_PENDING,
                    create_time=now_ns(),
                    modify_time=now_ns(),
                )
            )
        return evals
