"""Critical-path attribution over retained traces.

Aggregates the span trees the store kept into a per-stage attribution of
``eval.e2e``: for each trace, wall time is attributed to the DEEPEST span
covering each instant along the critical path (a parent's time not
covered by any child is the parent's own — e.g. the part of
``plan.submit`` that is neither queue wait nor verify nor commit), then
totals are aggregated across all retained traces and across the slowest
tail separately. The report names the bottleneck stage — reproducing the
ROADMAP item 2 finding (plan submit/queue-wait dominating eval e2e p99
while ``plan.evaluate`` stays ~1–2ms → the serialized applier) from
retained traces alone, no hand-assembled stage splits.
"""

from __future__ import annotations

from typing import Optional

#: stages owned by the plan applier's QUEUE/serialization: when one of
#: these is the bottleneck, the verdict names the applier (ROADMAP
#: item 1's knee). plan.commit / plan.commit_barrier moved out when the
#: applier pipelined (PR 13): commits now overlap verification, so a
#: commit-dominated tail is raft consensus latency (fsync/replication —
#: the worker legitimately waits for its entry to land), not the
#: applier convoying plans behind one loop
APPLIER_STAGES = frozenset({"plan.submit", "plan.queue_wait"})

#: consensus-round stages: a tail these own is commit latency, named as
#: such so operators chase raft (fsync, replication, batch fold), not
#: the applier loop
CONSENSUS_STAGES = frozenset({"plan.commit", "plan.commit_barrier"})

#: device-dispatch stages: a tail these own spent its time in (or
#: waiting on) the placement kernel. On a sharded run whose dispatch
#: spans carry per-placement collective rounds, the verdict names the
#: CROSS-SHARD COLLECTIVE CONVOY — ROADMAP item 2's bottleneck, read
#: from retained traces + the devprof round counter instead of guessed
DEVICE_STAGES = frozenset(
    {"drain.kernel_dispatch", "eval.plan_kernel", "drain.materialize"}
)
#: root-ish spans never named as a bottleneck "stage" (they ARE the e2e)
ROOT_NAMES = frozenset({"eval.e2e", "job.submit"})
#: stages whose wall time is COVERED ELSEWHERE in the tree and must not
#: enter the critical-path totals (their instants would be attributed
#: twice): drain.device_compute overlaps the host-side materialization
#: by design (double-buffering); fsm.apply_plan runs INSIDE the
#: plan.commit window (the commit waits on the apply); mirror.patch
#: lands after the root closed entirely (a late span at the next drain
#: batch's sync). All three are reported separately, not silently
#: dropped — hidden-by-overlap time is still the number to watch when
#: the overlap stops hiding it.
PARALLEL_STAGES = frozenset(
    {"drain.device_compute", "fsm.apply_plan", "mirror.patch"}
)


def build_tree(record: dict) -> tuple[list[dict], dict]:
    """(roots, children_by_span_id) for one trace record. A span whose
    parent is not in the record is a root — a connected trace has
    exactly one."""
    spans = record.get("spans") or []
    by_id = {s["span_id"]: s for s in spans}
    children: dict[str, list] = {}
    roots = []
    for s in spans:
        parent = s.get("parent_id")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    for kids in children.values():
        kids.sort(key=lambda s: s.get("start") or 0.0)
    return roots, children


def orphan_count(record: dict) -> int:
    """Spans not reachable from the trace's single true root (0 for a
    fully connected tree). Used by the chaos assertions."""
    roots, _ = build_tree(record)
    return max(0, len(roots) - 1)


def _attribute_span(span: dict, children: dict, acc: dict, par: dict):
    """Walk one span: child-covered intervals attribute to the children
    (recursively); uncovered remainder is the span's own. PARALLEL
    stages accumulate into ``par`` and do NOT advance the cursor — their
    wall time is covered by the host-side siblings they overlap."""
    start = span.get("start") or 0.0
    dur = (span.get("duration_ms") or 0.0) / 1e3
    end = start + dur
    cursor = start
    own = 0.0
    for child in children.get(span["span_id"], ()):
        if child["name"] in PARALLEL_STAGES:
            # full duration, no recursion: the parallel branch is a
            # leaf-shaped hardware-time report, not part of the path
            par[child["name"]] = (
                par.get(child["name"], 0.0)
                + (child.get("duration_ms") or 0.0) / 1e3
            )
            continue
        c0 = child.get("start") or 0.0
        c1 = c0 + (child.get("duration_ms") or 0.0) / 1e3
        if c0 > cursor:
            own += min(c0, end) - cursor
        _attribute_span(child, children, acc, par)
        cursor = max(cursor, min(c1, end))
    if end > cursor:
        own += end - cursor
    if own > 0:
        acc[span["name"]] = acc.get(span["name"], 0.0) + own


def attribute_trace(record: dict) -> tuple[dict, dict]:
    """(critical-path stage seconds, parallel-stage seconds) for one
    trace."""
    roots, children = build_tree(record)
    acc: dict[str, float] = {}
    par: dict[str, float] = {}
    for root in roots:
        _attribute_span(root, children, acc, par)
    return acc, par


def _mesh_dispatch_stats(records: list[dict]) -> dict:
    """Collective-round accounting from SHARDED dispatch spans: any span
    tagged ``shards > 1`` (drain.kernel_dispatch / eval.plan_kernel /
    drain.device_compute carry the topology), summing the
    ``collective_rounds`` / ``placements`` tags the exact-scan dispatch
    stamps. ``rounds_per_placement`` is None when no sharded span
    carried the counter (e.g. every sharded dispatch rode the runs
    planner, whose rounds resolve in devprof, not span tags)."""
    spans = rounds = placements = shards = wavefront = 0
    for r in records:
        for s in r.get("spans") or ():
            tags = s.get("tags") or {}
            try:
                width = int(tags.get("shards") or 1)
            except (TypeError, ValueError):
                continue
            if width <= 1:
                continue
            spans += 1
            shards = max(shards, width)
            rounds += int(tags.get("collective_rounds") or 0)
            placements += int(tags.get("placements") or 0)
            # wavefront dispatches stamp MEASURED rounds (a device
            # scalar read at the materialize sync) instead of the
            # one-per-lane static count — their presence is what turns
            # the convoy verdict into the amortized reading below
            if "wavefront" in (
                str(tags.get("planner") or ""), str(tags.get("mode") or "")
            ):
                wavefront += 1
    return {
        "sharded_spans": spans,
        "shards": shards,
        "rounds": rounds,
        "placements": placements,
        "wavefront_spans": wavefront,
        "rounds_per_placement": (
            round(rounds / placements, 4) if placements else None
        ),
    }


def _devprof_rounds_per_placement():
    """The device profiler's global collective-round ratio — the
    fallback when sharded spans exist but none carried the counter
    tags. Never imports jax; None when devprof is off or dark."""
    try:
        from ..debug import devprof

        return devprof.summary().get("collective_rounds_per_placement")
    except Exception:
        return None


def _stage_table(per_trace: list[dict]) -> dict:
    totals: dict[str, float] = {}
    for acc in per_trace:
        for name, sec in acc.items():
            totals[name] = totals.get(name, 0.0) + sec
    grand = sum(totals.values()) or 1.0
    return {
        name: {
            "seconds": round(sec, 6),
            "share": round(sec / grand, 4),
        }
        for name, sec in sorted(totals.items(), key=lambda e: -e[1])
    }


def attribute(records: list[dict], tail_pct: float = 0.99) -> dict:
    """Aggregate critical-path attribution across retained traces.

    Returns ``{traces, stages, tail: {threshold_ms, traces, stages},
    bottleneck, verdict}`` where ``tail`` covers the traces at or above
    the ``tail_pct`` duration quantile (≥1 trace), ``bottleneck`` is the
    dominant non-root stage of the tail, and ``verdict`` is the
    one-line human reading of it."""
    records = [r for r in records if r.get("spans")]
    if not records:
        return {
            "traces": 0, "stages": {}, "parallel": {}, "tail": {},
            "mesh": _mesh_dispatch_stats(()), "bottleneck": None,
            "verdict": "no retained traces",
        }
    per_trace = [(r, *attribute_trace(r)) for r in records]
    durations = sorted(r.get("duration_ms") or 0.0 for r in records)
    idx = min(len(durations) - 1, int(len(durations) * tail_pct))
    threshold = durations[idx]
    tail = [
        acc for r, acc, _ in per_trace
        if (r.get("duration_ms") or 0.0) >= threshold
    ]
    all_stages = _stage_table([acc for _, acc, _ in per_trace])
    tail_stages = _stage_table(tail)
    parallel_totals: dict[str, float] = {}
    for _, _, par in per_trace:
        for name, sec in par.items():
            parallel_totals[name] = parallel_totals.get(name, 0.0) + sec

    bottleneck = None
    for name in tail_stages:
        if name not in ROOT_NAMES:
            bottleneck = name
            break
    if bottleneck is None and tail_stages:
        bottleneck = next(iter(tail_stages))

    # the mesh-comm verdict (ROADMAP item 2): device stages dominate —
    # either the bottleneck is a dispatch stage, or the overlap-hidden
    # drain.device_compute outweighs every stage on the path — AND the
    # sharded dispatch spans (or the devprof round counter) show the
    # fill loop issuing ~one collective round per placement
    mesh = _mesh_dispatch_stats(records)
    top_stage_s = max(
        (row["seconds"] for name, row in tail_stages.items()
         if name not in ROOT_NAMES),
        default=0.0,
    )
    device_dominant = bottleneck in DEVICE_STAGES or (
        parallel_totals.get("drain.device_compute", 0.0) > top_stage_s
    )
    rpp = mesh["rounds_per_placement"]
    if rpp is None and mesh["sharded_spans"]:
        rpp = _devprof_rounds_per_placement()
    mesh["effective_rounds_per_placement"] = rpp
    convoy = (
        device_dominant
        and mesh["sharded_spans"] > 0
        and rpp is not None
        and rpp >= 0.5
    )

    if convoy:
        verdict = (
            "cross-shard collective convoy: device dispatch dominates "
            f"the p{int(tail_pct * 100)} tail and sharded dispatches "
            f"issued {rpp} collective rounds per placement over a "
            f"{mesh['shards']}-way mesh — the sequential fill loop pays "
            "one cross-mesh reduction per placement; batch conflict-free "
            "placements into wavefronts (ROADMAP item 2)"
        )
    elif (
        device_dominant
        and mesh["sharded_spans"] > 0
        and mesh.get("wavefront_spans", 0) > 0
        and rpp is not None
    ):
        # the negative of the convoy: wavefront dispatches present and
        # the MEASURED rounds-per-placement sits under the convoy
        # threshold — the mesh cost is amortized, look elsewhere
        verdict = (
            "device dispatch dominates but the wavefront planner "
            f"amortizes the mesh: {rpp} collective rounds per placement "
            f"over a {mesh['shards']}-way mesh "
            f"({mesh['wavefront_spans']} wavefront dispatch spans) — "
            "not a convoy; per-shard compute or host "
            "build/materialize is the next knee"
        )
    elif bottleneck in APPLIER_STAGES:
        verdict = (
            f"serialized plan applier: '{bottleneck}' owns "
            f"{tail_stages[bottleneck]['share'] * 100:.0f}% of the "
            f"p{int(tail_pct * 100)} tail (plan submit/queue-wait "
            "dominate while verification stays flat)"
        )
    elif bottleneck in CONSENSUS_STAGES:
        verdict = (
            f"consensus commit latency: '{bottleneck}' owns "
            f"{tail_stages[bottleneck]['share'] * 100:.0f}% of the "
            f"p{int(tail_pct * 100)} tail (the pipelined applier keeps "
            "verifying while entries commit; tune raft/fold, not the "
            "applier)"
        )
    elif bottleneck is not None:
        verdict = (
            f"'{bottleneck}' owns "
            f"{tail_stages[bottleneck]['share'] * 100:.0f}% of the "
            f"p{int(tail_pct * 100)} tail"
        )
    else:
        verdict = "no attributable stages"
    return {
        "traces": len(records),
        "stages": all_stages,
        # hardware time hidden by the double-buffer overlap: NOT in the
        # path totals (its instants are attributed to the host spans
        # covering the sync), reported so the overlap's headroom is
        # visible when it stops hiding the device
        "parallel": {
            name: round(sec, 6)
            for name, sec in sorted(parallel_totals.items())
        },
        "tail": {
            "threshold_ms": round(threshold, 3),
            "traces": len(tail),
            "stages": tail_stages,
        },
        # sharded dispatch accounting (the mesh-comm verdict's inputs,
        # kept visible even when the verdict names something else)
        "mesh": mesh,
        "bottleneck": bottleneck,
        "verdict": verdict,
    }


def format_report(report: dict, limit: int = 12) -> str:
    """Human-readable critical-path table (the CLI surface)."""
    lines = [
        f"retained traces: {report.get('traces', 0)}",
        f"verdict: {report.get('verdict', '')}",
        "",
        f"{'stage':<28} {'share':>7} {'seconds':>10}   "
        f"{'tail share':>10}",
    ]
    stages = report.get("stages") or {}
    tail_stages = (report.get("tail") or {}).get("stages") or {}
    for i, (name, row) in enumerate(stages.items()):
        if i >= limit:
            break
        tail_row = tail_stages.get(name)
        tail_share = (
            f"{tail_row['share'] * 100:.1f}%" if tail_row else "-"
        )
        lines.append(
            f"{name:<28} {row['share'] * 100:>6.1f}% "
            f"{row['seconds']:>10.4f}   {tail_share:>10}"
        )
    parallel = report.get("parallel") or {}
    if parallel:
        lines.append("")
        for name, sec in parallel.items():
            lines.append(
                f"{name:<28} (parallel, overlap-hidden) {sec:>10.4f}s"
            )
    return "\n".join(lines)
