"""Deployment watcher end-to-end tests (semantics ref:
nomad/deploymentwatcher/deployments_watcher_test.go).

All scenarios run on the in-process dev agent with the mock driver; health
is reported by the client's alloc health watcher, and the leader's
deployment watcher drives promotion / failure / revert.
"""

import time

from nomad_tpu import mock
from nomad_tpu.structs.model import (
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_PAUSED,
    DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    UpdateStrategy,
)

SECOND_NS = 1_000_000_000


def _deploy_job(count=2, canary=0, auto_promote=False, auto_revert=False,
                run_for=60, exit_code=0):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = count
    tg.tasks[0].driver = "mock_driver"
    tg.tasks[0].config = {"run_for": run_for, "exit_code": exit_code}
    tg.tasks[0].resources.networks = []
    tg.restart_policy.attempts = 0
    tg.restart_policy.mode = "fail"
    tg.reschedule_policy.attempts = 0
    tg.reschedule_policy.unlimited = False
    tg.update = UpdateStrategy(
        max_parallel=count,
        health_check="task_states",
        # tasks must stay up 300ms to count healthy, so crash-looping
        # tasks (run_for 0.1) report unhealthy instead of racing to healthy
        min_healthy_time=int(0.3 * SECOND_NS),
        healthy_deadline=10 * SECOND_NS,
        progress_deadline=30 * SECOND_NS,
        canary=canary,
        auto_promote=auto_promote,
        auto_revert=auto_revert,
    )
    return job


def _wait(fn, timeout=20.0, interval=0.1):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        last = fn()
        if last:
            return last
        time.sleep(interval)
    return last


class TestDeploymentE2E:
    def _agent(self):
        from nomad_tpu.agent import DevAgent

        agent = DevAgent(num_clients=2, server_config={"seed": 7})
        agent.start()
        return agent

    def test_initial_deployment_succeeds_and_stabilizes(self):
        agent = self._agent()
        try:
            job = _deploy_job(count=2)
            agent.run_job(job)

            d = _wait(
                lambda: agent.state.latest_deployment_by_job_id(
                    job.namespace, job.id
                )
            )
            assert d is not None, "no deployment created"

            ok = _wait(
                lambda: (
                    agent.state.deployment_by_id(d.id).status
                    == DEPLOYMENT_STATUS_SUCCESSFUL
                )
            )
            final = agent.state.deployment_by_id(d.id)
            assert ok, (final.status, final.status_description, final.task_groups)
            # successful deployment marks the job version stable
            assert agent.state.job_by_id(job.namespace, job.id).stable
        finally:
            agent.stop()

    def test_canary_auto_promote(self):
        agent = self._agent()
        try:
            job = _deploy_job(count=2)
            agent.run_job(job)
            _wait(
                lambda: (d := agent.state.latest_deployment_by_job_id(
                    job.namespace, job.id
                )) is not None and d.status == DEPLOYMENT_STATUS_SUCCESSFUL
            )

            # v1 with a canary + auto-promote
            v1 = job.copy()
            v1.task_groups[0].tasks[0].config = {"run_for": 61, "exit_code": 0}
            v1.task_groups[0].update.canary = 1
            v1.task_groups[0].update.auto_promote = True
            agent.run_job(v1)

            def canary_deployment():
                d = agent.state.latest_deployment_by_job_id(job.namespace, job.id)
                if d is not None and any(
                    s.desired_canaries > 0 for s in d.task_groups.values()
                ):
                    return d
                return None

            d = _wait(canary_deployment)
            assert d is not None, "no canary deployment created"

            ok = _wait(
                lambda: (
                    agent.state.deployment_by_id(d.id).status
                    == DEPLOYMENT_STATUS_SUCCESSFUL
                ),
                timeout=30,
            )
            final = agent.state.deployment_by_id(d.id)
            assert ok, (final.status, final.status_description, final.task_groups)
            assert all(s.promoted for s in final.task_groups.values())
        finally:
            agent.stop()

    def test_unhealthy_alloc_fails_deployment_and_reverts(self):
        agent = self._agent()
        try:
            job = _deploy_job(count=1, auto_revert=True)
            agent.run_job(job)
            _wait(
                lambda: (d := agent.state.latest_deployment_by_job_id(
                    job.namespace, job.id
                )) is not None and d.status == DEPLOYMENT_STATUS_SUCCESSFUL
            )
            assert agent.state.job_by_id(job.namespace, job.id).stable
            v0 = agent.state.job_by_id(job.namespace, job.id).version

            # v1 crashes immediately → unhealthy → deployment fails →
            # auto-revert re-registers the stable v0 spec as a new version
            v1 = job.copy()
            v1.task_groups[0].tasks[0].config = {"run_for": 0.1, "exit_code": 1}
            agent.run_job(v1)

            def failed_deployment():
                for d in agent.state.deployments():
                    if (
                        d.job_id == job.id
                        and d.status == DEPLOYMENT_STATUS_FAILED
                    ):
                        return d
                return None

            d = _wait(failed_deployment, timeout=30)
            assert d is not None, [
                (x.status, x.status_description)
                for x in agent.state.deployments()
            ]
            assert "rolling back" in d.status_description

            # job rolled back: newest version runs the healthy config
            reverted = _wait(
                lambda: (
                    agent.state.job_by_id(job.namespace, job.id).version
                    > v0 + 1
                )
            )
            assert reverted
            cur = agent.state.job_by_id(job.namespace, job.id)
            assert cur.task_groups[0].tasks[0].config["exit_code"] == 0
        finally:
            agent.stop()

    def test_manual_pause_and_fail(self):
        agent = self._agent()
        try:
            job = _deploy_job(count=1)
            # long min_healthy_time keeps the deployment running long
            # enough to pause it deterministically
            job.task_groups[0].update.min_healthy_time = 60 * SECOND_NS
            agent.run_job(job)
            d = _wait(
                lambda: agent.state.latest_deployment_by_job_id(
                    job.namespace, job.id
                )
            )
            assert d is not None

            agent.server.deployment_pause(d.id, True)
            assert (
                agent.state.deployment_by_id(d.id).status
                == DEPLOYMENT_STATUS_PAUSED
            )
            agent.server.deployment_pause(d.id, False)
            assert (
                agent.state.deployment_by_id(d.id).status
                == DEPLOYMENT_STATUS_RUNNING
            )

            agent.server.deployment_fail(d.id)
            final = agent.state.deployment_by_id(d.id)
            assert final.status == DEPLOYMENT_STATUS_FAILED
        finally:
            agent.stop()


class TestDeploymentHTTP:
    def test_deployment_http_surface(self):
        from nomad_tpu.agent import DevAgent
        from nomad_tpu.api import ApiClient, HTTPServer

        agent = DevAgent(num_clients=1, server_config={"seed": 7})
        agent.start()
        http = HTTPServer(agent.server, port=0, agent=agent)
        http.start()
        client = ApiClient(address=http.address)
        try:
            job = _deploy_job(count=1)
            agent.run_job(job)
            d = _wait(
                lambda: agent.state.latest_deployment_by_job_id(
                    job.namespace, job.id
                )
            )
            assert d is not None

            got = client.deployment(d.id)
            assert got["job_id"] == job.id
            assert client.job_deployments(job.id)
            allocs = _wait(lambda: client.deployment_allocations(d.id))
            assert allocs and allocs[0]["JobID"] == job.id

            _wait(
                lambda: client.deployment(d.id)["status"]
                == DEPLOYMENT_STATUS_SUCCESSFUL
            )

            # revert via HTTP: v1 then back to v0
            v1 = job.copy()
            v1.task_groups[0].tasks[0].config = {"run_for": 61}
            agent.run_job(v1)
            _wait(
                lambda: agent.state.job_by_id(job.namespace, job.id).version >= 1
            )
            out = client.job_revert(job.id, 0)
            assert out["EvalID"]
            versions = client.job_versions(job.id)
            assert len(versions) >= 3
        finally:
            http.stop()
            agent.stop()


class TestProgressDeadline:
    def test_progress_deadline_expiry_fails_deployment(self):
        """A deployment whose allocs can never become healthy before the
        per-group progress deadline is failed by the watcher with the
        deadline description (ref deployments_watcher progress deadline;
        deployment_watcher.py DESC_PROGRESS_DEADLINE)."""
        from nomad_tpu.agent import DevAgent

        agent = DevAgent(num_clients=1, server_config={"seed": 7})
        agent.start()
        try:
            job = _deploy_job(count=1)
            tg = job.task_groups[0]
            # healthy requires 5s of uptime but the deadline is 1s: no
            # alloc can make progress in time — and none report UNhealthy
            # either, so only the deadline can fail the deployment
            tg.update.min_healthy_time = 5 * SECOND_NS
            tg.update.healthy_deadline = 20 * SECOND_NS
            tg.update.progress_deadline = 1 * SECOND_NS
            agent.run_job(job)

            def deadline_failed():
                for d in agent.state.deployments():
                    if (
                        d.job_id == job.id
                        and d.status == DEPLOYMENT_STATUS_FAILED
                        and "progress deadline" in d.status_description
                    ):
                        return d
                return None

            d = _wait(deadline_failed, timeout=30)
            assert d is not None, [
                (x.status, x.status_description)
                for x in agent.state.deployments()
            ]
        finally:
            agent.stop()

    def test_healthy_alloc_extends_progress_deadline(self):
        """Each healthy alloc re-arms the deadline: a rollout whose steps
        each fit inside the window completes even though the TOTAL time
        exceeds one deadline period."""
        from nomad_tpu.agent import DevAgent

        agent = DevAgent(num_clients=2, server_config={"seed": 7})
        agent.start()
        try:
            job = _deploy_job(count=4)
            tg = job.task_groups[0]
            tg.update.max_parallel = 1  # one-at-a-time rollout
            tg.update.min_healthy_time = int(0.4 * SECOND_NS)
            tg.update.progress_deadline = 3 * SECOND_NS
            agent.run_job(job)
            _wait(
                lambda: (d := agent.state.latest_deployment_by_job_id(
                    job.namespace, job.id
                )) is not None and d.status == DEPLOYMENT_STATUS_SUCCESSFUL,
                timeout=30,
            )
            d = agent.state.latest_deployment_by_job_id(
                job.namespace, job.id
            )
            assert d.status == DEPLOYMENT_STATUS_SUCCESSFUL, (
                d.status, d.status_description,
            )
        finally:
            agent.stop()
