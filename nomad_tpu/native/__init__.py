"""Native helpers (the framework's C++ tier).

The reference's only first-party native surface is the libcontainer/nsenter
isolation layer under drivers/shared/executor (SURVEY §2.9); here that is
``nsexec.cc``, compiled on demand with the system toolchain and cached
next to the source (or in NOMAD_TPU_NATIVE_DIR when the package directory
is read-only)."""

from __future__ import annotations

import os
import shutil
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_LOCK = threading.Lock()


class NativeBuildError(RuntimeError):
    pass


def _build_dir() -> str:
    d = os.environ.get("NOMAD_TPU_NATIVE_DIR")
    if d:
        os.makedirs(d, exist_ok=True)
        return d
    return _HERE


def nsexec_path(rebuild: bool = False) -> str:
    """Path to the compiled nsexec binary, building it if stale or absent."""
    src = os.path.join(_HERE, "nsexec.cc")
    out = os.path.join(_build_dir(), "nsexec")
    with _BUILD_LOCK:
        if (
            not rebuild
            and os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)
        ):
            return out
        cxx = shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")
        if cxx is None:
            raise NativeBuildError("no C++ compiler on PATH")
        tmp = out + ".tmp"
        proc = subprocess.run(
            [cxx, "-O2", "-static", "-o", tmp, src],
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            # retry without -static (glibc-only toolchains)
            proc = subprocess.run(
                [cxx, "-O2", "-o", tmp, src], capture_output=True, text=True
            )
        if proc.returncode != 0:
            raise NativeBuildError(f"nsexec build failed:\n{proc.stderr}")
        os.replace(tmp, out)
        return out


_FASTOBJ = None
_FASTOBJ_TRIED = False


def fastobj():
    """The C batch-materialization module (_fastobj.c), compiled on demand
    like nsexec; returns None when no toolchain is available so callers
    fall back to the pure-Python loops (same semantics, ~5x slower at
    50K-alloc plan scale)."""
    global _FASTOBJ, _FASTOBJ_TRIED
    if _FASTOBJ_TRIED:
        return _FASTOBJ
    with _BUILD_LOCK:
        if _FASTOBJ_TRIED:
            return _FASTOBJ
        try:
            _FASTOBJ = _build_fastobj()
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "C fast-path (_fastobj) unavailable, using the pure-Python "
                "loops (~5x slower at 50K-alloc plan scale): %s", e
            )
            _FASTOBJ = None
        _FASTOBJ_TRIED = True
    return _FASTOBJ


def _build_fastobj():
    import importlib.machinery
    import importlib.util
    import sysconfig

    import sys

    src = os.path.join(_HERE, "_fastobj.c")
    # cache tag in the filename: a stale .so built against another
    # interpreter ABI must never be dlopen'd (mtime alone can't tell)
    out = os.path.join(
        _build_dir(), f"_fastobj.{sys.implementation.cache_tag}.so"
    )
    if not os.path.exists(out) or os.path.getmtime(out) < os.path.getmtime(src):
        cc = (
            shutil.which("cc")
            or shutil.which("gcc")
            or shutil.which("clang")
        )
        if cc is None:
            raise NativeBuildError("no C compiler on PATH")
        inc = sysconfig.get_paths()["include"]
        # per-process tmp name: _BUILD_LOCK is per-process, so two fresh
        # processes may build concurrently — each must os.replace its own
        # fully-written file (the rename is atomic; last writer wins)
        tmp = f"{out}.tmp.{os.getpid()}.so"
        proc = subprocess.run(
            [cc, "-O2", "-shared", "-fPIC", f"-I{inc}", "-o", tmp, src],
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            raise NativeBuildError(f"_fastobj build failed:\n{proc.stderr}")
        os.replace(tmp, out)
    loader = importlib.machinery.ExtensionFileLoader("_fastobj", out)
    spec = importlib.util.spec_from_file_location("_fastobj", out, loader=loader)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


def isolation_available() -> bool:
    """Whether namespace isolation works here (nsexec --check)."""
    try:
        binary = nsexec_path()
    except NativeBuildError:
        return False
    try:
        return subprocess.run([binary, "--check"], timeout=10).returncode == 0
    except Exception:
        return False
