"""Web UI operational-surface tests (ref ui/app/adapters/deployment.js
promote + the job-deployment components; ui exec/fs/stats routes).

The SPA is a single HTML file whose behavior is fetch calls against
/v1/*; these tests drive the EXACT request sequences the UI issues —
same paths, methods, and bodies as the inline handlers (deployAction,
statsPoll, the search box, the evaluation drill-down) — so a green run
means the buttons work end-to-end, not just that the endpoints exist.
"""

import time

from nomad_tpu import mock
from nomad_tpu.structs.model import (
    DEPLOYMENT_STATUS_SUCCESSFUL,
    UpdateStrategy,
)
from nomad_tpu.ui import INDEX_HTML

SECOND_NS = 1_000_000_000


def _wait(fn, timeout=20.0, interval=0.1):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        last = fn()
        if last:
            return last
        time.sleep(interval)
    return last


def _agent_http():
    from nomad_tpu.agent import DevAgent
    from nomad_tpu.api import ApiClient, HTTPServer

    agent = DevAgent(num_clients=1, server_config={"seed": 11})
    agent.start()
    http = HTTPServer(agent.server, port=0, agent=agent)
    http.start()
    return agent, http, ApiClient(address=http.address)


def _deploy_job(canary=0, run_for=60):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 2
    tg.tasks[0].driver = "mock_driver"
    tg.tasks[0].config = {"run_for": run_for, "exit_code": 0}
    tg.tasks[0].resources.networks = []
    tg.restart_policy.attempts = 0
    tg.reschedule_policy.attempts = 0
    tg.reschedule_policy.unlimited = False
    tg.update = UpdateStrategy(
        max_parallel=2,
        health_check="task_states",
        min_healthy_time=int(0.3 * SECOND_NS),
        healthy_deadline=10 * SECOND_NS,
        progress_deadline=30 * SECOND_NS,
        canary=canary,
        auto_promote=False,
    )
    return job


class TestUiServed:
    def test_index_served_with_operational_controls(self):
        agent, http, client = _agent_http()
        try:
            import urllib.request

            html = (
                urllib.request.urlopen(http.address + "/ui", timeout=10)
                .read()
                .decode()
            )
            assert html == INDEX_HTML
            # the operational surfaces this round added must be wired in
            for needle in (
                "deployAction",  # promote/fail/pause/resume buttons
                "Promote canaries",
                "taskAction",  # task restart / signal
                "statsPoll",  # live per-task stats sparklines
                "sparkline",
                "evaluation(id)",  # eval drill-down route
                "Placement failures",
                'id="search"',  # global search box
                "'/v1/search'",
            ):
                assert needle in html, f"UI missing {needle!r}"
        finally:
            http.stop()
            agent.stop()


class TestUiCanaryPromote:
    def test_canary_promote_through_ui_request_sequence(self):
        """v0 deploys, v1 adds a canary; the UI's deployment page request
        chain (list → detail → allocations → promote with {All:true} →
        re-render) promotes it and the deployment completes."""
        agent, http, client = _agent_http()
        try:
            job = _deploy_job()
            agent.run_job(job)
            _wait(
                lambda: (
                    d := agent.state.latest_deployment_by_job_id(
                        job.namespace, job.id
                    )
                )
                is not None
                and d.status == DEPLOYMENT_STATUS_SUCCESSFUL
            )

            v1 = job.copy()
            v1.task_groups[0].tasks[0].config = {"run_for": 61, "exit_code": 0}
            v1.task_groups[0].update.canary = 1
            agent.run_job(v1)

            # the deployments LIST as the UI reads it (snake_case rows)
            def ui_list_row():
                rows, _ = client.get("/v1/deployments")
                for d in rows:
                    if d["job_id"] == job.id and any(
                        s["desired_canaries"] > 0
                        for s in d["task_groups"].values()
                    ):
                        return d
                return None

            row = _wait(ui_list_row)
            assert row is not None, "canary deployment never listed"
            dep_id = row["id"]

            # detail page data: wait until the canary is placed + healthy,
            # i.e. the moment the Promote button enables
            def promotable():
                d, _ = client.get("/v1/deployment/" + dep_id)
                active = d["status"] in ("running", "paused")
                needs = any(
                    s["desired_canaries"] > 0 and not s["promoted"]
                    for s in d["task_groups"].values()
                )
                healthy = all(
                    s["healthy_allocs"] >= s["desired_canaries"]
                    for s in d["task_groups"].values()
                    if s["desired_canaries"] > 0
                )
                return d if (active and needs and healthy) else None

            assert _wait(promotable), "canary never became promotable"

            # the detail page also loads the deployment's allocations
            allocs, _ = client.get("/v1/deployment/allocations/" + dep_id)
            assert allocs and allocs[0]["JobID"] == job.id
            # canary allocs carry DeploymentStatus for the Healthy column
            assert any(a.get("DeploymentStatus") for a in allocs)

            # deployAction('promote', {All:true}) — the button's exact call
            out, _ = client.put(
                "/v1/deployment/promote/" + dep_id, body={"All": True}
            )
            assert out["DeploymentModifyIndex"] > 0

            # re-render shows the group promoted; deployment completes
            def promoted():
                d, _ = client.get("/v1/deployment/" + dep_id)
                return all(
                    s["promoted"]
                    for s in d["task_groups"].values()
                    if s["desired_canaries"] > 0
                ) and d

            assert _wait(promoted), "promote did not take effect"
            final = _wait(
                lambda: (d := client.get("/v1/deployment/" + dep_id)[0])[
                    "status"
                ]
                == DEPLOYMENT_STATUS_SUCCESSFUL
                and d,
                timeout=30,
            )
            assert final, "deployment did not complete after promote"

            # pause/resume buttons on a fresh deployment: v2 rollout
            v2 = job.copy()
            v2.task_groups[0].tasks[0].config = {"run_for": 62, "exit_code": 0}
            agent.run_job(v2)
            d2 = _wait(
                lambda: (
                    d := agent.state.latest_deployment_by_job_id(
                        job.namespace, job.id
                    )
                )
                is not None
                and d.id != dep_id
                and d
            )
            client.put(
                "/v1/deployment/pause/" + d2.id, body={"Pause": True}
            )
            assert (
                client.get("/v1/deployment/" + d2.id)[0]["status"] == "paused"
            )
            client.put(
                "/v1/deployment/pause/" + d2.id, body={"Pause": False}
            )
            assert (
                client.get("/v1/deployment/" + d2.id)[0]["status"] == "running"
            )
        finally:
            http.stop()
            agent.stop()


class TestUiEvalAndSearch:
    def test_eval_placement_failure_breakdown_and_search(self):
        agent, http, client = _agent_http()
        try:
            # an unplaceable job: memory demand beyond any node
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.tasks[0].driver = "mock_driver"
            tg.tasks[0].resources.memory_mb = 10**9
            tg.tasks[0].resources.networks = []
            tg.reschedule_policy.attempts = 0
            tg.reschedule_policy.unlimited = False
            agent.run_job(job)

            # evaluations list as the UI renders it: the row must expose
            # failed_tg_allocs so the 'Placement Failures' column lights up
            def failed_eval():
                evals, _ = client.get("/v1/evaluations")
                for e in evals:
                    if e["job_id"] == job.id and e.get("failed_tg_allocs"):
                        return e
                return None

            row = _wait(failed_eval)
            assert row is not None, "no eval with placement failures"

            # the eval drill-down page's metric breakdown
            ev, _ = client.get("/v1/evaluation/" + row["id"])
            metric = ev["failed_tg_allocs"][tg.name]
            assert metric["nodes_evaluated"] >= 1
            assert metric.get("dimension_exhausted") or metric.get(
                "constraint_filtered"
            ), metric

            # the search box: PUT /v1/search {Prefix, Context:'all'}
            res, _ = client.put(
                "/v1/search",
                body={"Prefix": job.id[:8], "Context": "all"},
            )
            assert job.id in res["matches"]["jobs"]
        finally:
            http.stop()
            agent.stop()


class TestUiTaskDrilldown:
    def test_task_states_events_and_live_stats(self):
        agent, http, client = _agent_http()
        try:
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": "/bin/sh", "args": ["-c", "sleep 30"]}
            task.resources.networks = []
            agent.run_job(job)

            def running_alloc():
                allocs, _ = client.get(f"/v1/job/{job.id}/allocations")
                for a in allocs:
                    if a["ClientStatus"] == "running":
                        return a
                return None

            stub = _wait(running_alloc)
            assert stub, "alloc never ran"

            # the allocation page's task panel: states + events
            alloc, _ = client.get("/v1/allocation/" + stub["ID"])
            states = alloc["task_states"]
            assert states, "no task states"
            ts = states[task.name]
            assert ts["state"] == "running"
            events = ts["events"]
            assert events and all(
                "type" in e and "message" in e and "time" in e for e in events
            )
            assert any(e["type"] == "Started" for e in events), events

            # statsPoll's endpoint: per-task cpu/rss for the sparklines
            stats, _ = client.get(
                f"/v1/client/allocation/{stub['ID']}/stats"
            )
            usage = stats["tasks"][task.name]
            assert "cpu_percent" in usage and "rss_bytes" in usage
            assert usage["rss_bytes"] >= 0

            # taskAction('restart'): the button's exact call
            out, _ = client.put(
                f"/v1/client/allocation/{stub['ID']}/restart",
                body={"TaskName": task.name},
            )
            assert out["tasks"] == [task.name]
            _wait(
                lambda: client.get("/v1/allocation/" + stub["ID"])[0][
                    "task_states"
                ][task.name]["restarts"]
                >= 1
            )
            restarted = client.get("/v1/allocation/" + stub["ID"])[0]
            assert restarted["task_states"][task.name]["restarts"] >= 1
        finally:
            http.stop()
            agent.stop()


class TestUiNodeActions:
    def test_drain_and_eligibility_through_ui_request_sequence(self):
        """The node page's operator buttons: drain with default spec,
        stop-drain with MarkEligible, and the eligibility toggles — the
        exact PUT bodies the inline nodeAction handler sends."""
        agent, http, client = _agent_http()
        try:
            node_id = agent.clients[0].node.id

            # Drain (DrainSpec {} = enable with defaults). With nothing
            # placed the drainer completes immediately, but the node must
            # come out ineligible until explicitly re-marked.
            client.put(f"/v1/node/{node_id}/drain", body={"DrainSpec": {}})
            assert _wait(
                lambda: client.get("/v1/node/" + node_id)[0][
                    "scheduling_eligibility"
                ]
                == "ineligible"
            ), "drain did not mark the node ineligible"

            # Stop drain, restoring eligibility
            client.put(
                f"/v1/node/{node_id}/drain", body={"MarkEligible": True}
            )
            n, _ = client.get("/v1/node/" + node_id)
            assert n["drain"] is False
            assert n["scheduling_eligibility"] == "eligible"

            # Eligibility toggles
            client.put(
                f"/v1/node/{node_id}/eligibility",
                body={"Eligibility": "ineligible"},
            )
            assert (
                client.get("/v1/node/" + node_id)[0][
                    "scheduling_eligibility"
                ]
                == "ineligible"
            )
            client.put(
                f"/v1/node/{node_id}/eligibility",
                body={"Eligibility": "eligible"},
            )
            assert (
                client.get("/v1/node/" + node_id)[0][
                    "scheduling_eligibility"
                ]
                == "eligible"
            )

            # the SPA carries the controls
            import urllib.request

            html = (
                urllib.request.urlopen(http.address + "/ui", timeout=10)
                .read()
                .decode()
            )
            for needle in ("nodeAction", "Drain", "Mark ineligible"):
                assert needle in html, f"UI missing {needle!r}"
        finally:
            http.stop()
            agent.stop()
