"""Java task driver (ref drivers/java/driver.go): launch a jar or class
under the JVM, optionally inside the nsexec isolation shepherd the exec
driver uses.

Task config:
  jar_path     path to the jar (mutually exclusive with class)
  class        main class (uses class_path)
  class_path   -cp value (default task dir)
  jvm_options  list of JVM flags (-Xmx512m, ...)
  args         program arguments
"""

from __future__ import annotations

import shutil
import subprocess

from ..client.driver import RawExecDriver, TaskHandle
from ..structs.model import Task


class JavaDriver(RawExecDriver):
    name = "java"

    def __init__(self, binary: str = ""):
        super().__init__()
        self._java = binary or shutil.which("java")
        self._version = ""
        if self._java:
            self._version = self._probe_version()

    def _probe_version(self) -> str:
        """``java -version`` prints like 'openjdk version "11.0.2" ...'
        on stderr (ref java/driver.go parseJavaVersionOutput)."""
        try:
            out = subprocess.run(
                [self._java, "-version"],
                capture_output=True,
                text=True,
                timeout=10,
            )
            for line in (out.stderr + out.stdout).splitlines():
                if "version" in line and '"' in line:
                    return line.split('"')[1]
        except (OSError, subprocess.TimeoutExpired):
            pass
        return ""

    def fingerprint(self) -> dict:
        detected = bool(self._java)
        attrs = {}
        if detected:
            attrs["driver.java.version"] = self._version
        return {"detected": detected, "healthy": detected, "attributes": attrs}

    def start_task(self, task: Task, task_dir: str) -> TaskHandle:
        if not self._java:
            raise RuntimeError("java runtime not found on this node")
        cfg = task.config or {}
        jar = cfg.get("jar_path")
        main_class = cfg.get("class")
        if bool(jar) == bool(main_class):
            raise RuntimeError("java requires exactly one of jar_path/class")
        argv = [self._java] + list(cfg.get("jvm_options", []))
        if jar:
            argv += ["-jar", jar]
        else:
            argv += ["-cp", cfg.get("class_path", task_dir or "."), main_class]
        argv += [str(a) for a in cfg.get("args", [])]
        return self._spawn(task, argv, task_dir or None)
