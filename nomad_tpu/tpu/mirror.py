"""Committed-plane columnar view of cluster state.

The dense capacity/used planes live in the state store itself
(:class:`nomad_tpu.state.planes.CommittedPlanes`): the FSM apply patches
them in the SAME write transaction that swaps the MVCC tables, and
``StateStore._publish`` stamps them with the new generation identity and
raft index. The :class:`ColumnarMirror` here is therefore a thin adapter —
it no longer subscribes to events, chases frames, detects skew, or
checksums itself against rebuilds, because the planes are exact **by
construction**: ``planes.gen is snapshot._gen`` is the entire freshness
test. The EventBroker subscription machinery that used to keep the old
mirror fresh (and the skew/sever/lost-gap/checksum rebuild failure class
that came with it) is deleted; the broker now serves external watchers
only.

What remains here:

- :class:`MirrorCluster` — a ColumnarCluster whose ``mirror_used`` /
  ``exotic_live`` / alloc-record tables ALIAS the committed planes (zero
  copies; a store commit is immediately visible under the shared lock);
- :class:`DeviceState` — the device-resident planes, uploaded once per
  node-axis epoch and patched with dirty-row scatter updates fed straight
  from the store's in-commit track/untrack path;
- the adapter itself: ``sync`` / ``device_state`` / ``verify_handles`` /
  ``locked_cluster`` / ``stats`` with the same consumer contract as
  before. ``rebuilds`` is retained in the counters and is structurally
  zero — the acceptance gate of the refactor; node-axis changes surface
  as ``view_refreshes`` (an O(N) host re-derivation of the static
  planes), never as a rebuild of the usage state.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

import numpy as np

from ..state.planes import exotic_flag, usage_vec  # noqa: F401 — canonical
# definitions moved into the state layer with the planes; re-exported here
# because this module was their historical home
from .columnar import R_COLS, ColumnarCluster

logger = logging.getLogger("nomad_tpu.tpu.mirror")


class MirrorCluster(ColumnarCluster):
    """A ColumnarCluster whose usage plane and collision counts alias the
    store's committed planes. Built over ALL nodes in the state (not just
    ready ones) so per-eval eligibility is a ring permutation, never a
    node-axis change; a node status flap is an object swap the store
    already performed in the shared ``nodes`` list.

    The fast paths serve only the exact generation the planes are
    committed at; any other generation falls back to the base class's
    scan-the-table implementations, so a stale reader can never observe a
    half-applied write transaction."""

    def __init__(self, planes):
        super().__init__(planes.nodes)
        self._planes = planes
        self._epoch = planes.epoch
        self._mirror_lock = planes.lock
        # alias, don't copy: the store's write transactions patch these
        # in-commit, and this view sees the result the moment the planes
        # are restamped
        self.index = planes.index
        # nta: ignore[plane-mutation-outside-commit] WHY: read-only
        # aliasing, not mutation — the next four bind the committed
        # arrays/tables into this view so fast paths index them with
        # zero copies; nothing here ever writes through the alias
        #: reserved + Σ live-alloc contributions per row (int64, [N, R])
        self.mirror_used = planes.used
        #: live allocs per row carrying ports/devices (dimensions the
        #: dense planes can't verify): the plan applier's device verify
        #: degrades these rows to the exact host check
        # nta: ignore[plane-mutation-outside-commit] WHY: read-only alias
        self.exotic_live = planes.exotic_live
        #: alloc id → (node_id, usage vec, job_id, task_group, exotic)
        # nta: ignore[plane-mutation-outside-commit] WHY: read-only alias
        self._alloc_rec = planes.alloc_rec
        #: (job_id, task_group) → {node_id: live alloc count}
        # nta: ignore[plane-mutation-outside-commit] WHY: read-only alias
        self._job_counts = planes.job_counts

    @property
    def _synced_gen(self):
        """The generation this view is exact for: the planes' committed
        generation while the node axis it was derived over is current,
        else None (the adapter builds a fresh view on the next sync)."""
        p = self._planes
        return p.gen if p.epoch == self._epoch else None

    # -- committed-plane fast paths -------------------------------------
    def initial_used(self, state, plan=None) -> np.ndarray:
        gen = getattr(state, "_gen", state)
        with self._mirror_lock:
            if gen is self._synced_gen:
                used = self.mirror_used.copy()
                if plan is not None:
                    for node_id, stops in plan.node_update.items():
                        row = self.index.get(node_id)
                        if row is None:
                            continue
                        for a in stops:
                            rec = self._alloc_rec.get(a.id)
                            if rec is not None and rec[0] == node_id:
                                used[row] -= np.asarray(
                                    rec[1], dtype=np.int64
                                )
                return used
        # stale generation: the O(total allocs) rescan runs OUTSIDE the
        # lock — a reader one generation behind must not serialize the
        # store's write transactions behind a full table scan
        return super().initial_used(state, plan)

    def collision_counts(self, state, job_id: str, tg_name: str) -> np.ndarray:
        gen = getattr(state, "_gen", state)
        with self._mirror_lock:
            if gen is self._synced_gen:
                counts = np.zeros(len(self.nodes), dtype=np.int32)
                for node_id, c in self._job_counts.get(
                    (job_id, tg_name), {}
                ).items():
                    row = self.index.get(node_id)
                    if row is not None:
                        counts[row] = c
                return counts
        return super().collision_counts(state, job_id, tg_name)


class DeviceState:
    """Device-resident kernel state for one (epoch, padded-N) pair: the
    capacity/usable planes uploaded once, and a ``used`` plane maintained
    by scatter updates of just the dirty rows. Updates deliberately COPY
    rather than donate the retired buffer: every refresh follows a
    hand-out to an asynchronously-dispatched kernel that may still be
    reading it (the collector wakes consumers at dispatch), and with two
    drain workers the other worker's batch can hold it too — donating a
    buffer a live computation reads is undefined. The old buffer is freed
    as soon as the last kernel holding it completes."""

    #: dirty-row scatter shapes are bucketed so row-count churn doesn't
    #: compile a fresh scatter program per batch
    _ROW_BUCKETS = (8, 64, 512, 4096)

    def __init__(self, epoch: int, n_pad: int, capacity, usable, used,
                 mesh=None):
        from ..debug import devprof as _devprof

        self.epoch = epoch
        self.n_pad = n_pad
        #: the device mesh these planes are row-sharded over (None =
        #: single-chip); a kernel batch must only consume a DeviceState
        #: whose mesh matches its own, or GSPMD resharding (a silent
        #: cross-device copy + a fresh compiled layout) rides the hot path
        self.mesh = mesh
        n = capacity.shape[0]
        cap = np.zeros((n_pad, R_COLS), dtype=np.int32)
        cap[:n] = np.clip(capacity, 0, 2**31 - 1)
        usa = np.ones((n_pad, 2), dtype=np.float32)
        usa[:n] = usable
        use = np.full((n_pad, R_COLS), 2**30, dtype=np.int32)
        use[:n] = np.clip(used, 0, 2**30)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from . import shard as _shard

            rows = NamedSharding(mesh, P(_shard.AXIS, None))
            self.capacity = _devprof.device_put(cap, rows)
            self.usable = _devprof.device_put(usa, rows)
            self.used = _devprof.device_put(use, rows)
        else:
            self.capacity = _devprof.device_put(cap)
            self.usable = _devprof.device_put(usa)
            self.used = _devprof.device_put(use)
        #: dirty rows since the last refresh — registered as a sink with
        #: the committed planes, so the store's in-commit track/untrack
        #: feeds it directly
        self.pending: set[int] = set()

    @staticmethod
    def _row_bucket(n: int) -> int:
        for b in DeviceState._ROW_BUCKETS:
            if n <= b:
                return b
        return ((n + 4095) // 4096) * 4096

    def refresh(self, used_host: np.ndarray):
        """Push pending dirty rows to the device as one scatter update."""
        if not self.pending:
            return
        from ..debug import devprof as _devprof

        rows = np.fromiter(self.pending, dtype=np.int32, count=len(self.pending))
        self.pending.clear()
        b = self._row_bucket(len(rows))
        padded = np.zeros(b, dtype=np.int32)
        padded[: len(rows)] = rows  # pad lanes repeat row 0: same-value set, idempotent
        vals = np.clip(used_host[padded], 0, 2**30).astype(np.int32)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # dirty rows/values replicate EXPLICITLY: an uncommitted
            # host array next to the sharded plane would hand XLA a
            # layout choice the prewarmed scatter never compiled
            rep = NamedSharding(self.mesh, P())
            padded_d = _devprof.device_put(padded, rep)
            vals_d = _devprof.device_put(vals, rep)
        else:
            padded_d = _devprof.device_put(padded)
            vals_d = _devprof.device_put(vals)
        self.used = _scatter_fn(self.mesh)(self.used, padded_d, vals_d)

    def arrays(self):
        """(capacity, usable, used) device refs — immutable snapshots: a
        later refresh produces a NEW used buffer, so an in-flight kernel's
        captured ref never changes underneath it."""
        return self.capacity, self.usable, self.used


# nta: ignore[unbounded-cache] WHY: keyed by mesh identity — one entry
# per configured mesh (at most two in practice: None + the process mesh)
_SCATTER_FNS: dict = {}


def _scatter_fn(mesh):
    """The jitted dirty-row scatter for ``mesh`` (None = single-chip).
    The sharded variant pins ``out_shardings`` to the row-sharded spec so
    the refreshed ``used`` buffer stays partitioned exactly like the one
    it replaces — GSPMD would otherwise be free to gather the output and
    hand the next kernel batch a replicated plane (one silent recompile
    plus an O(N) transfer per drain batch)."""
    key = id(mesh) if mesh is not None else None
    fn = _SCATTER_FNS.get(key)
    if fn is None:
        import jax

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from . import shard as _shard

            fn = jax.jit(
                lambda used, rows, vals: used.at[rows].set(vals),
                out_shardings=NamedSharding(mesh, P(_shard.AXIS, None)),
            )
        else:
            fn = jax.jit(lambda used, rows, vals: used.at[rows].set(vals))
        _SCATTER_FNS[key] = fn
    return fn


class ColumnarMirror:
    """The committed-plane columnar view for one server: an adapter over
    ``state.planes`` that builds the MirrorCluster view per node-axis
    epoch and owns the device-resident plane cache."""

    def __init__(self, state, broker=None, verify_every: int = 0):
        # ``broker`` and ``verify_every`` are accepted for construction-
        # site compatibility and ignored: plane freshness comes from the
        # store commit path, not an event subscription, and the checksum
        # self-verify the old mirror needed is now the watchdog's
        # plane_divergence audit (state/planes.py audit_sample)
        self._state = state
        self._planes = state.planes
        self._lock = self._planes.lock
        self._closed = False
        self._cluster: Optional[MirrorCluster] = None
        self._device: dict[int, DeviceState] = {}
        self.counters = {
            "hits": 0,
            "rebuilds": 0,  # structurally zero — kept as the gate metric
            "stale": 0,
            "view_refreshes": 0,
            "over_budget": 0,
            "rebuild_reasons": {},
        }

    # ------------------------------------------------------------------
    def _ensure_cluster(self) -> MirrorCluster:
        """The MirrorCluster view for the planes' current node axis,
        re-derived (static planes only: capacity/usable/single_nic — the
        usage state is aliased, never copied) when the axis epoch moved.
        Caller holds the plane lock."""
        cluster = self._cluster
        if cluster is None or cluster._epoch != self._planes.epoch:
            from .. import metrics

            cluster = MirrorCluster(self._planes)
            self._cluster = cluster
            # retire device planes for the dead axis; their pending-row
            # sinks die with them
            for ds in self._device.values():
                self._planes.unregister_sink(ds.pending)
            self._device.clear()
            self.counters["view_refreshes"] += 1
            metrics.incr("tpu.mirror_view_refresh")
        return cluster

    def sync(self, snapshot) -> Optional[MirrorCluster]:
        """The MirrorCluster view of exactly ``snapshot``, or None when
        the committed planes are at a different generation (a write
        landed between the caller's snapshot and this sync — the caller
        builds a one-off legacy cluster instead; counted stale)."""
        from .. import metrics

        gen = getattr(snapshot, "_gen", snapshot)
        with self._lock:
            if self._closed:
                return None
            if self._planes.gen is not gen:
                self.counters["stale"] += 1
                metrics.incr("tpu.mirror_stale")
                return None
            cluster = self._ensure_cluster()
            self.counters["hits"] += 1
            metrics.incr("tpu.mirror_hit")
            return cluster

    # ------------------------------------------------------------------
    # device-resident kernel state
    # ------------------------------------------------------------------
    def device_state(self, n_pad: int, gen, mesh=None) -> Optional[tuple]:
        """Device refs (capacity, usable, used) for the node plane padded
        to ``n_pad``, valid for state generation ``gen``; None when the
        committed planes are at a different generation (caller falls back
        to a host transfer of its own snapshot arrays). With ``mesh``,
        the planes are row-sharded over it (the caller's fused batch
        dispatches sharded, so its state plane must already live
        partitioned); a cached state for a different mesh is rebuilt,
        never reshared."""
        # Budget gate: when the paging stanza says a full n_pad-row
        # resident mirror would blow the device budget, refuse to build
        # one — the caller degrades to its host-plane path (counted) and
        # the over-budget axis is the paged dispatch's job.
        from . import paging as _paging

        if _paging.should_page(n_pad, R_COLS):
            from .. import metrics

            with self._lock:
                self.counters["over_budget"] += 1
            metrics.incr("tpu.mirror_over_budget")
            return None
        with self._lock:
            planes = self._planes
            if self._closed or planes.gen is not gen:
                return None
            cluster = self._ensure_cluster()
            ds = self._device.get(n_pad)
            if ds is not None and (
                ds.epoch != planes.epoch or ds.mesh is not mesh
            ):
                planes.unregister_sink(ds.pending)
                ds = None
            if ds is None:
                ds = DeviceState(
                    planes.epoch, n_pad, cluster.capacity,
                    cluster.usable, planes.used, mesh=mesh,
                )
                self._device[n_pad] = ds
                # from here on the store's in-commit track/untrack marks
                # dirty rows straight into this DeviceState
                planes.register_sink(ds.pending)
            else:
                ds.refresh(planes.used)
            return ds.arrays()

    # ------------------------------------------------------------------
    # plan-applier dense device verify (core/plan_apply.py)
    # ------------------------------------------------------------------
    def verify_handles(self, snapshot, n_pad: int, mesh=None):
        """The plan applier's device-verify view of ``snapshot``: the
        committed-plane cluster and ``(capacity, usable, used)`` device
        refs at exactly that generation, or None when the planes have
        already committed PAST the snapshot (the applier then degrades to
        the host oracle, counted in tpu.mirror_stale /
        plan.verify_device_degrade). ``mesh`` must match what the drain
        batches pass for the same n_pad (the MIN_NODES-gated active
        mesh): the DeviceState cache is keyed by n_pad, so a mesh
        mismatch between the two consumers would rebuild the full planes
        on every alternation instead of riding the dirty-row scatter."""
        cluster = self.sync(snapshot)
        if cluster is None:
            return None
        gen = getattr(snapshot, "_gen", snapshot)
        arrays = self.device_state(n_pad, gen, mesh=mesh)
        if arrays is None:
            return None
        return cluster, arrays, gen

    def locked_cluster(self, gen):
        """Context manager yielding the MirrorCluster while the planes
        are still committed at ``gen`` (else None), with the plane lock
        held: the applier's per-plan host-side gather (rows, node
        objects, exotic counts, alloc-rec vectors) reads a consistent
        plane set even if a write transaction is concurrently patching
        the store forward."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            with self._lock:
                cluster = self._cluster
                if (
                    self._closed
                    or cluster is None
                    or cluster._synced_gen is not gen
                ):
                    yield None
                else:
                    yield cluster

        return _ctx()

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            planes = self._planes
            out = dict(self.counters)
            out["rebuild_reasons"] = dict(self.counters["rebuild_reasons"])
            out["applied_index"] = planes.version
            out["epoch"] = planes.epoch
            out["nodes"] = len(planes.nodes)
            out["tracked_allocs"] = len(planes.alloc_rec)
            return out

    def close(self):
        with self._lock:
            self._closed = True
            for ds in self._device.values():
                self._planes.unregister_sink(ds.pending)
            self._device.clear()
            self._cluster = None
