"""Dense device path: constraint-free device{} asks ride the kernel as the
5th resource column, with concrete instance IDs arbitrated host-side on the
winner (SURVEY §7 step 4; ref scheduler/device.go:40-131 for the oracle
semantics being matched)."""

import random

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler import Harness
from nomad_tpu.structs import compute_class
from nomad_tpu.structs.model import (
    Affinity,
    Constraint,
    Evaluation,
    RequestedDevice,
    generate_uuid,
)
from nomad_tpu.tpu import batch_sched


def build_nodes(n, devices_every=4):
    rng = random.Random(7)
    templates = []
    for cpu, mem in ((4000, 8192), (8000, 16384)):
        t = mock.node()
        t.node_resources.cpu.cpu_shares = cpu
        t.node_resources.memory.memory_mb = mem
        t.node_resources.networks = []
        t.reserved_resources.networks.reserved_host_ports = ""
        compute_class(t)
        templates.append(t)
    tpu_t = mock.tpu_node()
    tpu_t.node_resources.networks = []
    tpu_t.reserved_resources.networks.reserved_host_ports = ""
    compute_class(tpu_t)
    nodes = []
    for i in range(n):
        t = tpu_t if i % devices_every == 0 else templates[rng.randrange(2)]
        node = t.copy()
        node.id = generate_uuid()
        nodes.append(node)
    return nodes


def device_job(count, dev_count=1, name="tpu"):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = count
    tg.tasks[0].resources.cpu = 100
    tg.tasks[0].resources.memory_mb = 64
    tg.tasks[0].resources.networks = []
    tg.tasks[0].resources.devices = [
        RequestedDevice(name=name, count=dev_count)
    ]
    return job


def make_eval(job):
    return Evaluation(
        id=generate_uuid(),
        namespace=job.namespace,
        priority=job.priority,
        type=job.type,
        triggered_by="job-register",
        job_id=job.id,
        status="pending",
    )


def run(factory, job, nodes, seed=29, harness=None):
    h = harness or Harness(seed=seed)
    if harness is None:
        for n in nodes:
            h.state.upsert_node(h.next_index(), n)
    h.state.upsert_job(h.next_index(), job)
    ev = make_eval(job)
    h.state.upsert_evals(h.next_index(), [ev])
    sched = h.process(factory, ev)
    run.last_sched = sched
    return h, h.state.allocs_by_job(job.namespace, job.id)


def assert_unique_instances(allocs):
    seen = set()
    for a in allocs:
        devs = [
            d
            for tr in a.allocated_resources.tasks.values()
            for d in tr.devices
        ]
        assert devs, f"alloc {a.name} placed without a device grant"
        for d in devs:
            assert d.device_ids
            for iid in d.device_ids:
                key = (a.node_id, d.vendor, d.type, d.name, iid)
                assert key not in seen, f"instance double-booked: {key}"
                seen.add(key)
    return seen


def test_device_parity_with_oracle():
    """Kernel placements for a device job match the scalar oracle node-for-
    node (both sides built from the same seed, compared by node index)."""
    nodes_a = build_nodes(80)
    _, oracle = run("service", device_job(24), nodes_a)
    nodes_b = build_nodes(80)
    batch_sched.LAST_KERNEL_STATS.clear()
    _, kernel = run("tpu-batch", device_job(24), nodes_b)
    assert batch_sched.LAST_KERNEL_STATS.get("mode") == "windowed"

    idx_a = {n.id: i for i, n in enumerate(nodes_a)}
    idx_b = {n.id: i for i, n in enumerate(nodes_b)}
    by_name_a = {a.name.split(".")[-1]: idx_a[a.node_id] for a in oracle}
    by_name_b = {a.name.split(".")[-1]: idx_b[a.node_id] for a in kernel}
    assert by_name_a == by_name_b
    assert_unique_instances(kernel)


def test_device_exhaustion_partial_placement():
    """More asks than instances: the kernel places exactly the capacity and
    reports the device dimension in the failure metric."""
    nodes = build_nodes(40, devices_every=4)  # 10 tpu nodes x 2 instances
    h, allocs = run("tpu-batch", device_job(32), nodes)
    assert len(allocs) == 20
    assert_unique_instances(allocs)
    failed = run.last_sched.failed_tg_allocs
    assert failed, "exhaustion must surface failed_tg_allocs"
    metrics = next(iter(failed.values()))
    assert "devices" in metrics.dimension_exhausted


def test_device_used_accounting_across_evals():
    """A second job's kernel pass must see instances consumed by the first
    job's allocs (cluster.device_used) and overflow to free nodes only."""
    nodes = build_nodes(40, devices_every=4)
    h, first = run("tpu-batch", device_job(10), nodes)
    _, second = run("tpu-batch", device_job(10), nodes, harness=h)
    assert len(first) == 10 and len(second) == 10
    assert_unique_instances(list(first) + list(second))


def test_device_constraint_falls_back():
    """Constraint-bearing device asks ride the oracle (they filter per
    device group, which the dense column can't express)."""
    nodes = build_nodes(40)
    job = device_job(12)
    job.task_groups[0].tasks[0].resources.devices[0].constraints = [
        Constraint(l_target="${device.attr.memory}", r_target="8", operand=">=")
    ]
    before = batch_sched.counters_snapshot()["fallback_reasons"].get(
        "unsupported_group", 0
    )
    run("tpu-batch", job, nodes)
    after = batch_sched.counters_snapshot()["fallback_reasons"].get(
        "unsupported_group", 0
    )
    assert after == before + 1


def test_device_affinity_falls_back():
    nodes = build_nodes(40)
    job = device_job(12)
    job.task_groups[0].tasks[0].resources.devices[0].affinities = [
        Affinity(l_target="${device.attr.memory}", r_target="8", operand=">=", weight=50)
    ]
    before = batch_sched.counters_snapshot()["fallback_reasons"].get(
        "unsupported_group", 0
    )
    run("tpu-batch", job, nodes)
    after = batch_sched.counters_snapshot()["fallback_reasons"].get(
        "unsupported_group", 0
    )
    assert after == before + 1


def test_mixed_signature_escapes_before_shuffle():
    """Two groups asking different device signatures in one eval escape to
    the oracle wholesale (one shared count column can't serve both)."""
    nodes = build_nodes(40)
    job = device_job(12)
    tg2 = job.task_groups[0].copy()
    tg2.name = "other"
    tg2.count = 12
    tg2.tasks[0].resources.devices = [RequestedDevice(name="gpu", count=1)]
    job.task_groups.append(tg2)
    before = batch_sched.counters_snapshot()["fallback_reasons"].get(
        "device_mixed_signature", 0
    )
    h, allocs = run("tpu-batch", job, nodes)
    after = batch_sched.counters_snapshot()["fallback_reasons"].get(
        "device_mixed_signature", 0
    )
    assert after == before + 1
