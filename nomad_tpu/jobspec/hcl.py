"""Minimal HCL1 parser — enough for the job specification language
(ref jobspec/parse.go, which feeds HCL1 through hashicorp/hcl).

Supports the constructs jobspecs use: blocks (`job "name" { ... }`, nested,
with 0..2 string labels), assignments (`key = value`), strings (with escapes),
heredocs, numbers, booleans, lists, objects (`{ k = v }`), comments
(#, //, /* */), and duration-literal passthrough (durations stay strings for
the caller to parse). Produces plain dicts: blocks become
``{type: {label: body}}`` and repeated blocks become lists.
"""

from __future__ import annotations

import re
from typing import Any, Optional

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>[ \t\r,]+)
  | (?P<comment>\#[^\n]*|//[^\n]*|/\*.*?\*/)
  | (?P<heredoc><<-?(?P<hd_tag>[A-Za-z_][A-Za-z0-9_]*)\n(?P<hd_body>.*?)\n\s*(?P=hd_tag))
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>-?\d+\.\d+|-?\d+(?![\w.]))
  | (?P<bool>\btrue\b|\bfalse\b)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_\-.]*)
  | (?P<punct>[{}\[\]=\n])
    """,
    re.VERBOSE | re.DOTALL,
)


class HCLError(ValueError):
    pass


def _tokenize(src: str):
    tokens = []
    pos = 0
    line = 1
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise HCLError(f"unexpected character {src[pos]!r} at line {line}")
        line += src[pos : m.end()].count("\n")
        pos = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        if kind == "heredoc":
            tokens.append(("string", m.group("hd_body"), line))
        elif kind == "punct" and m.group() == "\n":
            tokens.append(("newline", "\n", line))
        else:
            tokens.append((kind, m.group(), line))
    tokens.append(("eof", "", line))
    return tokens


_ESCAPES = {"n": "\n", "t": "\t", '"': '"', "\\": "\\", "r": "\r"}


def _unquote(s: str) -> str:
    body = s[1:-1]
    out = []
    i = 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            out.append(_ESCAPES.get(body[i + 1], "\\" + body[i + 1]))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


class _Parser:
    def __init__(self, tokens):
        self.tokens = tokens
        self.pos = 0

    def peek(self):
        return self.tokens[self.pos]

    def next(self):
        tok = self.tokens[self.pos]
        self.pos += 1
        return tok

    def skip_newlines(self):
        while self.peek()[0] == "newline":
            self.next()

    def parse_body(self, stop: Optional[str] = "eof") -> dict:
        """Parse a sequence of assignments and blocks until `stop`."""
        out: dict[str, Any] = {}
        while True:
            self.skip_newlines()
            kind, value, line = self.peek()
            if kind == "eof" or (stop == "}" and value == "}"):
                return out
            if kind not in ("ident", "string"):
                raise HCLError(f"expected key at line {line}, got {value!r}")
            key = _unquote(value) if kind == "string" else value
            self.next()
            self._parse_entry(out, key)

    def _parse_entry(self, out: dict, key: str):
        labels = []
        while True:
            kind, value, line = self.peek()
            if kind == "punct" and value == "=":
                self.next()
                self._store(out, key, labels, self.parse_value())
                return
            if kind == "string" and not labels or (kind == "string" and labels):
                labels.append(_unquote(value))
                self.next()
                continue
            if kind == "punct" and value == "{":
                self.next()
                body = self.parse_body(stop="}")
                self._expect("}")
                self._store(out, key, labels, body)
                return
            raise HCLError(
                f"unexpected {value!r} after {key!r} at line {line}"
            )

    def _store(self, out: dict, key: str, labels: list[str], value):
        """Blocks with labels nest: job "x" { } → {"job": {"x": {...}}}.
        Repeated keys become lists (HCL1 object-list semantics)."""
        target = out
        path = [key] + labels
        for part in path[:-1]:
            nxt = target.get(part)
            if not isinstance(nxt, dict):
                nxt = {}
                target[part] = nxt
            target = nxt
        last = path[-1]
        if last in target:
            existing = target[last]
            if isinstance(existing, list):
                existing.append(value)
            else:
                target[last] = [existing, value]
        else:
            target[last] = value

    def _expect(self, punct: str):
        kind, value, line = self.next()
        if value != punct:
            raise HCLError(f"expected {punct!r} at line {line}, got {value!r}")

    def parse_value(self):
        self.skip_newlines()
        kind, value, line = self.next()
        if kind == "string":
            return _unquote(value)
        if kind == "number":
            return float(value) if "." in value else int(value)
        if kind == "bool":
            return value == "true"
        if kind == "ident":
            return value  # bare identifier treated as string
        if value == "[":
            items = []
            while True:
                self.skip_newlines()
                if self.peek()[1] == "]":
                    self.next()
                    return items
                items.append(self.parse_value())
        if value == "{":
            body = self.parse_body(stop="}")
            self._expect("}")
            return body
        raise HCLError(f"unexpected value {value!r} at line {line}")


def parse(src: str) -> dict:
    """Parse HCL source into nested dicts."""
    return _Parser(_tokenize(src)).parse_body()


_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)$")
_DURATION_NS = {
    "ns": 1,
    "us": 1_000,
    "µs": 1_000,
    "ms": 1_000_000,
    "s": 1_000_000_000,
    "m": 60 * 1_000_000_000,
    "h": 3600 * 1_000_000_000,
}


def parse_duration(v) -> int:
    """Go-style duration string → nanoseconds ('30s', '10m', '1.5h')."""
    if isinstance(v, (int, float)):
        return int(v)
    total = 0
    rest = v.strip()
    part_re = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
    pos = 0
    matched = False
    for m in part_re.finditer(rest):
        if m.start() != pos:
            raise HCLError(f"invalid duration: {v!r}")
        total += int(float(m.group(1)) * _DURATION_NS[m.group(2)])
        pos = m.end()
        matched = True
    if not matched or pos != len(rest):
        raise HCLError(f"invalid duration: {v!r}")
    return total
