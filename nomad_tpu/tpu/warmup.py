"""Kernel prewarm: compile the planner shape ladder before the first eval.

Cold compile of the three planners was 13s at round 2 — first eval at a new
bucket shape ate seconds of scheduling latency. Together with the
persistent compilation cache (tpu/__init__.py) this makes agent startup
absorb the cost once: ``prewarm_async`` lowers+compiles the runs, windowed
and exact-scan planners for the configured (nodes, allocs) buckets in a
daemon thread, so by the time real evals arrive the programs are resident
(or at worst loading from the on-disk cache instead of compiling).

Shapes must match production exactly to hit: the batch scheduler buckets
the node and alloc axes (batch_sched._bucket), so prewarming the bucket
ladder covers every cluster size that rounds into it.
"""

from __future__ import annotations

import threading

from .batch_sched import _bucket


def bucket_shape(n_nodes: int, n_allocs: int) -> tuple[int, int]:
    """The exact padded shape production hits for a real (nodes, allocs)
    pair — computed through the ONE bucketing policy (batch_sched._bucket)
    so the prewarm ladder can never drift from the scheduler again. (The
    previous hand-written ladder listed 51200 for the 50K-alloc headline
    while the scheduler pads 50K to 50176: the prewarmed program was never
    the one the headline ran, so the first real eval at that shape still
    compiled.)"""
    return _bucket(n_nodes), _bucket(n_allocs)


#: default ladder: dev/CI clusters and the 10K-node / 50K-alloc headline,
#: expressed as the REAL cluster sizes and bucketed through production's
#: padding policy
DEFAULT_SIZES = ((100, 100), (1000, 1000), (10000, 50000))
DEFAULT_SHAPES = tuple(bucket_shape(n, a) for n, a in DEFAULT_SIZES)
#: spread value-table width compiled for (datacenter-style spreads)
DEFAULT_V = 4


def prewarm(shapes=DEFAULT_SHAPES, v_values: int = DEFAULT_V) -> int:
    """Compile the planners for each (node_bucket, alloc_bucket) shape;
    returns the number of programs compiled. Failures are swallowed — a
    prewarm must never take the agent down."""
    import numpy as np
    import jax.numpy as jnp

    # the jitted internals: warmup needs .lower() for AOT compilation,
    # which the fault-gated public wrappers don't carry
    from .kernel import (
        BatchArgs,
        BatchState,
        RunArgs,
        WindowArgs,
        _plan_batch_jit as plan_batch,
        _plan_batch_runs_jit as plan_batch_runs,
        _plan_batch_windowed_jit as plan_batch_windowed,
    )

    compiled = 0
    for n_pad, a_pad in shapes:
        try:
            capacity = jnp.ones((n_pad, 4), dtype=jnp.int32)
            usable = jnp.ones((n_pad, 2), dtype=jnp.float32)
            feas = jnp.ones(n_pad, dtype=bool)
            fzero = jnp.zeros(n_pad, dtype=jnp.float32)
            bzero = jnp.zeros(n_pad, dtype=bool)
            perm = jnp.arange(n_pad, dtype=jnp.int32)
            demand = jnp.ones(4, dtype=jnp.int32)
            used0 = jnp.zeros((n_pad, 4), dtype=jnp.int32)
            coll0 = jnp.zeros(n_pad, dtype=jnp.int32)
            V = v_values

            rargs = RunArgs(
                capacity=capacity,
                usable=usable,
                feasible=feas,
                affinity=fzero,
                affinity_present=bzero,
                group_count=jnp.int32(1),
                node_value=jnp.zeros(n_pad, dtype=jnp.int32),
                spread_desired=jnp.full(V, -1.0, dtype=jnp.float32),
                spread_implicit=jnp.float32(-1.0),
                spread_weight_frac=jnp.float32(1.0),
                spread_even=jnp.asarray(False),
                spread_active=jnp.asarray(True),
                perm=perm,
                demand=demand,
                n_allocs=jnp.int32(1),
            )
            rinit = (
                used0,
                coll0,
                jnp.zeros(V, dtype=jnp.int32),
                jnp.zeros(V, dtype=bool),
            )
            plan_batch_runs.lower(rargs, rinit, a_pad, False).compile()
            compiled += 1

            wargs = WindowArgs(
                capacity=capacity,
                usable=usable,
                feasible=feas,
                perm=perm,
                demand=demand,
                group_count=jnp.int32(1),
                limit=jnp.int32(2),
                n_allocs=jnp.int32(1),
            )
            plan_batch_windowed.lower(
                wargs, used0, coll0, n_pad, a_pad
            ).compile()
            compiled += 1

            bargs = BatchArgs(
                capacity=capacity,
                usable=usable,
                feasible=feas[None, :],
                affinity=fzero[None, :],
                affinity_present=bzero[None, :],
                group_count=jnp.ones(1, dtype=jnp.int32),
                group_eval=jnp.zeros(1, dtype=jnp.int32),
                node_value=jnp.zeros((1, n_pad), dtype=jnp.int32),
                spread_desired=jnp.full((1, V), -1.0, dtype=jnp.float32),
                spread_implicit=jnp.full(1, -1.0, dtype=jnp.float32),
                spread_weight_frac=jnp.ones(1, dtype=jnp.float32),
                spread_even=jnp.zeros(1, dtype=bool),
                spread_active=jnp.ones(1, dtype=bool),
                perm=perm[None, :],
                ring=jnp.array([n_pad], dtype=jnp.int32),
                demands=jnp.ones((a_pad, 4), dtype=jnp.int32),
                groups=jnp.zeros(a_pad, dtype=jnp.int32),
                limits=jnp.full(a_pad, n_pad, dtype=jnp.int32),
                valid=jnp.ones(a_pad, dtype=bool),
            )
            binit = BatchState(
                used=used0,
                collisions=jnp.zeros((1, n_pad), dtype=jnp.int32),
                spread_counts=jnp.zeros((1, V), dtype=jnp.int32),
                spread_present=jnp.zeros((1, V), dtype=bool),
                offset=jnp.zeros(1, dtype=jnp.int32),
            )
            plan_batch.lower(bargs, binit, n_pad).compile()
            compiled += 1
        except Exception:
            continue
    return compiled


def prewarm_drain(n_nodes: int, batch: int, v_values: int = 8) -> int:
    """Compile the FUSED drain-batch shapes for a (cluster size, drain
    size) pair: the multi-eval ``plan_batch`` program plus the per-eval
    usage-base program the collector dispatches alongside it
    (drain.py:_run computes exactly these paddings). Returns programs
    compiled; failures are swallowed like ``prewarm``."""
    import numpy as np
    import jax.numpy as jnp

    from .drain import _used_bases_fn
    from .kernel import BatchArgs, BatchState, _plan_batch_jit

    N = _bucket(n_nodes)
    E = _bucket(batch)
    G = _bucket(batch)
    A = _bucket(batch * 4)
    V = _bucket(max(v_values, 8))
    compiled = 0
    try:
        args = BatchArgs(
            capacity=jnp.ones((N, 4), dtype=jnp.int32),
            usable=jnp.ones((N, 2), dtype=jnp.float32),
            feasible=jnp.ones((G, N), dtype=bool),
            affinity=jnp.zeros((G, N), dtype=jnp.float32),
            affinity_present=jnp.zeros((G, N), dtype=bool),
            group_count=jnp.ones(G, dtype=jnp.int32),
            group_eval=jnp.zeros(G, dtype=jnp.int32),
            node_value=jnp.full((G, N), -1, dtype=jnp.int32),
            spread_desired=jnp.full((G, V), -1.0, dtype=jnp.float32),
            spread_implicit=jnp.full(G, -1.0, dtype=jnp.float32),
            spread_weight_frac=jnp.zeros(G, dtype=jnp.float32),
            spread_even=jnp.zeros(G, dtype=bool),
            spread_active=jnp.zeros(G, dtype=bool),
            perm=jnp.tile(jnp.arange(N, dtype=jnp.int32), (E, 1)),
            ring=jnp.full(E, n_nodes, dtype=jnp.int32),
            demands=jnp.ones((A, 4), dtype=jnp.int32),
            groups=jnp.zeros(A, dtype=jnp.int32),
            limits=jnp.full(A, 2, dtype=jnp.int32),
            valid=jnp.ones(A, dtype=bool),
        )
        init = BatchState(
            used=jnp.zeros((N, 4), dtype=jnp.int32),
            collisions=jnp.zeros((G, N), dtype=jnp.int32),
            spread_counts=jnp.zeros((G, V), dtype=jnp.int32),
            spread_present=jnp.zeros((G, V), dtype=bool),
            offset=jnp.zeros(E, dtype=jnp.int32),
        )
        _plan_batch_jit.lower(args, init, n_nodes).compile()
        compiled += 1
        _used_bases_fn().lower(
            init.used,
            jnp.full(A, -1, dtype=jnp.int32),
            args.demands,
            jnp.zeros(A, dtype=jnp.int32),
            E,
            jnp.int32(n_nodes),
        ).compile()
        compiled += 1
    except Exception:
        pass
    return compiled


def prewarm_async(shapes=DEFAULT_SHAPES, drain: tuple = None) -> threading.Thread:
    """Fire-and-forget prewarm; returns the daemon thread. ``drain``
    optionally adds the fused (n_nodes, batch) drain shapes."""

    def run():
        prewarm(shapes)
        if drain is not None:
            prewarm_drain(*drain)

    t = threading.Thread(target=run, name="tpu-prewarm", daemon=True)
    t.start()
    return t
