"""Cluster event stream (ref nomad/stream/: the Nomad 1.0 event broker
behind /v1/event/stream). FSM-sourced typed events in a bounded ring
buffer, fanned out through encode-once frames to per-subscriber queues
with topic/key filters; cold subscribers can start from a state snapshot
stamped at raft index N (snapshot-on-subscribe) and ride deltas from N.
``mux.py`` hosts the shared-socket fan-out pump the chunked HTTP tier
scales on."""

from .broker import (
    ALL_TOPICS,
    TOPIC_ALL,
    TOPIC_ALLOC,
    TOPIC_DEPLOYMENT,
    TOPIC_EVAL,
    TOPIC_JOB,
    TOPIC_NODE,
    TOPIC_NODE_EVENT,
    TOPIC_PLAN_RESULT,
    BrokerLimitError,
    Event,
    EventBroker,
    Frame,
    Subscription,
    SubscriptionClosedError,
    encode_event,
    event_visible,
    event_wire,
    required_capability,
)

__all__ = [
    "ALL_TOPICS",
    "TOPIC_ALL",
    "TOPIC_ALLOC",
    "TOPIC_DEPLOYMENT",
    "TOPIC_EVAL",
    "TOPIC_JOB",
    "TOPIC_NODE",
    "TOPIC_NODE_EVENT",
    "TOPIC_PLAN_RESULT",
    "BrokerLimitError",
    "Event",
    "EventBroker",
    "Frame",
    "Subscription",
    "SubscriptionClosedError",
    "encode_event",
    "event_visible",
    "event_wire",
    "required_capability",
]
