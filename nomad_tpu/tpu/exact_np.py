"""Host-side numpy exact stepper: the vectorized oracle.

One numpy pass per placement with EXACTLY the scalar iterator chain's
semantics (stack.go:104-162 / select.go:35-67 / rank.go:146-521 /
spread.go:110-227): rotating candidate cursor, limit window with the
3-deep nonpositive deferral, binpack/anti-affinity/affinity/spread planes
averaged over fired planes, first-strict-max tie-break in visit order.

Role in the parity chain (bench.py): the scalar iterator walk costs
~0.3s/placement at 10K nodes, so direct oracle checks could only sample
~1% of the headline eval. This stepper reproduces the same decision
sequence at ~1ms/placement in float64 (the scalar chain's precision, NOT
the device kernel's float32 — a vectorized oracle that inherited the
kernel's rounding would under-report genuine divergence), letting the
bench oracle-check 10x+ more placements. It shares the columnar plane
construction with the kernel tier, so the scalar chain remains the root
of trust: bench pins ``oracle-np == scalar oracle`` on spot windows, and
tests/test_tpu_parity.py pins it across shapes.
"""

from __future__ import annotations

import numpy as np

MAX_SKIP = 3  # ref stack.go:17
NEG_INF = -1e300


def _rot_incl(x: np.ndarray, offset: int, positions: np.ndarray) -> np.ndarray:
    """Inclusive count of ``x`` along rotation order up to each position
    (the ring starts at ``offset``); numpy twin of kernel._rot_incl."""
    xc = np.cumsum(x.astype(np.int64))
    xex = xc - x.astype(np.int64)
    total = int(xc[-1]) if len(xc) else 0
    x_off = int(xex[offset])
    return np.where(positions >= offset, xc - x_off, total - x_off + xc)


def _class_boosts_np(
    counts, present, desired, implicit, weight_frac, even_flag, active_flag
):
    """float64 twin of kernel._class_boosts (spread.go:110-227)."""
    used_count = counts.astype(np.float64) + 1.0
    desired_eff = np.where(desired >= 0.0, desired, implicit)
    with np.errstate(divide="ignore", invalid="ignore"):
        target = np.where(
            desired_eff >= 0.0,
            (desired_eff - used_count) / np.maximum(desired_eff, 1e-9) * weight_frac,
            -1.0,
        )

    counts_f = counts.astype(np.float64)
    big = float(2**30)
    any_present = bool(present.any())
    min_count = (
        float(np.min(np.where(present, counts_f, big))) if any_present else 0.0
    )
    max_count = (
        float(np.max(np.where(present, counts_f, -big))) if any_present else 0.0
    )
    delta_boost = np.where(
        min_count == 0.0,
        -1.0,
        (min_count - counts_f) / max(min_count, 1e-9),
    )
    even = np.where(
        counts_f != min_count,
        delta_boost,
        (
            -1.0
            if min_count == max_count
            else (
                1.0
                if min_count == 0.0
                else (max_count - min_count) / max(min_count, 1e-9)
            )
        ),
    )
    if not any_present:
        even = np.zeros_like(counts_f)

    per_class = even if even_flag else target
    boosts = np.concatenate([per_class, np.array([-1.0])])
    return boosts if active_flag else np.zeros_like(boosts)


def plan_exact_np(
    capacity: np.ndarray,  # i64[N,R]
    usable: np.ndarray,  # f64[N,2]
    feasible: np.ndarray,  # bool[G,N]
    affinity: np.ndarray,  # f64[G,N]
    affinity_present: np.ndarray,  # bool[G,N]
    group_count: np.ndarray,  # i64[G]
    node_value: np.ndarray,  # i64[G,N] (-1 = missing)
    spread_desired: np.ndarray,  # f64[G,V] (-1 = absent)
    spread_implicit: np.ndarray,  # f64[G] (-1 = none)
    spread_weight_frac: np.ndarray,  # f64[G]
    spread_even: np.ndarray,  # bool[G]
    spread_active: np.ndarray,  # bool[G]
    perm: np.ndarray,  # i64[N] node id at ring position p
    demands: np.ndarray,  # i64[A,R]
    groups: np.ndarray,  # i64[A]
    limits: np.ndarray,  # i64[A]
    used0: np.ndarray,  # i64[N,R]
    collisions0: np.ndarray,  # i64[G,N]
    counts0: np.ndarray,  # i64[G,V]
    present0: np.ndarray,  # bool[G,V]
) -> np.ndarray:
    """Run the placement sequence; returns node index per alloc (-1 = none)."""
    n = capacity.shape[0]
    A = demands.shape[0]
    V = counts0.shape[1]
    positions = np.arange(n)
    placements = np.full(A, -1, dtype=np.int64)

    used = used0.astype(np.int64).copy()
    collisions = collisions0.astype(np.int64).copy()
    counts = counts0.astype(np.int64).copy()
    present = present0.astype(bool).copy()
    offset = 0

    cap_perm = capacity[perm]
    usable_perm = usable[perm].astype(np.float64)
    feas_perm = feasible[:, perm]
    aff_perm = affinity[:, perm].astype(np.float64)
    aff_present_perm = affinity_present[:, perm]
    nv_perm = node_value[:, perm]

    for i in range(A):
        g = int(groups[i])
        demand = demands[i]
        limit = int(limits[i])

        used_p = used[perm]
        fit_p = feas_perm[g] & np.all(used_p + demand[None, :] <= cap_perm, axis=1)

        # scores (in ring coordinates throughout)
        util = (used_p + demand[None, :])[:, :2].astype(np.float64)
        free = 1.0 - util / usable_perm
        total = np.power(10.0, free[:, 0]) + np.power(10.0, free[:, 1])
        binpack = np.clip(20.0 - total, 0.0, 18.0) / 18.0

        coll = collisions[g][perm]
        anti_present = coll > 0
        anti = np.where(
            anti_present,
            -(coll.astype(np.float64) + 1.0) / float(group_count[g]),
            0.0,
        )

        boosts = _class_boosts_np(
            counts[g],
            present[g],
            spread_desired[g].astype(np.float64),
            float(spread_implicit[g]),
            float(spread_weight_frac[g]),
            bool(spread_even[g]),
            bool(spread_active[g]),
        )
        v = nv_perm[g]
        cls = np.where(v >= 0, v, V)
        spread_score = boosts[cls]
        spread_fired = bool(spread_active[g]) & (spread_score != 0.0)
        spread_score = np.where(spread_fired, spread_score, 0.0)

        num = (
            1.0
            + anti_present.astype(np.float64)
            + aff_present_perm[g].astype(np.float64)
            + spread_fired.astype(np.float64)
        )
        score_p = (
            binpack
            + np.where(anti_present, anti, 0.0)
            + np.where(aff_present_perm[g], aff_perm[g], 0.0)
            + spread_score
        ) / num

        # limit-iterator deferral (select.go:35-67)
        nonpos = fit_p & (score_p <= 0.0)
        nonpos_incl = _rot_incl(nonpos, offset, positions)
        skipped = nonpos & (nonpos_incl <= MAX_SKIP)

        kept = fit_p & ~skipped
        ret_incl = _rot_incl(kept, offset, positions)
        returned = kept & (ret_incl <= limit)
        n_returned = int(returned.sum())

        need = max(limit - n_returned, 0)
        skip_incl = _rot_incl(skipped, offset, positions)
        replay = skipped & (skip_incl <= need)
        candidates = returned | replay

        rot_rank = np.where(positions >= offset, positions - offset, n - offset + positions)

        if candidates.any():
            max_score = np.max(np.where(candidates, score_p, NEG_INF))
            tie = candidates & (score_p == max_score)
            visit_order = rot_rank + np.where(replay, n, 0)
            best_p = int(np.argmin(np.where(tie, visit_order, 2**62)))
            best_node = int(perm[best_p])

            placements[i] = best_node
            used[best_node] += demand
            collisions[g, best_node] += 1
            bv = int(node_value[g, best_node])
            if bool(spread_active[g]) and bv >= 0:
                counts[g, bv] += 1
                present[g, bv] = True

        # StaticIterator.seen accounting
        last_ret_rank = int(np.max(np.where(returned, rot_rank, -1)))
        consumed = last_ret_rank + 1 if n_returned >= limit else n
        offset = (offset + consumed) % max(n, 1)

    return placements
