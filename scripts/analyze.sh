#!/usr/bin/env sh
# CI entry point for the static analyzer (ANALYSIS.md).
# Exit 0 = clean modulo the committed ANALYSIS_BASELINE.json;
# exit 1 = new findings (printed as JSON); exit 2 = analyzer error.
# Extra args pass through, e.g.:
#   scripts/analyze.sh --rules lock-order-cycle nomad_tpu/tpu/
set -eu

cd "$(dirname "$0")/.."
exec python -m nomad_tpu.analysis --format json "$@"
