"""Scheduler worker: dequeues evals, snapshots state, runs the scheduler, and
submits plans (ref nomad/worker.go:74-523).

The worker implements the scheduler's Planner protocol: SubmitPlan routes
through the leader's plan queue (optimistic concurrency), and a RefreshIndex
response hands the scheduler a newer snapshot to retry against.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Optional

import itertools

from ..scheduler.scheduler import new_scheduler
from ..testing import faults as _faults
from ..trace import tracer
from ..structs.model import (
    EVAL_STATUS_FAILED,
    Evaluation,
    Plan,
    PlanResult,
)
from .broker import FAILED_QUEUE, BrokerError
from .overload import DeadlineExceeded

logger = logging.getLogger("nomad_tpu.worker")

DEQUEUE_TIMEOUT = 0.5
RAFT_SYNC_LIMIT = 5.0

#: process-wide worker thread numbering — the name is the debug
#: profiler's classification key ("worker" class)
_WORKER_SEQ = itertools.count()




class Worker:
    """One scheduling worker (the reference runs NumCPU of these)."""

    def __init__(self, server, schedulers: Optional[list[str]] = None, seed=None):
        self.server = server
        # _failed is drained by the leader's reaper (Server._reap_failed_evals),
        # not by scheduling workers (ref leader.go:505 reapFailedEvaluations)
        self.schedulers = schedulers or ["service", "batch", "system", "_core"]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.seed = seed
        # set per-invocation; lets SubmitPlan attach the eval token and
        # blocked evals record the snapshot they were evaluated against
        self._eval_token = ""
        self._eval: Optional[Evaluation] = None
        self._snapshot_index = 0

    # ------------------------------------------------------------------
    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run, daemon=True,
            name=f"sched-worker-{next(_WORKER_SEQ)}",
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def run(self):
        """ref worker.go:105-140"""
        while not self._stop.is_set():
            ev, token = self.server.eval_broker.dequeue(
                self.schedulers, timeout=DEQUEUE_TIMEOUT
            )
            if ev is None:
                continue
            try:
                self.process_eval(ev, token)
            except _faults.SimulatedCrash:
                # the chaos harness killed this worker "process": no ack,
                # no nack — the broker's nack timer requeues the eval when
                # the lease expires, as with a real worker death
                logger.warning("worker crash injected; thread exiting")
                return

    # ------------------------------------------------------------------
    def _snapshot_with_lease(self, ev: Evaluation, token: str):
        """Wait for the eval's raft index in sub-lease slices, extending
        the broker lease between slices so a sync that outlasts
        nack_timeout can't nack the eval out from under a live worker
        (ref worker.go waitForIndex, which resets the lease periodically
        INSIDE the wait — a single post-wait reset fires only after the
        nack already landed)."""
        broker = self.server.eval_broker
        slice_ = max(min(broker.nack_timeout / 2.0, RAFT_SYNC_LIMIT), 0.05)
        deadline = time.monotonic() + RAFT_SYNC_LIMIT
        while True:
            remaining = deadline - time.monotonic()
            try:
                return self.server.state.snapshot_min_index(
                    ev.modify_index,
                    timeout=min(slice_, max(remaining, 0.01)),
                )
            except TimeoutError:
                if time.monotonic() >= deadline:
                    raise
                # still waiting, still making progress: extend the lease
                try:
                    broker.outstanding_reset(ev.id, token)
                except BrokerError:
                    pass

    def _fail_deadline_exceeded(self, ev: Evaluation, token: str, where: str):
        """Terminal resolution of expired work (core/overload.py): mark
        the eval failed ``deadline_exceeded`` and ACK it — nacking would
        requeue work nobody is waiting on anymore, and the broker would
        only refuse it again at the next dequeue."""
        logger.warning(
            "eval %s deadline exceeded at %s; failing terminal",
            ev.id[:8], where,
        )
        if where == "worker":
            # the applier/drain stages count their own refusal metric at
            # the refusal point; the worker-stage refusal is counted here
            from .. import metrics

            metrics.incr("overload.deadline_exceeded.worker")
        try:
            self.server.eval_deadline_exceeded(ev, where)
        except Exception:
            logger.exception(
                "deadline-exceeded update failed for %s", ev.id[:8]
            )
        try:
            self.server.eval_broker.ack(ev.id, token)
        except BrokerError:
            pass

    def process_eval(self, ev: Evaluation, token: str, snapshot=None, collector=None):
        """Dequeue → snapshot ≥ wait index → invoke scheduler → ack/nack
        (ref worker.go:142-276). ``snapshot``/``collector`` are supplied by
        the batch-drain path (one shared snapshot, fused kernel)."""
        if ev.deadline and time.time_ns() >= ev.deadline:
            # refuse BEFORE the snapshot wait and the scheduler invoke:
            # the deadline passed between broker delivery and here
            if collector is not None:
                collector.leave(ev.id)
            self._fail_deadline_exceeded(ev, token, "worker")
            return
        try:
            # the worker's slice of the eval's span tree: dequeue → ack
            # on THIS worker (a nack + re-dequeue elsewhere adds another
            # worker.process span to the same trace)
            with tracer.span(
                "worker.process",
                parent=tracer.ctx_for_eval(ev.id),
                tags={"eval_type": ev.type},
            ):
                # inside the try so an "error"-action rule nacks like any
                # processing failure; a "crash" rule raises SimulatedCrash
                # (BaseException) straight past the handler, like a real
                # death
                _faults.fault_point("worker.post_dequeue")
                if snapshot is None:
                    with tracer.span("eval.snapshot_wait"):
                        snapshot = self._snapshot_with_lease(ev, token)
                    # fresh lease for the scheduling pass itself
                    try:
                        self.server.eval_broker.outstanding_reset(
                            ev.id, token
                        )
                    except BrokerError:
                        pass
                self._eval_token = token
                self._eval = ev
                self._snapshot_index = snapshot.latest_index()
                self.invoke_scheduler(snapshot, ev, collector=collector)
        except DeadlineExceeded as e:
            # a downstream stage (applier verify/commit, drain dispatch)
            # refused the work past its deadline: terminal, not a nack —
            # retrying expired work only deepens the overload
            self._fail_deadline_exceeded(
                ev, token, getattr(e, "where", "") or "worker"
            )
            return
        except Exception:
            logger.exception("eval processing failed; nacking %s", ev.id)
            try:
                self.server.eval_broker.nack(ev.id, token)
            except BrokerError:
                pass
            return
        finally:
            self._eval_token = ""
            self._eval = None
            if collector is not None:
                # no-op if the eval submitted or already left (fallback)
                collector.leave(ev.id)
        try:
            self.server.eval_broker.ack(ev.id, token)
        except BrokerError:
            pass

    def invoke_scheduler(self, snapshot, ev: Evaluation, collector=None):
        """ref worker.go:244-276"""
        if ev.type == "_core":
            # GC runs in-worker against the snapshot (core_sched.go:26)
            from .core_sched import CoreScheduler

            CoreScheduler(self.server, snapshot).process(ev)
            return
        rng = random.Random(self.seed) if self.seed is not None else None
        sched_name = ev.type
        override = self.server.config.get("default_scheduler")
        if override:
            # route evals through the TPU backends: service/batch take the
            # generic-semantics tpu-batch, system takes the plane-batched
            # tpu-system. A non-generic override must never reach
            # service/batch evals (system semantics ignore group counts).
            if ev.type in ("service", "batch") and override in (
                "tpu-batch", "service", "batch"
            ):
                sched_name = override
            elif ev.type == "system" and override in ("tpu-batch", "tpu-system"):
                sched_name = "tpu-system"
        sched = new_scheduler(sched_name, snapshot, self, rng=rng)
        if collector is not None and hasattr(sched, "drain_collector"):
            # non-tpu schedulers simply never consume the collector; the
            # caller's finally-leave covers them
            sched.drain_collector = collector
        from .. import metrics

        with tracer.span(
            "eval.evaluate",
            tags={"scheduler": sched_name},
            metric=f"worker.invoke_scheduler.{sched_name}",
        ):
            sched.process(ev)
        metrics.incr(f"worker.evals_processed.{ev.type}")

    # ------------------------------------------------------------------
    # Planner protocol (ref worker.go:347-523)
    # ------------------------------------------------------------------
    def submit_plan(self, plan: Plan):
        """Attach the eval token, route through the plan queue, and hand back
        a fresh snapshot when the applier asks for a refresh. SnapshotIndex
        is the index this worker actually EVALUATED against (ref worker.go
        SubmitPlan), not the store head: the pipelined applier floors its
        verify snapshot at the batch's max SnapshotIndex, and chasing
        unrelated writes that landed after the scheduler ran only adds
        commit latency without adding safety (the applier re-verifies
        against its own, always-newer, base anyway)."""
        _faults.fault_point("worker.pre_submit")
        plan.eval_token = self._eval_token
        plan.snapshot_index = self._snapshot_index
        with tracer.span("plan.submit", metric="plan.submit"):
            result, error = self.server.plan_submit(plan)
        if error is not None:
            raise error
        if result is None:
            raise RuntimeError("plan submission timed out")

        new_state = None
        if result.refresh_index:
            with tracer.span("plan.refresh_wait"):
                new_state = self.server.state.snapshot_min_index(
                    result.refresh_index, timeout=RAFT_SYNC_LIMIT
                )
            # the scheduler retries against the refreshed snapshot: later
            # submits must carry ITS index (worker.go updates its snapshot
            # watermark on refresh)
            self._snapshot_index = new_state.latest_index()
        return result, new_state

    def update_eval(self, ev: Evaluation):
        """ref worker.go:426-445 (raft Eval.Update; broker routing happens
        in the FSM apply)"""
        self.server.update_evals([ev])
        if ev.status == EVAL_STATUS_FAILED:
            logger.warning("eval failed: %s (%s)", ev.id, ev.status_description)

    def create_eval(self, ev: Evaluation):
        """ref worker.go:447-466"""
        if ev.should_block() and not ev.snapshot_index:
            ev.snapshot_index = self._snapshot_index
        self.server.update_evals([ev])

    def reblock_eval(self, ev: Evaluation):
        """ref worker.go:468-523"""
        if not ev.snapshot_index:
            ev.snapshot_index = self._snapshot_index
        self.server.update_evals([ev])

    def note_kernel_fault(self, reason: str):
        """Surface a device-tier fault the scheduler degraded around
        (tpu/batch_sched.py exact-np fallback): metric + node event on the
        TPU plane. Best-effort — the eval itself already succeeded, and a
        leadership change mid-emission must not fail it retroactively."""
        try:
            self.server.note_kernel_fault(self._eval, reason)
        except Exception:
            logger.exception("kernel-fault event emission failed")


class BatchDrainWorker(Worker):
    """Worker that drains up to ``batch_size`` ready evals per cycle and
    fuses their placement scans into one kernel invocation (the north-star
    bridge: EvalBroker.dequeue_batch → one multi-eval program → individual
    plan submission and ack/nack; SURVEY §2.3, worker.go:105-276).

    Each drained eval runs its full scheduler bookkeeping on its own thread
    against one shared snapshot; their kernels rendezvous at a
    KernelBatchCollector. At-least-once semantics are untouched: every eval
    is acked/nacked individually by its own thread.

    Within a batch the collector double-buffers: the fused kernel is
    dispatched asynchronously and every parked eval wakes AT DISPATCH with
    device handles, so host-side materialization (and the broker refilling
    for the next batch) overlaps device compute. Deeper pipelining —
    spawning batch N+1's eval threads while N's plans are still
    committing — measured strictly worse here: it doubles the optimistic
    plan-apply race surface (≈2× refresh retries) and the extra threads
    contend for the interpreter lock exactly when batch N is
    materializing, so batches are joined before the next dequeue.
    """

    def __init__(self, server, schedulers=None, seed=None, batch_size: int = 16):
        super().__init__(server, schedulers, seed)
        self.batch_size = batch_size

    def run(self):
        while not self._stop.is_set():
            batch = self.server.eval_broker.dequeue_batch(
                self.schedulers, self.batch_size, timeout=DEQUEUE_TIMEOUT
            )
            if not batch:
                continue
            try:
                threads = self.process_batch(batch)
            except _faults.SimulatedCrash:
                # single-eval batches run on this thread: an injected
                # crash kills the whole worker, leases clean up
                logger.warning("drain worker crash injected; thread exiting")
                return
            for t in threads:
                t.join(timeout=120.0)

    def process_batch(self, batch: list) -> list:
        """Spawn one thread per drained eval; returns the threads for the
        run loop to join."""
        live = []
        for ev, token in batch:
            if ev.deadline and time.time_ns() >= ev.deadline:
                # expired between broker delivery and the batch forming:
                # refuse before the shared snapshot wait and the fused
                # kernel ever see it
                self._fail_deadline_exceeded(ev, token, "worker")
            else:
                live.append((ev, token))
        batch = live
        if not batch:
            return []
        if len(batch) == 1:
            self.process_eval(*batch[0])
            return []

        from ..tpu.drain import KernelBatchCollector, SharedCluster

        try:
            snapshot = self.server.state.snapshot_min_index(
                max(ev.modify_index for ev, _ in batch), timeout=RAFT_SYNC_LIMIT
            )
        except Exception:
            logger.exception("drain snapshot failed; nacking batch")
            for ev, token in batch:
                try:
                    self.server.eval_broker.nack(ev.id, token)
                except BrokerError:
                    pass
            return []

        shared = SharedCluster(
            snapshot, mirror=getattr(self.server, "columnar_mirror", None)
        )
        collector = KernelBatchCollector(
            shared, expected=len(batch), pad_evals=self.batch_size
        )
        threads = []
        for ev, token in batch:
            # one planner per eval: SubmitPlan attaches per-eval tokens and
            # refresh snapshots, so workers can't be shared across threads
            w = Worker(self.server, self.schedulers, seed=self.seed)

            def run_one(w=w, ev=ev, token=token):
                try:
                    w.process_eval(
                        ev, token, snapshot=snapshot, collector=collector
                    )
                except _faults.SimulatedCrash:
                    # injected death of one drain lane: no ack/nack — the
                    # broker lease expiry requeues the eval
                    logger.warning(
                        "drain worker crash injected; eval %s left to "
                        "lease expiry",
                        ev.id,
                    )

            # "drain-eval" classifies as worker-class for the profiler:
            # these lanes do the actual plan.submit waiting
            t = threading.Thread(
                target=run_one, daemon=True, name=f"drain-eval-{ev.id[:8]}"
            )
            threads.append(t)
            t.start()
        return threads
