"""System-scheduler corpus ported from the reference
(scheduler/system_sched_test.go — cited per test). Each case drives the
scalar system scheduler through the Harness exactly like the Go tests
drive NewSystemScheduler; kernel-eligible cases additionally run through
tpu-system at the bottom (TestTPUSystemPortParity).
"""

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.testing import Harness, RejectPlan
from nomad_tpu.structs import compute_class
from nomad_tpu.structs.model import (
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_DESIRED_STATUS_EVICT,
    ALLOC_DESIRED_STATUS_STOP,
    AllocatedCpuResources,
    AllocatedMemoryResources,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Constraint,
    NetworkResource,
    NODE_SCHED_INELIGIBLE,
    NodeCpuResources,
    NodeDiskResources,
    NodeMemoryResources,
    NodeResources,
    Port,
    Resources,
    UpdateStrategy,
    generate_uuid,
)
from test_scheduler import make_eval, run_eval, setup_harness


def planned_allocs(plan):
    return [a for allocs in plan.node_allocation.values() for a in allocs]


def updated_allocs(plan):
    return [a for allocs in plan.node_update.values() for a in allocs]


def stored_job(h, job):
    """The state store's copy of an upserted job: allocs must reference IT
    (the Go tests alias the same pointer UpsertJob indexed; this store
    copies on upsert, so alloc.job built from the in-memory original would
    spuriously read as a destructive update)."""
    return h.state.job_by_id(job.namespace, job.id) or job


def sys_alloc(job, node, tg="web"):
    a = mock.alloc()
    a.job = job
    a.job_id = job.id
    a.namespace = job.namespace
    a.node_id = node.id
    a.task_group = tg
    a.name = f"my-job.{tg}[0]"
    return a


def non_terminal(allocs):
    return [a for a in allocs if not a.terminal_status()]


class TestSystemSchedPort:
    def test_job_register(self):
        # ref TestSystemSched_JobRegister (system_sched_test.go:18)
        h, _ = setup_harness(10)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        sched, ev = run_eval(h, job)

        assert len(h.plans) == 1
        plan = h.plans[0]
        assert plan.annotations is None
        assert len(planned_allocs(plan)) == 10
        out = h.state.allocs_by_job(job.namespace, job.id)
        assert len(out) == 10
        # available-node metric records the dc
        assert out[0].metrics.nodes_available.get("dc1") == 10
        assert h.evals[0].queued_allocations.get("web", 0) == 0
        assert h.evals[0].status == "complete"

    def test_job_register_sticky_allocs(self):
        # ref TestSystemSched_JobRegister_StickyAllocs (:92)
        h, _ = setup_harness(10)
        job = mock.system_job()
        job.task_groups[0].ephemeral_disk.sticky = True
        h.state.upsert_job(h.next_index(), job)
        run_eval(h, job)
        plan = h.plans[0]
        assert len(planned_allocs(plan)) == 10

        # fail one alloc on its node; the replacement must stay there
        failed = planned_allocs(plan)[4].copy()
        failed.client_status = ALLOC_CLIENT_STATUS_FAILED
        h.state.update_allocs_from_client(h.next_index(), [failed])

        h1 = Harness(state=h.state, seed=42)
        h1._next_index = h._next_index
        ev = make_eval(job, triggered_by="node-update")
        h1.state.upsert_evals(h1.next_index(), [ev])
        h1.process("system", ev)
        new_planned = planned_allocs(h1.plans[0])
        assert len(new_planned) == 1
        assert new_planned[0].node_id == failed.node_id
        assert new_planned[0].previous_allocation == failed.id

    def test_job_register_ephemeral_disk_constraint(self):
        # ref TestSystemSched_JobRegister_EphemeralDiskConstraint (:168)
        h, _ = setup_harness(1)
        job = mock.system_job()
        job.task_groups[0].ephemeral_disk.size_mb = 60 * 1024
        h.state.upsert_job(h.next_index(), job)
        job1 = mock.system_job()
        job1.task_groups[0].ephemeral_disk.size_mb = 60 * 1024
        h.state.upsert_job(h.next_index(), job1)

        run_eval(h, job)
        assert len(h.state.allocs_by_job(job.namespace, job.id)) == 1

        h1 = Harness(state=h.state, seed=42)
        h1._next_index = h._next_index
        ev1 = make_eval(job1)
        h1.state.upsert_evals(h1.next_index(), [ev1])
        h1.process("system", ev1)
        assert len(h1.state.allocs_by_job(job1.namespace, job1.id)) == 0

    def test_exhaust_resources_preempts_service(self):
        # ref TestSystemSched_ExhaustResources (:237)
        h, _ = setup_harness(1)
        h.state.set_scheduler_config(
            h.next_index(),
            {"preemption_config": {"system_scheduler_enabled": True}},
        )
        svc = mock.job()
        svc.task_groups[0].count = 1
        svc.task_groups[0].tasks[0].resources.cpu = 3600
        h.state.upsert_job(h.next_index(), svc)
        run_eval(h, svc, sched_type="service")

        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        run_eval(h, job)

        new_plan = h.plans[1]
        assert len(new_plan.node_allocation) == 1
        assert len(new_plan.node_preemptions) == 1
        for allocs in new_plan.node_allocation.values():
            assert len(allocs) == 1
            assert allocs[0].job_id == job.id
        for allocs in new_plan.node_preemptions.values():
            assert len(allocs) == 1
            assert allocs[0].job_id == svc.id
        assert h.evals[1].queued_allocations.get("web", 0) == 0

    def test_job_register_annotate(self):
        # ref TestSystemSched_JobRegister_Annotate (:315)
        h = Harness(seed=42)
        for i in range(10):
            n = mock.node()
            n.node_class = "foo" if i < 9 else "bar"
            compute_class(n)
            h.state.upsert_node(h.next_index(), n)
        job = mock.system_job()
        job.constraints.append(
            Constraint(l_target="${node.class}", r_target="foo", operand="==")
        )
        h.state.upsert_job(h.next_index(), job)
        ev = make_eval(job, annotate_plan=True)
        h.state.upsert_evals(h.next_index(), [ev])
        h.process("system", ev)

        assert len(h.plans) == 1
        plan = h.plans[0]
        assert len(planned_allocs(plan)) == 9
        out = h.state.allocs_by_job(job.namespace, job.id)
        assert len(out) == 9
        assert out[0].metrics.nodes_available.get("dc1") == 10
        assert h.evals[0].status == "complete"

        assert plan.annotations is not None
        desired = plan.annotations.desired_tg_updates
        assert set(desired) == {"web"}
        assert desired["web"].place == 9
        assert desired["web"].stop == 0
        assert desired["web"].ignore == 0

    def test_job_register_add_node(self):
        # ref TestSystemSched_JobRegister_AddNode (:411)
        h, nodes = setup_harness(10)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        job = stored_job(h, job)
        allocs = [sys_alloc(job, n) for n in nodes]
        h.state.upsert_allocs(h.next_index(), allocs)

        new_node = mock.node()
        h.state.upsert_node(h.next_index(), new_node)
        ev = make_eval(job, triggered_by="node-update")
        h.state.upsert_evals(h.next_index(), [ev])
        h.process("system", ev)

        assert len(h.plans) == 1
        plan = h.plans[0]
        assert len(updated_allocs(plan)) == 0
        assert len(planned_allocs(plan)) == 1
        assert new_node.id in plan.node_allocation
        out = non_terminal(h.state.allocs_by_job(job.namespace, job.id))
        assert len(out) == 11
        assert h.evals[0].status == "complete"

    def test_job_register_alloc_fail_no_nodes(self):
        # ref TestSystemSched_JobRegister_AllocFail (:501)
        h, _ = setup_harness(0)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        run_eval(h, job)
        # no-op: no plan at all
        assert len(h.plans) == 0
        assert h.evals[0].status == "complete"

    def test_job_modify(self):
        # ref TestSystemSched_JobModify (:533)
        h, nodes = setup_harness(10)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        job = stored_job(h, job)
        allocs = [sys_alloc(job, n) for n in nodes]
        h.state.upsert_allocs(h.next_index(), allocs)

        # terminal allocs are ignored
        terminal = []
        for i in range(5):
            a = sys_alloc(job, nodes[i])
            a.desired_status = ALLOC_DESIRED_STATUS_STOP
            terminal.append(a)
        h.state.upsert_allocs(h.next_index(), terminal)

        job2 = mock.system_job()
        job2.id = job.id
        job2.task_groups[0].tasks[0].config["command"] = "/bin/other"
        h.state.upsert_job(h.next_index(), job2)

        run_eval(h, job2)
        assert len(h.plans) == 1
        plan = h.plans[0]
        assert len(updated_allocs(plan)) == len(allocs)
        assert len(planned_allocs(plan)) == 10
        out = non_terminal(h.state.allocs_by_job(job.namespace, job.id))
        assert len(out) == 10
        assert h.evals[0].status == "complete"

    def test_job_modify_rolling(self):
        # ref TestSystemSched_JobModify_Rolling (:635)
        h, nodes = setup_harness(10)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        job = stored_job(h, job)
        allocs = [sys_alloc(job, n) for n in nodes]
        h.state.upsert_allocs(h.next_index(), allocs)

        job2 = mock.system_job()
        job2.id = job.id
        job2.update = UpdateStrategy(
            stagger=30 * 1_000_000_000, max_parallel=5
        )
        job2.task_groups[0].tasks[0].config["command"] = "/bin/other"
        h.state.upsert_job(h.next_index(), job2)

        run_eval(h, job2)
        assert len(h.plans) == 1
        plan = h.plans[0]
        assert len(updated_allocs(plan)) == job2.update.max_parallel
        assert len(planned_allocs(plan)) == job2.update.max_parallel
        assert h.evals[0].status == "complete"

        # a follow-up rolling eval was created and linked
        assert h.evals[0].next_eval
        assert h.create_evals
        create = h.create_evals[0]
        assert h.evals[0].next_eval == create.id
        assert create.previous_eval == h.evals[0].id
        assert create.triggered_by == "rolling-update"

    def test_job_modify_in_place(self):
        # ref TestSystemSched_JobModify_InPlace (:738)
        h, nodes = setup_harness(10)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        job = stored_job(h, job)
        allocs = [sys_alloc(job, n) for n in nodes]
        h.state.upsert_allocs(h.next_index(), allocs)

        job2 = mock.system_job()
        job2.id = job.id
        h.state.upsert_job(h.next_index(), job2)

        run_eval(h, job2)
        assert len(h.plans) == 1
        plan = h.plans[0]
        assert len(updated_allocs(plan)) == 0
        planned = planned_allocs(plan)
        assert len(planned) == 10
        # every existing alloc was updated in place to the new job version
        job2_stored = stored_job(h, job2)
        out = h.state.allocs_by_job(job.namespace, job.id)
        assert len(out) == 10
        for a in out:
            assert a.job.job_modify_index == job2_stored.job_modify_index
        assert h.evals[0].status == "complete"

    def test_job_deregister_purged(self):
        # ref TestSystemSched_JobDeregister_Purged (:837)
        h, nodes = setup_harness(10)
        job = mock.system_job()  # NOT in state: purged
        allocs = [sys_alloc(job, n) for n in nodes]
        h.state.upsert_allocs(h.next_index(), allocs)

        ev = make_eval(job, triggered_by="job-deregister")
        h.state.upsert_evals(h.next_index(), [ev])
        h.process("system", ev)

        assert len(h.plans) == 1
        plan = h.plans[0]
        for n in nodes:
            assert len(plan.node_update.get(n.id, [])) == 1
        out = non_terminal(h.state.allocs_by_job(job.namespace, job.id))
        assert len(out) == 0
        assert h.evals[0].status == "complete"

    def test_job_deregister_stopped(self):
        # ref TestSystemSched_JobDeregister_Stopped (:909)
        h, nodes = setup_harness(10)
        job = mock.system_job()
        job.stop = True
        h.state.upsert_job(h.next_index(), job)
        job_s = stored_job(h, job)
        allocs = [sys_alloc(job_s, n) for n in nodes]
        h.state.upsert_allocs(h.next_index(), allocs)

        ev = make_eval(job, triggered_by="job-deregister")
        h.state.upsert_evals(h.next_index(), [ev])
        h.process("system", ev)

        assert len(h.plans) == 1
        plan = h.plans[0]
        for n in nodes:
            assert len(plan.node_update.get(n.id, [])) == 1
        out = non_terminal(h.state.allocs_by_job(job.namespace, job.id))
        assert len(out) == 0
        assert h.evals[0].status == "complete"

    def test_node_down(self):
        # ref TestSystemSched_NodeDown (:983)
        h = Harness(seed=42)
        node = mock.node()
        node.status = "down"
        h.state.upsert_node(h.next_index(), node)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        a = sys_alloc(stored_job(h, job), node)
        a.desired_transition.migrate = True
        h.state.upsert_allocs(h.next_index(), [a])

        ev = make_eval(job, triggered_by="node-update", node_id=node.id)
        h.state.upsert_evals(h.next_index(), [ev])
        h.process("system", ev)

        assert len(h.plans) == 1
        plan = h.plans[0]
        assert len(plan.node_update.get(node.id, [])) == 1
        planned = updated_allocs(plan)
        assert len(planned) == 1
        assert (
            planned[0].desired_status == ALLOC_DESIRED_STATUS_STOP
            or planned[0].client_status == "lost"
        )
        assert h.evals[0].status == "complete"

    def test_node_drain_down(self):
        # ref TestSystemSched_NodeDrain_Down (:1050)
        h = Harness(seed=42)
        node = mock.node()
        node.drain = True
        node.status = "down"
        h.state.upsert_node(h.next_index(), node)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        a = sys_alloc(stored_job(h, job), node)
        h.state.upsert_allocs(h.next_index(), [a])

        ev = make_eval(job, triggered_by="node-update", node_id=node.id)
        h.state.upsert_evals(h.next_index(), [ev])
        h.process("system", ev)

        assert len(h.plans) == 1
        plan = h.plans[0]
        lost = [x.id for x in plan.node_update.get(node.id, [])]
        assert lost == [a.id]
        assert h.evals[0].status == "complete"

    def test_node_drain(self):
        # ref TestSystemSched_NodeDrain (:1112)
        h = Harness(seed=42)
        node = mock.node()
        node.drain = True
        h.state.upsert_node(h.next_index(), node)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        a = sys_alloc(stored_job(h, job), node)
        a.desired_transition.migrate = True
        h.state.upsert_allocs(h.next_index(), [a])

        ev = make_eval(job, triggered_by="node-update", node_id=node.id)
        h.state.upsert_evals(h.next_index(), [ev])
        h.process("system", ev)

        assert len(h.plans) == 1
        plan = h.plans[0]
        assert len(plan.node_update.get(node.id, [])) == 1
        planned = updated_allocs(plan)
        assert len(planned) == 1
        assert planned[0].desired_status == ALLOC_DESIRED_STATUS_STOP
        assert h.evals[0].status == "complete"

    def test_node_update_no_changes(self):
        # ref TestSystemSched_NodeUpdate (:1179)
        h = Harness(seed=42)
        node = mock.node()
        h.state.upsert_node(h.next_index(), node)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        a = sys_alloc(stored_job(h, job), node)
        h.state.upsert_allocs(h.next_index(), [a])

        ev = make_eval(job, triggered_by="node-update", node_id=node.id)
        h.state.upsert_evals(h.next_index(), [ev])
        h.process("system", ev)

        assert h.evals[0].queued_allocations.get("web", 0) == 0
        assert h.evals[0].status == "complete"

    def test_retry_limit(self):
        # ref TestSystemSched_RetryLimit (:1223)
        h, _ = setup_harness(10)
        h.planner = RejectPlan(h)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        run_eval(h, job)

        assert len(h.plans) > 0
        assert len(h.state.allocs_by_job(job.namespace, job.id)) == 0
        assert h.evals[0].status == "failed"

    def test_queued_with_constraints(self):
        # ref TestSystemSched_Queued_With_Constraints (:1276)
        h = Harness(seed=42)
        node = mock.node()
        node.attributes["kernel.name"] = "darwin"
        h.state.upsert_node(h.next_index(), node)
        job = mock.system_job()  # constrained to kernel.name = linux
        h.state.upsert_job(h.next_index(), job)
        ev = make_eval(job, triggered_by="node-update", node_id=node.id)
        h.state.upsert_evals(h.next_index(), [ev])
        h.process("system", ev)

        assert h.evals[0].queued_allocations.get("web", 0) == 0

    def test_constraint_errors(self):
        # ref TestSystemSched_ConstraintErrors (:1314)
        h = Harness(seed=42)
        node = None
        for tag in ["aaaaaa", "foo", "foo", "foo"]:
            node = mock.node()
            node.meta["tag"] = tag
            compute_class(node)
            h.state.upsert_node(h.next_index(), node)
        # mark the last node ineligible (via the dedicated transition —
        # plain re-registration retains the stored eligibility, matching
        # the reference's upsertNodeTxn; the Go test leans on memdb
        # pointer aliasing to mutate in place)
        h.state.update_node_eligibility(
            h.next_index(), node.id, NODE_SCHED_INELIGIBLE
        )

        job = mock.system_job()
        job.constraints.append(
            Constraint(l_target="${meta.tag}", r_target="foo", operand="=")
        )
        h.state.upsert_job(h.next_index(), job)
        run_eval(h, job)

        assert h.evals[0].status == "complete"
        assert h.evals[0].queued_allocations.get("web") == 0
        assert len(h.plans) == 1
        assert h.plans[0].annotations is None
        # two eligible matching nodes
        assert len(h.plans[0].node_allocation) == 2
        allocs = h.state.allocs_by_job(job.namespace, job.id)
        assert len(allocs) == 2
        # no spurious failed-TG metrics
        assert not h.evals[0].failed_tg_allocs

    def test_chained_alloc(self):
        # ref TestSystemSched_ChainedAlloc (:1385)
        h, _ = setup_harness(10)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        run_eval(h, job)
        alloc_ids = sorted(a.id for a in planned_allocs(h.plans[0]))

        h1 = Harness(state=h.state, seed=42)
        h1._next_index = h._next_index
        job1 = mock.system_job()
        job1.id = job.id
        job1.task_groups[0].tasks[0].env = {"foo": "bar"}
        h1.state.upsert_job(h1.next_index(), job1)
        for _ in range(2):
            h1.state.upsert_node(h1.next_index(), mock.node())

        ev1 = make_eval(job1)
        h1.state.upsert_evals(h1.next_index(), [ev1])
        h1.process("system", ev1)

        plan = h1.plans[0]
        prev_allocs, new_allocs = [], []
        for a in planned_allocs(plan):
            if a.previous_allocation:
                prev_allocs.append(a.previous_allocation)
            else:
                new_allocs.append(a.id)
        # every replacement chains to one of the original allocs; the two
        # new nodes get unchained placements
        assert sorted(prev_allocs) == alloc_ids
        assert len(new_allocs) == 2

    def test_plan_with_drained_node(self):
        # ref TestSystemSched_PlanWithDrainedNode (:1479)
        h = Harness(seed=42)
        node = mock.node()
        node.node_class = "green"
        node.drain = True
        compute_class(node)
        h.state.upsert_node(h.next_index(), node)
        node2 = mock.node()
        node2.node_class = "blue"
        compute_class(node2)
        h.state.upsert_node(h.next_index(), node2)

        job = mock.system_job()
        tg1 = job.task_groups[0]
        tg1.constraints.append(
            Constraint(l_target="${node.class}", r_target="green", operand="==")
        )
        tg2 = tg1.copy()
        tg2.name = "web2"
        tg2.constraints[-1].r_target = "blue"
        job.task_groups.append(tg2)
        h.state.upsert_job(h.next_index(), job)
        job_s = stored_job(h, job)

        a = sys_alloc(job_s, node)
        a.desired_transition.migrate = True
        a2 = sys_alloc(job_s, node2, tg="web2")
        h.state.upsert_allocs(h.next_index(), [a, a2])

        ev = make_eval(job, triggered_by="node-update", node_id=node.id)
        h.state.upsert_evals(h.next_index(), [ev])
        h.process("system", ev)

        assert len(h.plans) == 1
        plan = h.plans[0]
        planned = plan.node_update.get(node.id, [])
        assert len(planned) == 1
        assert len(plan.node_allocation) == 0
        assert planned[0].desired_status == ALLOC_DESIRED_STATUS_STOP
        assert h.evals[0].status == "complete"

    def test_queued_allocs_multiple_tgs(self):
        # ref TestSystemSched_QueuedAllocsMultTG (:1570)
        h = Harness(seed=42)
        node = mock.node()
        node.node_class = "green"
        compute_class(node)
        h.state.upsert_node(h.next_index(), node)
        node2 = mock.node()
        node2.node_class = "blue"
        compute_class(node2)
        h.state.upsert_node(h.next_index(), node2)

        job = mock.system_job()
        tg1 = job.task_groups[0]
        tg1.constraints.append(
            Constraint(l_target="${node.class}", r_target="green", operand="==")
        )
        tg2 = tg1.copy()
        tg2.name = "web2"
        tg2.constraints[-1].r_target = "blue"
        job.task_groups.append(tg2)
        h.state.upsert_job(h.next_index(), job)

        ev = make_eval(job, triggered_by="node-update", node_id=node.id)
        h.state.upsert_evals(h.next_index(), [ev])
        h.process("system", ev)

        assert len(h.plans) == 1
        qa = h.evals[0].queued_allocations
        assert qa.get("web", 0) == 0 and qa.get("web2", 0) == 0
        assert h.evals[0].status == "complete"

    def test_system_preemption_two_nodes(self):
        # ref TestSystemSched_Preemption (:1631)
        h = Harness(seed=42)
        nodes = []
        for _ in range(2):
            n = mock.node()
            n.node_resources = NodeResources(
                cpu=NodeCpuResources(cpu_shares=3072),
                memory=NodeMemoryResources(memory_mb=5034),
                disk=NodeDiskResources(disk_mb=20 * 1024),
                networks=[
                    NetworkResource(
                        device="eth0", cidr="192.168.0.100/32",
                        ip="192.168.0.100", mbits=1000,
                    )
                ],
            )
            h.state.upsert_node(h.next_index(), n)
            nodes.append(n)

        h.state.set_scheduler_config(
            h.next_index(),
            {"preemption_config": {"system_scheduler_enabled": True}},
        )

        def batch_with_alloc(priority, cpu, mem, networks, disk, name):
            j = mock.batch_job()
            j.type = "batch"
            j.priority = priority
            a = mock.alloc()
            a.job = j
            a.job_id = j.id
            a.namespace = j.namespace
            a.node_id = nodes[0].id
            a.name = name
            a.task_group = j.task_groups[0].name
            a.allocated_resources = AllocatedResources(
                tasks={
                    "web": AllocatedTaskResources(
                        cpu=AllocatedCpuResources(cpu_shares=cpu),
                        memory=AllocatedMemoryResources(memory_mb=mem),
                        networks=networks,
                    )
                },
                shared=AllocatedSharedResources(disk_mb=disk),
            )
            return j, a

        job1, alloc1 = batch_with_alloc(
            20, 512, 1024,
            [NetworkResource(
                device="eth0", ip="192.168.0.100", mbits=200,
                reserved_ports=[Port(label="web", value=80)],
            )],
            5 * 1024, "my-job[0]",
        )
        h.state.upsert_job(h.next_index(), job1)
        job2, alloc2 = batch_with_alloc(
            20, 512, 1024,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=200)],
            5 * 1024, "my-job[2]",
        )
        h.state.upsert_job(h.next_index(), job2)
        job3, alloc3 = batch_with_alloc(
            40, 1024, 25,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=400)],
            5 * 1024, "my-job[0]",
        )
        h.state.upsert_job(h.next_index(), job3)
        h.state.upsert_allocs(
            h.next_index(), [alloc1, alloc2, alloc3]
        )

        # high-priority allocs must NOT be preempted
        job4, alloc4 = batch_with_alloc(
            100, 1024, 2048,
            [NetworkResource(device="eth0", ip="192.168.0.100", mbits=100)],
            2 * 1024, "my-job4[0]",
        )
        h.state.upsert_job(h.next_index(), job4)
        h.state.upsert_allocs(h.next_index(), [alloc4])

        job = mock.system_job()
        job.task_groups[0].tasks[0].resources = Resources(
            cpu=1948, memory_mb=256,
            networks=[NetworkResource(
                mbits=800, dynamic_ports=[Port(label="http")]
            )],
        )
        h.state.upsert_job(h.next_index(), job)
        run_eval(h, job)

        assert len(h.plans) == 1
        plan = h.plans[0]
        assert plan.annotations is None
        assert len(plan.node_allocation) == 2
        preempting_alloc_id = next(
            a.id
            for a in plan.node_allocation.get(nodes[0].id, [])
        )
        assert len(h.state.allocs_by_job(job.namespace, job.id)) == 2

        assert nodes[0].id in plan.node_preemptions
        victims = plan.node_preemptions[nodes[0].id]
        assert len(victims) == 3
        expected_jobs = {job1.id, job2.id, job3.id}
        assert {v.job_id for v in victims} == expected_jobs

        # committed state: victims evicted with the preemptor recorded
        for jid in expected_jobs:
            for a in h.state.allocs_by_job("default", jid):
                assert a.desired_status == ALLOC_DESIRED_STATUS_EVICT
                assert preempting_alloc_id in a.desired_description
        assert h.evals[0].status == "complete"


class TestTPUSystemPortParity:
    """Kernel-eligible system corpus cases re-run through tpu-system — the
    placement sets must match the scalar oracle exactly."""

    @pytest.mark.parametrize("num_nodes", [1, 7, 10])
    def test_register_all_nodes_via_kernel(self, num_nodes):
        h, _ = setup_harness(num_nodes)
        job = mock.system_job()
        h.state.upsert_job(h.next_index(), job)
        run_eval(h, job, sched_type="tpu-system")
        assert len(h.state.allocs_by_job(job.namespace, job.id)) == num_nodes

        h2, _ = setup_harness(num_nodes)
        job2 = mock.system_job()
        h2.state.upsert_job(h2.next_index(), job2)
        run_eval(h2, job2, sched_type="system")
        assert len(h2.state.allocs_by_job(job2.namespace, job2.id)) == num_nodes

    def test_annotate_constraint_subset_via_kernel(self):
        def scenario(sched_type):
            h = Harness(seed=42)
            for i in range(10):
                n = mock.node()
                n.node_class = "foo" if i < 9 else "bar"
                compute_class(n)
                h.state.upsert_node(h.next_index(), n)
            job = mock.system_job()
            job.constraints.append(
                Constraint(
                    l_target="${node.class}", r_target="foo", operand="=="
                )
            )
            h.state.upsert_job(h.next_index(), job)
            run_eval(h, job, sched_type=sched_type)
            return len(h.state.allocs_by_job(job.namespace, job.id))

        assert scenario("tpu-system") == scenario("system") == 9

    def test_drain_migration_via_kernel(self):
        def scenario(sched_type):
            h = Harness(seed=42)
            nodes = []
            for _ in range(4):
                n = mock.node()
                nodes.append(n)
                h.state.upsert_node(h.next_index(), n)
            job = mock.system_job()
            h.state.upsert_job(h.next_index(), job)
            allocs = [sys_alloc(job, n) for n in nodes]
            allocs[0].desired_transition.migrate = True
            h.state.upsert_allocs(h.next_index(), allocs)
            drained = nodes[0].copy()
            drained.drain = True
            h.state.upsert_node(h.next_index(), drained)
            ev = make_eval(
                job, triggered_by="node-update", node_id=drained.id
            )
            h.state.upsert_evals(h.next_index(), [ev])
            h.process(sched_type, ev)
            stops = sorted(
                a.id
                for a in h.plans[0].node_update.get(drained.id, [])
            )
            return stops, allocs[0].id

        kernel_stops, kid = scenario("tpu-system")
        oracle_stops, oid = scenario("system")
        assert len(kernel_stops) == len(oracle_stops) == 1
