"""Sharded execution of all three placement planners on the 8-device
virtual mesh (conftest.py): the node axis — the framework's scale axis — is
partitioned with NamedSharding(P("nodes")) and every planner must produce
EXACTLY the placements of its unsharded run (GSPMD inserts the cross-shard
argmax/gather collectives; semantics may not drift).

This is the multi-chip contract the driver's dryrun validates at compile
level; these tests pin value-level equality so a sharding regression in any
planner fails the suite (VERDICT r2 next-round #1)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nomad_tpu.tpu.kernel import (
    BatchArgs,
    BatchState,
    RunArgs,
    WindowArgs,
    plan_batch,
    plan_batch_runs,
    plan_batch_windowed,
)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    if len(devices) < N_DEV:
        pytest.skip(f"need {N_DEV} virtual devices, have {len(devices)}")
    return Mesh(np.array(devices[:N_DEV]), ("nodes",))


def build_cluster(n_nodes, n_allocs, n_values=4, seed=0):
    """Heterogeneous capacities, ~10% infeasible nodes, spread classes."""
    rng = np.random.default_rng(seed)
    capacity = np.stack(
        [
            rng.choice([4000, 8000, 16000, 32000], n_nodes),
            rng.choice([8192, 16384, 32768], n_nodes),
            np.full(n_nodes, 100 * 1024),
            np.full(n_nodes, 1000),
        ],
        axis=1,
    ).astype(np.int32)
    reserved = np.tile(np.array([100, 256, 4096, 0], dtype=np.int32), (n_nodes, 1))
    usable = (capacity[:, :2] - reserved[:, :2]).astype(np.float32)
    feasible = rng.random(n_nodes) > 0.1
    node_value = (np.arange(n_nodes) % n_values).astype(np.int32)
    perm = rng.permutation(n_nodes).astype(np.int32)
    demand = np.array([100, 128, 10, 5], dtype=np.int32)
    return dict(
        capacity=capacity,
        reserved=reserved,
        usable=usable,
        feasible=feasible,
        node_value=node_value,
        perm=perm,
        demand=demand,
        n_allocs=n_allocs,
        n_values=n_values,
    )


def exact_args(c, spread=True):
    n_nodes = c["capacity"].shape[0]
    n_allocs = c["n_allocs"]
    V = c["n_values"]
    args = BatchArgs(
        capacity=c["capacity"],
        usable=c["usable"],
        feasible=c["feasible"][None, :],
        affinity=np.zeros((1, n_nodes), dtype=np.float32),
        affinity_present=np.zeros((1, n_nodes), dtype=bool),
        group_count=np.full(1, n_allocs, dtype=np.int32),
        group_eval=np.zeros(1, dtype=np.int32),
        node_value=c["node_value"][None, :],
        spread_desired=np.full(
            (1, V), float(n_allocs) / V if spread else -1.0, dtype=np.float32
        ),
        spread_implicit=np.full(1, -1.0, dtype=np.float32),
        spread_weight_frac=np.ones(1, dtype=np.float32),
        spread_even=np.zeros(1, dtype=bool),
        spread_active=np.full(1, spread, dtype=bool),
        perm=c["perm"][None, :],
        ring=np.array([n_nodes], dtype=np.int32),
        demands=np.tile(c["demand"], (n_allocs, 1)),
        groups=np.zeros(n_allocs, dtype=np.int32),
        limits=np.full(n_allocs, n_nodes, dtype=np.int32),
        valid=np.ones(n_allocs, dtype=bool),
    )
    init = BatchState(
        used=c["reserved"].copy(),
        collisions=np.zeros((1, n_nodes), dtype=np.int32),
        spread_counts=np.zeros((1, V), dtype=np.int32),
        spread_present=np.zeros((1, V), dtype=bool),
        offset=np.zeros(1, dtype=np.int32),
    )
    return args, init


def exact_shardings(mesh):
    rows = NamedSharding(mesh, P("nodes", None))
    cols = NamedSharding(mesh, P(None, "nodes"))
    rep = NamedSharding(mesh, P())
    args = BatchArgs(
        capacity=rows, usable=rows, feasible=cols, affinity=cols,
        affinity_present=cols, group_count=rep, group_eval=rep,
        node_value=cols, spread_desired=rep, spread_implicit=rep,
        spread_weight_frac=rep, spread_even=rep, spread_active=rep,
        perm=cols, ring=rep, demands=rep, groups=rep, limits=rep, valid=rep,
    )
    state = BatchState(
        used=rows, collisions=cols, spread_counts=rep,
        spread_present=rep, offset=rep,
    )
    return args, state


def test_exact_scan_sharded_equals_unsharded(mesh):
    """Exact sequential-scan kernel at 1K nodes: node axis over 8 devices."""
    c = build_cluster(1024, 96)
    args, init = exact_args(c)
    n_real = 1024

    _, want = plan_batch(
        BatchArgs(*[jnp.asarray(a) for a in args]),
        BatchState(*[jnp.asarray(s) for s in init]),
        n_real,
    )
    want = np.asarray(want)

    arg_sh, st_sh = exact_shardings(mesh)
    d_args = jax.device_put(BatchArgs(*[jnp.asarray(a) for a in args]), arg_sh)
    d_init = jax.device_put(BatchState(*[jnp.asarray(s) for s in init]), st_sh)
    _, got = plan_batch(d_args, d_init, n_real)
    got = np.asarray(got)

    assert (want >= 0).sum() == c["n_allocs"]
    np.testing.assert_array_equal(want, got)


def _run_args(c, affinity=True, spread=True):
    n_nodes = c["capacity"].shape[0]
    V = c["n_values"]
    perm = c["perm"]
    aff = np.where(
        np.arange(n_nodes) % 5 == 0, 0.5, 0.0
    ).astype(np.float32) if affinity else np.zeros(n_nodes, dtype=np.float32)
    rargs = RunArgs(
        capacity=c["capacity"][perm],
        usable=c["usable"][perm],
        feasible=c["feasible"][perm],
        affinity=aff[perm],
        affinity_present=(aff > 0)[perm],
        group_count=np.int32(c["n_allocs"]),
        node_value=c["node_value"][perm],
        spread_desired=np.full(
            V, float(c["n_allocs"]) / V if spread else -1.0, dtype=np.float32
        ),
        spread_implicit=np.float32(-1.0),
        spread_weight_frac=np.float32(1.0),
        spread_even=False,
        spread_active=spread,
        perm=perm,
        demand=c["demand"],
        n_allocs=np.int32(c["n_allocs"]),
    )
    init = (
        c["reserved"][perm],
        np.zeros(n_nodes, dtype=np.int32),
        np.zeros(V, dtype=np.int32),
        np.zeros(V, dtype=bool),
    )
    return rargs, init


def test_runs_planner_sharded_equals_unsharded(mesh):
    """Run-based full-ring planner under NamedSharding(P('nodes'))."""
    c = build_cluster(1024, 512, seed=3)
    rargs, init = _run_args(c)
    a_pad = 512

    want = np.asarray(
        plan_batch_runs(
            RunArgs(*[jnp.asarray(a) for a in rargs]),
            tuple(jnp.asarray(x) for x in init),
            a_pad,
            False,
        )
    )

    node = NamedSharding(mesh, P("nodes"))
    rows = NamedSharding(mesh, P("nodes", None))
    rep = NamedSharding(mesh, P())
    arg_sh = RunArgs(
        capacity=rows, usable=rows, feasible=node, affinity=node,
        affinity_present=node, group_count=rep, node_value=node,
        spread_desired=rep, spread_implicit=rep, spread_weight_frac=rep,
        spread_even=rep, spread_active=rep, perm=node, demand=rep,
        n_allocs=rep,
    )
    d_args = jax.device_put(RunArgs(*[jnp.asarray(a) for a in rargs]), arg_sh)
    d_init = (
        jax.device_put(jnp.asarray(init[0]), rows),
        jax.device_put(jnp.asarray(init[1]), node),
        jax.device_put(jnp.asarray(init[2]), rep),
        jax.device_put(jnp.asarray(init[3]), rep),
    )
    got = np.asarray(plan_batch_runs(d_args, d_init, a_pad, False))

    assert (want >= 0).sum() > 0
    np.testing.assert_array_equal(want, got)


def test_windowed_planner_sharded_equals_unsharded(mesh):
    """Rotation-parallel windowed planner under NamedSharding(P('nodes'))."""
    c = build_cluster(1024, 512, seed=5)
    n_real, a_pad = 1024, 512
    wargs = WindowArgs(
        capacity=c["capacity"],
        usable=c["usable"],
        feasible=c["feasible"],
        perm=c["perm"],
        demand=c["demand"],
        group_count=np.int32(c["n_allocs"]),
        limit=np.int32(10),  # log2(1024)
        n_allocs=np.int32(c["n_allocs"]),
    )
    used0 = c["reserved"].copy()
    coll0 = np.zeros(n_real, dtype=np.int32)

    want = np.asarray(
        plan_batch_windowed(
            WindowArgs(*[jnp.asarray(a) for a in wargs]),
            jnp.asarray(used0),
            jnp.asarray(coll0),
            n_real,
            a_pad,
        )
    )

    node = NamedSharding(mesh, P("nodes"))
    rows = NamedSharding(mesh, P("nodes", None))
    rep = NamedSharding(mesh, P())
    arg_sh = WindowArgs(
        capacity=rows, usable=rows, feasible=node, perm=node,
        demand=rep, group_count=rep, limit=rep, n_allocs=rep,
    )
    d_args = jax.device_put(WindowArgs(*[jnp.asarray(a) for a in wargs]), arg_sh)
    got = np.asarray(
        plan_batch_windowed(
            d_args,
            jax.device_put(jnp.asarray(used0), rows),
            jax.device_put(jnp.asarray(coll0), node),
            n_real,
            a_pad,
        )
    )

    assert (want >= 0).sum() > 0
    np.testing.assert_array_equal(want, got)


def test_exact_scan_sharded_multi_group(mesh):
    """Two groups with different demands sharing the usage plane, sharded."""
    n_nodes, n_allocs = 512, 64
    c = build_cluster(n_nodes, n_allocs, seed=9)
    args, init = exact_args(c, spread=False)
    # second group: double demand, no spread
    args = args._replace(
        feasible=np.concatenate([args.feasible, args.feasible]),
        affinity=np.concatenate([args.affinity, args.affinity]),
        affinity_present=np.concatenate(
            [args.affinity_present, args.affinity_present]
        ),
        group_count=np.array([n_allocs // 2, n_allocs // 2], dtype=np.int32),
        group_eval=np.zeros(2, dtype=np.int32),
        node_value=np.concatenate([args.node_value, args.node_value]),
        spread_desired=np.full((2, c["n_values"]), -1.0, dtype=np.float32),
        spread_implicit=np.full(2, -1.0, dtype=np.float32),
        spread_weight_frac=np.zeros(2, dtype=np.float32),
        spread_even=np.zeros(2, dtype=bool),
        spread_active=np.zeros(2, dtype=bool),
        demands=np.where(
            (np.arange(n_allocs) % 2 == 0)[:, None],
            c["demand"],
            c["demand"] * 2,
        ).astype(np.int32),
        groups=(np.arange(n_allocs) % 2).astype(np.int32),
    )
    init = init._replace(
        collisions=np.zeros((2, n_nodes), dtype=np.int32),
        spread_counts=np.zeros((2, c["n_values"]), dtype=np.int32),
        spread_present=np.zeros((2, c["n_values"]), dtype=bool),
    )

    _, want = plan_batch(
        BatchArgs(*[jnp.asarray(a) for a in args]),
        BatchState(*[jnp.asarray(s) for s in init]),
        n_nodes,
    )
    want = np.asarray(want)

    arg_sh, st_sh = exact_shardings(mesh)
    d_args = jax.device_put(BatchArgs(*[jnp.asarray(a) for a in args]), arg_sh)
    d_init = jax.device_put(BatchState(*[jnp.asarray(s) for s in init]), st_sh)
    _, got = plan_batch(d_args, d_init, n_nodes)

    assert (want >= 0).sum() == n_allocs
    np.testing.assert_array_equal(want, np.asarray(got))
