"""Task drivers (ref plugins/drivers/ + drivers/{mock,rawexec}).

The driver interface mirrors the reference's gRPC Driver service surface
(plugins/drivers/proto/driver.proto:13-84) in-process: fingerprint,
start/wait/stop/destroy/inspect/signal. The mock driver reproduces the
reference's scriptable test driver (drivers/mock): configurable run duration,
exit codes, and start errors. RawExecDriver runs real subprocesses with no
isolation (drivers/rawexec); the isolated exec driver arrives with the C++
executor.
"""

from __future__ import annotations

import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..structs.model import Task


def task_log_dir(task_dir: str) -> str:
    """Log directory inside a task dir (ref allocdir: alloc/logs)."""
    import os

    return os.path.join(task_dir, "logs")


def parse_duration(v) -> float:
    """Seconds from a number or a Go-style duration string ("250ms",
    "1m30s" — the format the reference's mock driver configs use,
    drivers/mock/driver.go run_for). Delegates to the jobspec parser so
    compound durations behave identically everywhere."""
    if isinstance(v, (int, float)):
        return float(v)
    from ..jobspec.hcl import parse_duration as _hcl_duration

    return _hcl_duration(str(v)) / 1e9


@dataclass
class TaskHandle:
    task_name: str = ""
    driver: str = ""
    proc: Optional[object] = None
    pid: int = 0
    exit_code: Optional[int] = None
    error: str = ""
    started_at: int = 0
    finished_at: int = 0
    recovered: bool = False
    _done: threading.Event = field(default_factory=threading.Event)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def finish(self, exit_code: int, error: str = ""):
        self.exit_code = exit_code
        self.error = error
        self.finished_at = time.time_ns()
        self._done.set()


class Driver:
    """Driver plugin interface (ref plugins/drivers/driver.go)."""

    name = "driver"

    def __init__(self):
        # per-instance: callers mutate in place (plugin_config.update),
        # so a class-level shared dict would leak config across drivers
        self.plugin_config: dict = {}

    def fingerprint(self) -> dict:
        """Returns {detected, healthy, attributes}."""
        return {"detected": True, "healthy": True, "attributes": {}}

    def start_task(self, task: Task, task_dir: str) -> TaskHandle:
        raise NotImplementedError

    def stop_task(self, handle: TaskHandle, timeout: float = 5.0,
                  signal_name: str = ""):
        """Gracefully stop: deliver ``signal_name`` (or the platform
        default), escalate to a hard kill after ``timeout`` (ref
        driver.proto StopTask's kill_timeout + the task kill_signal)."""
        raise NotImplementedError

    def destroy_task(self, handle: TaskHandle):
        pass

    def inspect_task(self, handle: TaskHandle) -> dict:
        return {
            "exit_code": handle.exit_code,
            "error": handle.error,
            "running": not handle._done.is_set(),
        }

    def signal_task(self, handle: TaskHandle, signal_name: str):
        """Deliver a signal to the running task (ref driver.proto
        SignalTask). Drivers without signal support raise."""
        raise ValueError(f"driver {self.name} does not support signals")

    def exec_streaming(
        self,
        handle: TaskHandle,
        cmd: list,
        tty: bool = False,
        task_dir: str = "",
        env: Optional[dict] = None,
    ):
        """Run a command INSIDE the task's execution context with
        bidirectional streaming IO (ref driver.proto:72-76
        ExecTaskStreaming); returns a client.execstream.ExecProcess.
        Drivers without an execution context to enter raise."""
        raise ValueError(f"driver {self.name} does not support exec")

    def task_stats(self, handle: TaskHandle) -> dict:
        """Per-task resource usage (ref driver.proto:59 TaskStats →
        TaskResourceUsage): cumulative cpu seconds, sampled cpu percent,
        RSS and pid count. The default walks the handle's process tree —
        right for every driver whose task is a local child (exec family,
        java, qemu); container runtimes override with their own stats
        source (docker stats)."""
        from .stats import task_resource_usage

        return task_resource_usage(handle)

    # -- plugin config (ref plugins/base/proto base.proto: ConfigSchema +
    # SetConfig, with hclspec's schema-validation role) -----------------
    def config_schema(self) -> dict:
        """{key: {"type": "string|number|bool", "required": bool,
        "default": ...}} describing the driver's plugin config."""
        return {}

    def set_config(self, config: dict):
        """Apply validated plugin configuration."""
        self.plugin_config = dict(config)

    # -- recovery (ref plugins/drivers/proto/driver.proto:35 RecoverTask) --
    def handle_data(self, handle: TaskHandle) -> dict:
        """Serializable reattach info persisted in the client state DB."""
        return {"driver": self.name, "task_name": handle.task_name}

    def recover_task(self, task: Task, data: dict) -> Optional[TaskHandle]:
        """Reattach to a task started by a previous client process; returns
        None when the task can't be recovered (the runner restarts it)."""
        return None


class MockDriver(Driver):
    """Scriptable driver for tests (ref drivers/mock/driver.go).

    Task config keys:
      run_for          seconds to run before exiting (default 0: exit now)
      exit_code        exit code to report (default 0)
      start_error      error string raised at start
      start_block_for  seconds to block in start
    """

    name = "mock_driver"

    def __init__(self):
        super().__init__()
        self._timers: dict[int, threading.Timer] = {}

    def config_schema(self) -> dict:
        """ref drivers/mock config options (subset), exercised by the
        plugin-protocol ConfigSchema/SetConfig tests."""
        return {
            "fingerprint_attr": {"type": "string"},
            "shutdown_delay_s": {"type": "number", "default": 0},
            "fail_fingerprint": {"type": "bool", "default": False},
        }

    def fingerprint(self) -> dict:
        if self.plugin_config.get("fail_fingerprint"):
            return {"detected": True, "healthy": False, "attributes": {}}
        attrs = {}
        if self.plugin_config.get("fingerprint_attr"):
            attrs["driver.mock.config"] = self.plugin_config["fingerprint_attr"]
        return {"detected": True, "healthy": True, "attributes": attrs}

    def start_task(self, task: Task, task_dir: str) -> TaskHandle:
        cfg = task.config or {}
        if cfg.get("start_error"):
            raise RuntimeError(str(cfg["start_error"]))
        if cfg.get("start_block_for"):
            time.sleep(parse_duration(cfg["start_block_for"]))

        handle = TaskHandle(
            task_name=task.name, driver=self.name, started_at=time.time_ns()
        )
        handle._cfg = dict(cfg)
        run_for = parse_duration(cfg.get("run_for", 0))
        exit_code = int(cfg.get("exit_code", 0))
        handle._run_for = run_for
        handle._exit_code = exit_code
        if run_for <= 0:
            handle.finish(exit_code)
        else:
            key = id(handle)

            def _finish():
                self._timers.pop(key, None)
                handle.finish(exit_code)

            # nta: ignore[thread-unnamed] WHY: Timer() takes no name
            # kwarg; named on the next line before start()
            t = threading.Timer(run_for, _finish)
            t.name = "driver-mock-finish-timer"
            t.daemon = True
            self._timers[key] = t
            t.start()
        return handle

    def stop_task(self, handle: TaskHandle, timeout: float = 5.0,
                  signal_name: str = ""):
        t = self._timers.pop(id(handle), None)
        if t is not None:
            t.cancel()
        if signal_name:
            handle.stop_signal = signal_name
        if not handle._done.is_set():
            handle.finish(130, "killed")

    def signal_task(self, handle: TaskHandle, signal_name: str):
        """Records delivered signals for assertions (ref drivers/mock
        scriptable signals); ``signal_error`` in the task config makes the
        delivery fail, ``exit_on_signal`` ends the task."""
        cfg = getattr(handle, "_cfg", {})
        if cfg.get("signal_error"):
            raise RuntimeError(str(cfg["signal_error"]))
        signals = getattr(handle, "signals", None)
        if signals is None:
            signals = handle.signals = []
        signals.append(signal_name)
        if cfg.get("exit_on_signal") and not handle._done.is_set():
            self.stop_task(handle)

    def exec_streaming(
        self,
        handle: TaskHandle,
        cmd: list,
        tty: bool = False,
        task_dir: str = "",
        env: Optional[dict] = None,
    ):
        """Test hook: mock tasks have no real process, so exec runs the
        command in the task dir (exercises the full streaming path)."""
        from .execstream import ExecProcess

        if handle._done.is_set():
            raise ValueError("task is not running")
        return ExecProcess(
            list(cmd),
            cwd=task_dir or None,
            env={"PATH": "/usr/bin:/bin:/usr/local/bin", **(env or {})},
            tty=tty,
        )

    def handle_data(self, handle: TaskHandle) -> dict:
        return {
            "driver": self.name,
            "task_name": handle.task_name,
            "started_at": handle.started_at,
            "run_for": getattr(handle, "_run_for", 0.0),
            "exit_code": getattr(handle, "_exit_code", 0),
        }

    def recover_task(self, task: Task, data: dict) -> Optional[TaskHandle]:
        """Scriptable recovery (the reference's mock driver RecoverTask):
        config fail_recover forces the unrecoverable path; otherwise the
        handle resumes with whatever run time remains."""
        cfg = task.config or {}
        if cfg.get("fail_recover"):
            return None
        handle = TaskHandle(
            task_name=task.name,
            driver=self.name,
            started_at=int(data.get("started_at", 0)),
            recovered=True,
        )
        exit_code = int(data.get("exit_code", 0))
        # carry the reattach info so handle_data round-trips through a
        # SECOND crash/recovery without zeroing run_for/exit_code
        handle._run_for = float(data.get("run_for", 0.0))
        handle._exit_code = exit_code
        remaining = (
            data.get("started_at", 0) / 1e9
            + float(data.get("run_for", 0.0))
            - time.time()
        )
        if remaining <= 0:
            handle.finish(exit_code)
            return handle
        key = id(handle)

        def _finish():
            self._timers.pop(key, None)
            handle.finish(exit_code)

        # nta: ignore[thread-unnamed] WHY: Timer() takes no name kwarg;
        # named on the next line before start()
        t = threading.Timer(remaining, _finish)
        t.name = "driver-mock-finish-timer"
        t.daemon = True
        self._timers[key] = t
        t.start()
        return handle


class RawExecDriver(Driver):
    """Run a real subprocess with no isolation (ref drivers/rawexec)."""

    name = "raw_exec"

    def _spawn(self, task: Task, argv: list, cwd, log_base=None) -> TaskHandle:
        """Shared Popen → TaskHandle → waiter tail for the exec family.
        stdout/stderr flow through in-process logmon copiers into rotated
        ``<log_base or cwd>/logs/<task>.<stream>.<n>`` files honoring the
        task's LogConfig (ref client/logmon/ + logging/logrotator)."""
        import os

        from .logmon import RotatingWriter, start_copier

        log_base = log_base or cwd
        log_dir = task_log_dir(log_base) if log_base else None
        stdout = stderr = subprocess.DEVNULL
        pipes = []  # (read_fd, writer)
        if log_dir is not None:
            cfg = task.log_config
            max_files = cfg.max_files if cfg is not None else 10
            max_mb = cfg.max_file_size_mb if cfg is not None else 10
            raw_fds: list[int] = []
            writers: list[RotatingWriter] = []
            try:
                out_r, stdout = os.pipe()
                raw_fds += [out_r, stdout]
                err_r, stderr = os.pipe()
                raw_fds += [err_r, stderr]
                writers.append(
                    RotatingWriter(log_dir, task.name, "stdout",
                                   max_files, max_mb)
                )
                writers.append(
                    RotatingWriter(log_dir, task.name, "stderr",
                                   max_files, max_mb)
                )
                pipes = [(out_r, writers[0]), (err_r, writers[1])]
            except Exception:
                # a half-built io setup must not leak fds per restart
                for fd in raw_fds:
                    try:
                        os.close(fd)
                    except OSError:
                        pass
                for writer in writers:
                    writer.close()
                raise
        try:
            proc = subprocess.Popen(
                argv,
                cwd=cwd,
                stdout=stdout,
                stderr=stderr,
                env={"PATH": "/usr/bin:/bin:/usr/local/bin", **task.env},
            )
        except Exception:
            for fd, writer in pipes:
                os.close(fd)
                writer.close()
            raise
        finally:
            # the child holds the write ends now (or Popen raised)
            for end in (stdout, stderr):
                if end is not subprocess.DEVNULL:
                    os.close(end)
        copiers = [start_copier(fd, writer) for fd, writer in pipes]
        handle = TaskHandle(
            task_name=task.name,
            driver=self.name,
            proc=proc,
            pid=proc.pid,
            started_at=time.time_ns(),
        )
        handle._proc_start = _proc_start_time(proc.pid)

        def waiter():
            code = proc.wait()
            # drain the pipes before completion is observable: a caller
            # reacting to the exit must find the final log bytes on disk
            # (copiers end at EOF, which the child's exit guarantees soon;
            # the timeout guards grandchildren holding the pipe open)
            for t in copiers:
                t.join(timeout=5.0)
            handle.finish(code)

        threading.Thread(
            target=waiter, daemon=True, name="driver-exec-waiter"
        ).start()
        return handle

    def start_task(self, task: Task, task_dir: str) -> TaskHandle:
        cfg = task.config or {}
        command = cfg.get("command")
        if not command:
            raise RuntimeError("raw_exec requires a command")
        args = [command] + list(cfg.get("args", []))
        return self._spawn(task, args, task_dir or None)

    def stop_task(self, handle: TaskHandle, timeout: float = 5.0,
                  signal_name: str = ""):
        import signal as signal_mod

        sig = signal_mod.SIGTERM
        if signal_name:
            name = str(signal_name).upper()
            if not name.startswith("SIG"):
                name = "SIG" + name
            resolved = getattr(signal_mod, name, None)
            if isinstance(resolved, signal_mod.Signals):
                sig = resolved
        proc = handle.proc
        if proc is not None:
            if proc.poll() is not None:
                return
            proc.send_signal(sig)
            try:
                proc.wait(timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
            return
        # recovered handle: not our child; signal by pid with the same
        # graceful → wait → kill escalation the child path gets
        if handle.pid and not handle._done.is_set():
            import os
            import signal

            try:
                os.kill(handle.pid, sig)
            except ProcessLookupError:
                return
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if not _pid_alive(handle.pid):
                    return
                time.sleep(0.05)
            try:
                os.kill(handle.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass

    def exec_streaming(
        self,
        handle: TaskHandle,
        cmd: list,
        tty: bool = False,
        task_dir: str = "",
        env: Optional[dict] = None,
    ):
        """raw_exec's context is the task dir + env (no isolation to
        enter, ref drivers/rawexec): the command runs beside the task."""
        from .execstream import ExecProcess

        if handle._done.is_set():
            raise ValueError("task is not running")
        return ExecProcess(
            list(cmd),
            cwd=task_dir or None,
            env={"PATH": "/usr/bin:/bin:/usr/local/bin", **(env or {})},
            tty=tty,
        )

    def signal_task(self, handle: TaskHandle, signal_name: str):
        """os-level signal delivery by pid (ref drivers/rawexec SignalTask)."""
        import os
        import signal as signal_mod

        if handle._done.is_set() or not handle.pid:
            raise ValueError("task is not running")
        name = str(signal_name).upper()
        if not name.startswith("SIG"):
            name = "SIG" + name
        sig = getattr(signal_mod, name, None)
        if not isinstance(sig, signal_mod.Signals):
            raise ValueError(f"unknown signal: {signal_name}")
        try:
            os.kill(handle.pid, sig)
        except ProcessLookupError:
            raise ValueError("task process has already exited")

    def handle_data(self, handle: TaskHandle) -> dict:
        return {
            "driver": self.name,
            "task_name": handle.task_name,
            "pid": handle.pid,
            "started_at": handle.started_at,
            "proc_start": getattr(handle, "_proc_start", 0),
        }

    def recover_task(self, task: Task, data: dict) -> Optional[TaskHandle]:
        """Reattach to a still-running process from a previous client
        process. The pid is no longer our child (reparented at client
        death), so liveness is polled and the exit code of a process that
        finishes after recovery is unknowable — it reports 0, the price of
        raw (executor-less) exec; the exec driver's shepherd process keeps
        real exit codes across client restarts. The persisted /proc start
        time guards against pid reuse: a recycled pid would make us adopt
        (and later kill) an unrelated process."""
        pid = int(data.get("pid", 0))
        if pid <= 0 or not _pid_alive(pid):
            return None
        persisted_start = int(data.get("proc_start", 0))
        if persisted_start and _proc_start_time(pid) != persisted_start:
            return None  # pid recycled by another process
        handle = TaskHandle(
            task_name=task.name,
            driver=self.name,
            pid=pid,
            started_at=int(data.get("started_at", 0)),
            recovered=True,
        )
        handle._proc_start = persisted_start

        def poller():
            while _pid_alive(pid):
                time.sleep(0.2)
            if not handle._done.is_set():
                handle.finish(0)

        threading.Thread(
            target=poller, daemon=True, name="driver-pid-poller"
        ).start()
        return handle


def _pid_alive(pid: int) -> bool:
    import os

    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _proc_start_time(pid: int) -> int:
    """Kernel start time of a pid (clock ticks since boot, field 22 of
    /proc/<pid>/stat) — the stable identity that survives everything but
    pid reuse. 0 when unreadable."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode("ascii", "replace")
        # comm may contain spaces/parens; field 22 counts after the last ')'
        rest = stat.rsplit(")", 1)[1].split()
        return int(rest[19])  # state is rest[0] → starttime is rest[19]
    except Exception:
        return 0


class ExecDriver(RawExecDriver):
    """Isolated exec via the nsexec shepherd (ref drivers/exec +
    drivers/shared/executor/executor_linux.go:29: libcontainer-backed
    isolation; here a small C++ namespace shepherd, SURVEY §2.9). Tasks run
    in fresh PID/mount/IPC/UTS namespaces with a namespace-local /proc; the
    persisted pid is the shepherd's, which forwards signals and propagates
    the task's exit status — so recovery-by-pid works exactly like
    raw_exec's but kills the whole namespace tree."""

    name = "exec"

    def __init__(self):
        super().__init__()
        self._nsexec = None
        self._healthy = False
        try:
            from ..native import isolation_available, nsexec_path

            if isolation_available():
                self._nsexec = nsexec_path()
                self._healthy = True
        except Exception:
            self._healthy = False
        self._sweep_stale_cgroups()

    def config_schema(self) -> dict:
        return {
            # node-wide default for tasks that don't set their own
            # seccomp stanza ("default" turns filtering on fleet-wide)
            "default_seccomp": {"type": "string", "default": "off"},
        }

    def set_config(self, config: dict):
        # a typo'd node-wide profile must fail HERE (one clear SetConfig
        # error), not at every subsequent task start
        profile = config.get("default_seccomp", "off")
        if profile not in ("default", "off"):
            raise ValueError(
                f"default_seccomp must be default|off, got {profile!r}"
            )
        super().set_config(config)

    def handle_data(self, handle: TaskHandle) -> dict:
        data = super().handle_data(handle)
        data["seccomp"] = getattr(handle, "_seccomp", "off")
        return data

    def recover_task(self, task: Task, data: dict) -> Optional[TaskHandle]:
        handle = super().recover_task(task, data)
        if handle is not None:
            # exec-into-task after a client restart still applies the filter
            handle._seccomp = data.get("seccomp", "off")
        return handle

    @staticmethod
    def _sweep_stale_cgroups():
        """A SIGKILL'd shepherd never runs its cgroup cleanup; empty
        nomad-* groups left behind are reclaimed here at driver startup so
        a churning node can't accumulate them forever."""
        import glob
        import os

        import time as _time

        now = _time.time()
        for root in (
            "/sys/fs/cgroup",
            "/sys/fs/cgroup/memory",
            "/sys/fs/cgroup/cpu",
        ):
            for d in glob.glob(os.path.join(root, "nomad-*")):
                try:
                    # age gate: a freshly created group may belong to a task
                    # whose child hasn't joined yet (another client's nsexec
                    # between setup and enter)
                    if now - os.stat(d).st_mtime < 300:
                        continue
                    os.rmdir(d)  # only succeeds when the group is empty
                except OSError:
                    pass

    def fingerprint(self) -> dict:
        return {
            "detected": self._nsexec is not None,
            "healthy": self._healthy,
            "attributes": {"driver.exec.isolation": "namespaces"},
        }

    def start_task(self, task: Task, task_dir: str) -> TaskHandle:
        if not self._healthy:
            raise RuntimeError("exec driver requires namespace isolation")
        cfg = task.config or {}
        command = cfg.get("command")
        if not command:
            raise RuntimeError("exec requires a command")
        args = [self._nsexec, "--workdir", task_dir or "/"]
        if cfg.get("chroot") and task_dir:
            # filesystem isolation (ref exec's default chroot env +
            # alloc-dir bind): the task dir becomes "/", system paths are
            # read-only binds, the shared alloc dir mounts at /alloc —
            # NOMAD_* dir vars are re-rooted to the in-chroot paths
            import os as os_mod

            alloc_shared = os_mod.path.join(
                os_mod.path.dirname(task_dir), "alloc"
            )
            os_mod.makedirs(alloc_shared, exist_ok=True)
            args += ["--chroot", task_dir, "--bind", f"{alloc_shared}:/alloc"]
            task = task.copy()
            task.env = {
                **task.env,
                "NOMAD_TASK_DIR": "/local",
                "NOMAD_ALLOC_DIR": "/alloc",
                "NOMAD_SECRETS_DIR": "/secrets",
            }
            cfg = task.config or {}
        # resource enforcement via the shepherd's cgroup (the executor's
        # resource-container role): best-effort, keyed uniquely per start
        if cfg.get("enforce_resources", True):
            import uuid as _uuid

            args += ["--cgroup", f"{task.name}-{_uuid.uuid4().hex[:8]}"]
            if task.resources.memory_mb:
                args += ["--memory-mb", str(task.resources.memory_mb)]
            if task.resources.cpu:
                args += ["--cpu-shares", str(task.resources.cpu)]
        # syscall filtering (SURVEY §2.9; ref libcontainer's seccomp
        # profile): task config seccomp = "default"|"off", defaulting to
        # the plugin config's default_seccomp (off unless configured)
        profile = cfg.get(
            "seccomp", self.plugin_config.get("default_seccomp", "off")
        )
        if profile not in ("default", "off"):
            raise RuntimeError(
                f"exec seccomp profile must be default|off, got {profile!r}"
            )
        if profile == "default":
            args += ["--seccomp", "default"]
        args += ["--", command] + list(cfg.get("args", []))
        handle = self._spawn(task, args, None, log_base=task_dir)
        # exec_streaming must re-apply the task's filter when it joins the
        # namespaces; recovery restores it from handle_data
        handle._seccomp = profile
        return handle

    def exec_streaming(
        self,
        handle: TaskHandle,
        cmd: list,
        tty: bool = False,
        task_dir: str = "",
        env: Optional[dict] = None,
    ):
        """Exec INSIDE the task's namespaces: nsexec --enter joins the
        namespace init's pid/mnt/ipc/uts via setns (the reference re-enters
        through its nsenter shim for ExecTaskStreaming). The namespace
        init is the shepherd's direct child (handle.pid is the shepherd,
        which lives OUTSIDE the pid namespace it created)."""
        from .execstream import ExecProcess

        if handle._done.is_set():
            raise ValueError("task is not running")
        child = _first_child(handle.pid)
        if child is None:
            raise ValueError("task namespace init not found")
        argv = [self._nsexec, "--enter", str(child)]
        if getattr(handle, "_seccomp", "off") == "default":
            # the exec'd process inherits the task's syscall filter — an
            # unfiltered shell inside a filtered sandbox defeats the point
            argv += ["--seccomp", "default"]
        argv += ["--"] + list(cmd)
        return ExecProcess(
            argv,
            env={"PATH": "/usr/bin:/bin:/usr/local/bin", **(env or {})},
            tty=tty,
        )


def _first_child(pid: int) -> Optional[int]:
    """First child of a pid (/proc children list); None when childless."""
    try:
        with open(
            f"/proc/{pid}/task/{pid}/children", "r", encoding="ascii"
        ) as f:
            kids = f.read().split()
        return int(kids[0]) if kids else None
    except (OSError, ValueError, IndexError):
        return None


BUILTIN_DRIVERS = {
    MockDriver.name: MockDriver,
    RawExecDriver.name: RawExecDriver,
    ExecDriver.name: ExecDriver,
}


def default_drivers() -> dict:
    """Instantiate every driver family a node agent carries by default:
    the builtin exec family plus the external-runtime tier (java, qemu,
    docker — ref helper/pluginutils/catalog/register.go's builtin driver
    registrations). Runtime-gated drivers report detected=False via
    fingerprint when their binary is absent."""
    out = {name: cls() for name, cls in BUILTIN_DRIVERS.items()}
    from ..drivers import EXTENDED_DRIVERS

    for name, cls in EXTENDED_DRIVERS.items():
        try:
            out[name] = cls()
        except Exception:  # a broken runtime probe must not kill the agent
            pass
    return out
