"""Placement stacks: the chained iterator pipelines for generic and system
scheduling (ref scheduler/stack.go, stack_oss.go).

GenericStack chain order (stack_oss.go:6-83): Random source → Quota(noop) →
FeasibilityWrapper[job: constraints; tg: drivers, constraints, host volumes,
devices] → DistinctHosts → DistinctProperty → FeasibleRank → BinPack →
JobAntiAffinity → ReschedulePenalty → NodeAffinity → Spread → ScoreNorm →
Limit(max(2,⌈log2 N⌉), skip≤3 at score ≤0; ∞ with affinities/spreads) →
MaxScore.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Optional

from ..structs.model import Job, Node, TaskGroup
from .context import EvalContext
from .feasible import (
    ConstraintChecker,
    DeviceChecker,
    DistinctHostsIterator,
    DistinctPropertyIterator,
    DriverChecker,
    FeasibilityWrapper,
    HostVolumeChecker,
    QuotaIterator,
    StaticIterator,
    shuffle_nodes,
)
from .rank import (
    BinPackIterator,
    FeasibleRankIterator,
    JobAntiAffinityIterator,
    NodeAffinityIterator,
    NodeReschedulingPenaltyIterator,
    RankedNode,
    ScoreNormalizationIterator,
)
from .select import LimitIterator, MaxScoreIterator
from .spread import SpreadIterator

# ref stack.go:10-18
SKIP_SCORE_THRESHOLD = 0.0
MAX_SKIP = 3


@dataclass
class SelectOptions:
    penalty_node_ids: set[str] = field(default_factory=set)
    preferred_nodes: list[Node] = field(default_factory=list)
    preempt: bool = False


def task_group_constraints(tg: TaskGroup):
    """Combined constraints + drivers for a task group
    (ref scheduler/util.go:609)."""
    constraints = list(tg.constraints)
    drivers: set[str] = set()
    for task in tg.tasks:
        drivers.add(task.driver)
        constraints.extend(task.constraints)
    return constraints, drivers


class GenericStack:
    """ref stack.go:42-162 + stack_oss.go"""

    def __init__(self, batch: bool, ctx: EvalContext):
        self.batch = batch
        self.ctx = ctx

        self.source = StaticIterator(ctx, [])
        self.quota = QuotaIterator(ctx, self.source)
        self.job_constraint = ConstraintChecker(ctx)
        self.task_group_drivers = DriverChecker(ctx)
        self.task_group_constraint = ConstraintChecker(ctx)
        self.task_group_devices = DeviceChecker(ctx)
        self.task_group_host_volumes = HostVolumeChecker(ctx)

        self.wrapped_checks = FeasibilityWrapper(
            ctx,
            self.quota,
            [self.job_constraint],
            [
                self.task_group_drivers,
                self.task_group_constraint,
                self.task_group_host_volumes,
                self.task_group_devices,
            ],
        )
        self.distinct_hosts_constraint = DistinctHostsIterator(ctx, self.wrapped_checks)
        self.distinct_property_constraint = DistinctPropertyIterator(
            ctx, self.distinct_hosts_constraint
        )
        rank_source = FeasibleRankIterator(ctx, self.distinct_property_constraint)
        self.bin_pack = BinPackIterator(ctx, rank_source, False, 0)
        self.job_anti_aff = JobAntiAffinityIterator(ctx, self.bin_pack, "")
        self.node_rescheduling_penalty = NodeReschedulingPenaltyIterator(
            ctx, self.job_anti_aff
        )
        self.node_affinity = NodeAffinityIterator(ctx, self.node_rescheduling_penalty)
        self.spread = SpreadIterator(ctx, self.node_affinity)
        self.score_norm = ScoreNormalizationIterator(ctx, self.spread)
        self.limit = LimitIterator(
            ctx, self.score_norm, 2, SKIP_SCORE_THRESHOLD, MAX_SKIP
        )
        self.max_score = MaxScoreIterator(ctx, self.limit)

    def set_nodes(self, base_nodes: list[Node]):
        """Shuffle + set the log₂-bounded candidate limit (ref stack.go:67-87)."""
        shuffle_nodes(self.ctx, base_nodes)
        self.source.set_nodes(base_nodes)

        limit = 2
        n = len(base_nodes)
        if not self.batch and n > 0:
            log_limit = int(math.ceil(math.log2(n)))
            if log_limit > limit:
                limit = log_limit
        self.limit.set_limit(limit)

    def set_job(self, job: Job):
        self.job_constraint.set_constraints(job.constraints)
        self.distinct_hosts_constraint.set_job(job)
        self.distinct_property_constraint.set_job(job)
        self.bin_pack.set_job(job)
        self.job_anti_aff.set_job(job)
        self.node_affinity.set_job(job)
        self.spread.set_job(job)
        self.ctx.get_eligibility().set_job(job)

    def select(
        self, tg: TaskGroup, options: Optional[SelectOptions] = None
    ) -> Optional[RankedNode]:
        """ref stack.go:104-162"""
        # Preferred-node (sticky-disk) handling
        if options is not None and options.preferred_nodes:
            original_nodes = self.source.nodes
            self.source.set_nodes(list(options.preferred_nodes))
            options_new = SelectOptions(
                penalty_node_ids=options.penalty_node_ids,
                preferred_nodes=[],
                preempt=options.preempt,
            )
            option = self.select(tg, options_new)
            self.source.set_nodes(original_nodes)
            if option is not None:
                return option
            return self.select(tg, options_new)

        self.max_score.reset()
        self.ctx.reset()
        start = time.monotonic()

        constraints, drivers = task_group_constraints(tg)
        self.task_group_drivers.set_drivers(drivers)
        self.task_group_constraint.set_constraints(constraints)
        self.task_group_devices.set_task_group(tg)
        self.task_group_host_volumes.set_volumes(tg.volumes)
        self.distinct_hosts_constraint.set_task_group(tg)
        self.distinct_property_constraint.set_task_group(tg)
        self.wrapped_checks.set_task_group(tg.name)
        self.bin_pack.set_task_group(tg)
        if options is not None:
            self.bin_pack.evict = options.preempt
        self.job_anti_aff.set_task_group(tg)
        if options is not None:
            self.node_rescheduling_penalty.set_penalty_nodes(options.penalty_node_ids)
        self.node_affinity.set_task_group(tg)
        self.spread.set_task_group(tg)

        if self.node_affinity.has_affinities() or self.spread.has_spreads():
            self.limit.set_limit(2**31 - 1)

        option = self.max_score.next()
        self.ctx.metrics.allocation_time = time.monotonic() - start
        return option


class SystemStack:
    """Stack for the system scheduler: every node considered, preemption
    enabled by scheduler config (ref stack.go:166-284)."""

    def __init__(self, ctx: EvalContext):
        self.ctx = ctx
        self.source = StaticIterator(ctx, [])
        self.quota = QuotaIterator(ctx, self.source)
        self.job_constraint = ConstraintChecker(ctx)
        self.task_group_drivers = DriverChecker(ctx)
        self.task_group_constraint = ConstraintChecker(ctx)
        self.task_group_host_volumes = HostVolumeChecker(ctx)
        self.task_group_devices = DeviceChecker(ctx)

        self.wrapped_checks = FeasibilityWrapper(
            ctx,
            self.quota,
            [self.job_constraint],
            [
                self.task_group_drivers,
                self.task_group_constraint,
                self.task_group_host_volumes,
                self.task_group_devices,
            ],
        )
        self.distinct_property_constraint = DistinctPropertyIterator(
            ctx, self.wrapped_checks
        )
        rank_source = FeasibleRankIterator(ctx, self.distinct_property_constraint)

        enable_preemption = True
        config = ctx.state.scheduler_config()
        if config is not None:
            enable_preemption = config.get("preemption_config", {}).get(
                "system_scheduler_enabled", True
            )
        self.bin_pack = BinPackIterator(ctx, rank_source, enable_preemption, 0)
        self.score_norm = ScoreNormalizationIterator(ctx, self.bin_pack)

    def set_nodes(self, base_nodes: list[Node]):
        self.source.set_nodes(base_nodes)

    def set_job(self, job: Job):
        self.job_constraint.set_constraints(job.constraints)
        self.distinct_property_constraint.set_job(job)
        self.bin_pack.set_job(job)
        self.ctx.get_eligibility().set_job(job)

    def select(
        self, tg: TaskGroup, options: Optional[SelectOptions] = None
    ) -> Optional[RankedNode]:
        self.score_norm.reset()
        self.ctx.reset()
        start = time.monotonic()

        constraints, drivers = task_group_constraints(tg)
        self.task_group_drivers.set_drivers(drivers)
        self.task_group_constraint.set_constraints(constraints)
        self.task_group_devices.set_task_group(tg)
        self.task_group_host_volumes.set_volumes(tg.volumes)
        self.wrapped_checks.set_task_group(tg.name)
        self.distinct_property_constraint.set_task_group(tg)
        self.bin_pack.set_task_group(tg)

        option = self.score_norm.next()
        self.ctx.metrics.allocation_time = time.monotonic() - start
        return option
