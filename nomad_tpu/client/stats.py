"""Host and per-task resource statistics (ref client/stats/host.go and
drivers/shared/executor's pid stats collector).

The host collector samples /proc/stat, /proc/meminfo, /proc/uptime and
statvfs; CPU percentages come from deltas between consecutive samples, the
same ticker model the reference's HostStatsCollector uses. Task stats read
/proc/<pid>/stat for utime/stime/rss (cumulative CPU and current memory of
a live task process tree's root)."""

from __future__ import annotations

import os
import time
from typing import Optional

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _read_proc_stat() -> Optional[dict]:
    """Aggregate cpu line of /proc/stat: {user, system, idle, total} in
    ticks."""
    try:
        with open("/proc/stat") as f:
            for line in f:
                if line.startswith("cpu "):
                    parts = [int(x) for x in line.split()[1:]]
                    user, nice, system, idle = parts[0], parts[1], parts[2], parts[3]
                    iowait = parts[4] if len(parts) > 4 else 0
                    total = sum(parts)
                    return {
                        "user": user + nice,
                        "system": system,
                        "idle": idle + iowait,
                        "total": total,
                    }
    except OSError:
        pass
    return None


def _read_meminfo() -> dict:
    """{total, available, free, used} in bytes (ref stats/host.go Memory)."""
    fields = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                key, _, rest = line.partition(":")
                try:
                    fields[key] = int(rest.split()[0]) * 1024
                except (ValueError, IndexError):
                    continue
    except OSError:
        return {"total": 0, "available": 0, "free": 0, "used": 0}
    total = fields.get("MemTotal", 0)
    free = fields.get("MemFree", 0)
    available = fields.get("MemAvailable", free)
    return {
        "total": total,
        "available": available,
        "free": free,
        "used": total - available,
    }


def _read_uptime() -> float:
    try:
        with open("/proc/uptime") as f:
            return float(f.read().split()[0])
    except (OSError, ValueError, IndexError):
        return 0.0


def disk_stats(path: str) -> dict:
    """{size, used, available, used_percent} for the filesystem holding
    ``path`` (ref stats/host.go DiskStats)."""
    try:
        st = os.statvfs(path)
    except OSError:
        return {"size": 0, "used": 0, "available": 0, "used_percent": 0.0}
    size = st.f_blocks * st.f_frsize
    available = st.f_bavail * st.f_frsize
    used = size - st.f_bfree * st.f_frsize
    return {
        "size": size,
        "used": used,
        "available": available,
        "used_percent": round(100.0 * used / size, 2) if size else 0.0,
    }


class HostStatsCollector:
    """Sampled host stats; CPU percent from /proc/stat deltas between
    calls (ref client/stats/cpu.go HostCpuStatsCalculator)."""

    _ZERO_CPU = {
        "total_percent": 0.0,
        "user_percent": 0.0,
        "system_percent": 0.0,
        "idle_percent": 0.0,
    }

    def __init__(self, data_dir: str = "/"):
        self.data_dir = data_dir
        self._prev = _read_proc_stat()
        self._prev_t = time.monotonic()
        # last computed percentages: re-served on a zero-tick delta (two
        # back-to-back collects inside one /proc/stat tick), where 0% CPU
        # would be a lie rather than a measurement
        self._last_cpu: Optional[dict] = None
        # one settle-and-resample per collector, not per call: a kernel
        # with no CPU accounting at all (sandboxed /proc/stat stuck at 0)
        # must not cost every collect() a sleep
        self._retry_spent = False

    def _cpu_percentages(self, retry: bool = True) -> dict:
        cur = _read_proc_stat()
        if cur is None or self._prev is None:
            if cur is not None:
                self._prev = cur
            return self._last_cpu or dict(self._ZERO_CPU)
        # iowait (folded into idle) is documented non-monotonic in
        # proc(5): clamp each delta so a decreasing counter can't push
        # a percentage below 0 / above 100
        cur = {k: max(v, self._prev[k]) for k, v in cur.items()}
        d_total = cur["total"] - self._prev["total"]
        if d_total <= 0:
            if self._last_cpu is not None:
                return self._last_cpu
            if retry and not self._retry_spent:
                # first-ever sample landed inside one tick: wait ~5 jiffies
                # and resample once instead of reporting 0%
                self._retry_spent = True
                time.sleep(0.05)
                return self._cpu_percentages(retry=False)
            return dict(self._ZERO_CPU)
        cpu = {
            "total_percent": round(
                100.0 * (d_total - (cur["idle"] - self._prev["idle"])) / d_total,
                2,
            ),
            "user_percent": round(
                100.0 * (cur["user"] - self._prev["user"]) / d_total, 2
            ),
            "system_percent": round(
                100.0 * (cur["system"] - self._prev["system"]) / d_total, 2
            ),
            "idle_percent": round(
                100.0 * (cur["idle"] - self._prev["idle"]) / d_total, 2
            ),
        }
        self._prev = cur
        self._prev_t = time.monotonic()
        self._last_cpu = cpu
        return cpu

    def collect(self) -> dict:
        return {
            "timestamp": time.time_ns(),
            "cpu": self._cpu_percentages(),
            "memory": _read_meminfo(),
            "disk": disk_stats(self.data_dir),
            "uptime_s": _read_uptime(),
        }


def pid_stats(pid: int) -> Optional[dict]:
    """Cumulative cpu time and current rss of ``pid`` from /proc/<pid>/stat
    (ref executor's pidCollector / ps lib)."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            raw = f.read().decode("ascii", "replace")
    except OSError:
        return None
    # comm may contain spaces/parens: fields start after the closing paren
    rest = raw.rpartition(")")[2].split()
    if len(rest) < 22:
        return None
    utime, stime = int(rest[11]), int(rest[12])
    rss_pages = int(rest[21])
    return {
        "cpu_time_s": round((utime + stime) / _CLK_TCK, 3),
        "rss_bytes": rss_pages * _PAGE_SIZE,
    }


def task_resource_usage(handle) -> dict:
    """ResourceUsage doc for one task handle (ref
    drivers/shared/executor TaskStats → TaskResourceUsage). CPU percent
    comes from the delta against the previous sample cached on the handle
    — the reference's stats collector uses the same consecutive-sample
    ticker model."""
    usage = {
        "cpu_time_s": 0.0,
        "cpu_percent": 0.0,
        "rss_bytes": 0,
        "pids": 0,
        "timestamp": time.time_ns(),
    }
    pid = getattr(handle, "pid", 0)
    if not pid or handle._done.is_set():
        return usage
    # walk the task's process tree: the driver's child plus descendants
    pids = _descendants(pid)
    for p in pids:
        st = pid_stats(p)
        if st is not None:
            usage["cpu_time_s"] = round(usage["cpu_time_s"] + st["cpu_time_s"], 3)
            usage["rss_bytes"] += st["rss_bytes"]
            usage["pids"] += 1
    prev = getattr(handle, "_stats_prev", None)
    if prev is not None:
        dt = (usage["timestamp"] - prev[1]) / 1e9
        if dt < 1.0:
            # two samplers (host rollup + alloc endpoint) share this slot:
            # a sub-second delta is numerically worthless, so reuse the
            # last percent and KEEP the baseline — otherwise concurrent
            # pollers corrupt each other's deltas
            usage["cpu_percent"] = prev[2]
            return usage
        usage["cpu_percent"] = round(
            max(usage["cpu_time_s"] - prev[0], 0.0) / dt * 100.0, 2
        )
    handle._stats_prev = (
        usage["cpu_time_s"], usage["timestamp"], usage["cpu_percent"]
    )
    return usage


def _descendants(root: int) -> list[int]:
    """root + all transitive children, via /proc/<pid>/task/<tid>/children."""
    out, frontier = [], [root]
    seen = set()
    while frontier:
        pid = frontier.pop()
        if pid in seen:
            continue
        seen.add(pid)
        out.append(pid)
        try:
            for tid in os.listdir(f"/proc/{pid}/task"):
                try:
                    with open(f"/proc/{pid}/task/{tid}/children") as f:
                        frontier.extend(int(c) for c in f.read().split())
                except (OSError, ValueError):
                    continue
        except OSError:
            continue
    return out
