"""Command-line interface (ref command/)."""

from .main import main
