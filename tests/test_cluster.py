"""In-process multi-server cluster tests (ref nomad/testing.go:41
TestServer + :120 TestJoin — the reference forms whole multi-server raft
clusters inside one test process; this is the same tier here)."""

import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.core.server import Server
from nomad_tpu.raft import InmemTransport, NotLeaderError, RaftConfig


def make_cluster(n=3, num_workers=1, config=None):
    transport = InmemTransport()
    voters = {f"s{i}": f"raft{i}" for i in range(n)}
    servers = []
    for i in range(n):
        cfg = dict(config or {})
        cfg.setdefault("seed", 42)
        cfg.setdefault("heartbeat_ttl", 60.0)
        cfg["raft"] = {
            "node_id": f"s{i}",
            "address": f"raft{i}",
            "voters": voters,
            "transport": transport,
            "config": RaftConfig(
                heartbeat_interval=0.02,
                election_timeout_min=0.05,
                election_timeout_max=0.10,
            ),
        }
        s = Server(cfg)
        servers.append(s)
    for s in servers:
        s.start(num_workers=num_workers, wait_for_leader=0.0)
    return servers, transport


def wait_leader(servers, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [s for s in servers if s.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.01)
    raise AssertionError("no single leader")


def stop_all(servers):
    for s in servers:
        s.stop()


class TestCluster:
    def test_replicated_scheduling(self):
        """Job registered on the leader: allocs placed and replicated to
        every server's state store."""
        servers, _ = make_cluster(3)
        try:
            leader = wait_leader(servers)
            for _ in range(3):
                leader.node_register(mock.node())
            job = mock.job()
            job.task_groups[0].count = 3
            eval_id = leader.job_register(job)

            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                ev = leader.state.eval_by_id(eval_id)
                if ev is not None and ev.status == "complete":
                    break
                time.sleep(0.05)
            assert leader.state.eval_by_id(eval_id).status == "complete"

            # replication: every follower converges to the same allocs
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                counts = [
                    len(s.state.allocs_by_job(job.namespace, job.id))
                    for s in servers
                ]
                if all(c == 3 for c in counts):
                    break
                time.sleep(0.05)
            for s in servers:
                assert len(s.state.allocs_by_job(job.namespace, job.id)) == 3
        finally:
            stop_all(servers)

    def test_follower_write_rejected_with_leader_hint(self):
        servers, _ = make_cluster(3)
        try:
            leader = wait_leader(servers)
            follower = next(s for s in servers if s is not leader)
            with pytest.raises(NotLeaderError) as exc:
                follower.job_register(mock.job())
            assert exc.value.leader_id == leader.raft.node_id
        finally:
            stop_all(servers)

    def test_leader_failover_scheduling_resumes(self):
        """Kill the leader; a new leader takes over broker + planner and
        completes work (ref leader.go establish/revokeLeadership)."""
        servers, transport = make_cluster(3)
        try:
            leader = wait_leader(servers)
            for _ in range(2):
                leader.node_register(mock.node())
            job = mock.job()
            job.task_groups[0].count = 2
            leader.job_register(job)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if len(leader.state.allocs_by_job(job.namespace, job.id)) == 2:
                    break
                time.sleep(0.05)

            # partition the leader away
            transport.disconnect(leader.raft.address)
            rest = [s for s in servers if s is not leader]
            new_leader = wait_leader(rest)
            assert new_leader is not leader

            # new leader restored broker from replicated state; a fresh job
            # schedules fine
            job2 = mock.job()
            job2.task_groups[0].count = 2
            eval2 = new_leader.job_register(job2)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                ev = new_leader.state.eval_by_id(eval2)
                if ev is not None and ev.status == "complete":
                    break
                time.sleep(0.05)
            assert new_leader.state.eval_by_id(eval2).status == "complete"
            assert (
                len(new_leader.state.allocs_by_job(job2.namespace, job2.id)) == 2
            )
        finally:
            stop_all(servers)
