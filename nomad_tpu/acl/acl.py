"""Compiled ACL evaluation (ref acl/acl.go: capability lookup with
longest-prefix glob matching over namespace rules; management bypasses
everything, anonymous is the empty ACL)."""

from __future__ import annotations

import fnmatch
from typing import Iterable, Optional

from .policy import (
    POLICY_DENY,
    POLICY_READ,
    POLICY_WRITE,
    ParsedPolicy,
)


class ACL:
    """The result of compiling a token's policies."""

    def __init__(self, management: bool = False):
        self.management = management
        # exact and glob namespace rules: name -> (capabilities, deny)
        self._ns_exact: dict[str, tuple[set[str], bool]] = {}
        self._ns_glob: list[tuple[str, set[str], bool]] = []
        self.node = ""
        self.agent = ""
        self.operator = ""

    # ------------------------------------------------------------------
    def _namespace_rule(self, ns: str) -> Optional[tuple[set[str], bool]]:
        rule = self._ns_exact.get(ns)
        if rule is not None:
            return rule
        # longest glob match wins (acl.go: maxPrefix radix lookup)
        best = None
        best_len = -1
        for pattern, caps, deny in self._ns_glob:
            if fnmatch.fnmatchcase(ns, pattern) and len(pattern) > best_len:
                best = (caps, deny)
                best_len = len(pattern)
        return best

    def allow_namespace_operation(self, ns: str, capability: str) -> bool:
        if self.management:
            return True
        rule = self._namespace_rule(ns)
        if rule is None:
            return False
        caps, deny = rule
        if deny:
            return False
        return capability in caps

    def allow_namespace(self, ns: str) -> bool:
        """Any capability at all in the namespace (acl.go AllowNamespace)."""
        if self.management:
            return True
        rule = self._namespace_rule(ns)
        return rule is not None and not rule[1] and bool(rule[0])

    def allow_capability_any_namespace(self, capability: str) -> bool:
        """Whether ANY namespace rule grants the capability — the gate for
        wildcard (?namespace=*) list requests, whose results are then
        filtered per object (ref acl.go AllowNsOpFunc wildcard handling)."""
        if self.management:
            return True
        for caps, deny in self._ns_exact.values():
            if not deny and capability in caps:
                return True
        for _, caps, deny in self._ns_glob:
            if not deny and capability in caps:
                return True
        return False

    # -- coarse domains -------------------------------------------------
    def _coarse_allows(self, granted: str, needed: str) -> bool:
        if self.management:
            return True
        if granted == POLICY_DENY or not granted:
            return False
        if needed == POLICY_READ:
            return granted in (POLICY_READ, POLICY_WRITE)
        return granted == POLICY_WRITE

    def allow_node_read(self) -> bool:
        return self._coarse_allows(self.node, POLICY_READ)

    def allow_node_write(self) -> bool:
        return self._coarse_allows(self.node, POLICY_WRITE)

    def allow_agent_read(self) -> bool:
        return self._coarse_allows(self.agent, POLICY_READ)

    def allow_agent_write(self) -> bool:
        return self._coarse_allows(self.agent, POLICY_WRITE)

    def allow_operator_read(self) -> bool:
        return self._coarse_allows(self.operator, POLICY_READ)

    def allow_operator_write(self) -> bool:
        return self._coarse_allows(self.operator, POLICY_WRITE)


#: the ACL for management tokens — allows everything (acl.go ManagementACL)
ACL_MANAGEMENT = ACL(management=True)

#: the ACL for requests without a token — allows nothing
ACL_ANONYMOUS = ACL()


def compile_acl(policies: Iterable[ParsedPolicy]) -> ACL:
    """Merge parsed policies into one ACL (ref acl.go NewACL: union of
    capabilities per namespace; deny dominates; coarse domains take the
    most permissive grant unless denied)."""
    acl = ACL()
    coarse_rank = {"": 0, POLICY_READ: 1, POLICY_WRITE: 2, POLICY_DENY: 3}
    for policy in policies:
        for ns in policy.namespaces:
            target_exact = "*" not in ns.name and "?" not in ns.name
            if target_exact:
                caps, deny = acl._ns_exact.get(ns.name, (set(), False))
                acl._ns_exact[ns.name] = (caps | ns.capabilities, deny or ns.deny)
            else:
                merged = False
                for i, (pattern, caps, deny) in enumerate(acl._ns_glob):
                    if pattern == ns.name:
                        acl._ns_glob[i] = (
                            pattern, caps | ns.capabilities, deny or ns.deny
                        )
                        merged = True
                        break
                if not merged:
                    acl._ns_glob.append(
                        (ns.name, set(ns.capabilities), ns.deny)
                    )
        for domain in ("node", "agent", "operator"):
            granted = getattr(policy, domain)
            if not granted:
                continue
            current = getattr(acl, domain)
            if granted == POLICY_DENY or coarse_rank[granted] > coarse_rank[current]:
                # deny dominates; otherwise most permissive wins
                if current == POLICY_DENY:
                    continue
                setattr(acl, domain, granted)
    return acl
