"""Static analysis for the nomad_tpu control plane and device plane.

``python -m nomad_tpu.analysis`` runs every registered checker over the
package and exits nonzero on findings not in the committed
``ANALYSIS_BASELINE.json``. See ANALYSIS.md for the checker catalog,
suppression syntax, and the baseline workflow.
"""

from __future__ import annotations

import os

from .framework import (  # noqa: F401
    BASELINE_NAME,
    CHECKER_DOCS,
    CHECKERS,
    Finding,
    ModuleInfo,
    Project,
    load_baseline,
    partition,
    run,
    write_baseline,
)

# importing the checker modules registers them
from . import (  # noqa: F401,E402
    growth,
    imports,
    jax_hygiene,
    lockgraph,
    plane_mutation,
    racegraph,
    raft_hygiene,
    retry_budget,
    shard_hygiene,
    span_hygiene,
    threads,
)


def repo_root() -> str:
    """The directory holding the nomad_tpu package (and the baseline)."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def analyze(
    root: str = None, checkers=None
) -> tuple[list[Finding], list[Finding]]:
    """(new, baselined) findings for the tree at ``root``."""
    root = root or repo_root()
    project = Project.load(root)
    findings = run(project, checkers)
    baseline = load_baseline(os.path.join(root, BASELINE_NAME))
    return partition(findings, baseline)


def count_new_findings(root: str = None) -> int:
    """New (non-baseline) finding count — bench.py surfaces this in
    BENCH_SUMMARY so analyzer drift shows up in the perf trajectory."""
    try:
        new, _ = analyze(root)
        return len(new)
    except Exception:
        return -1  # analyzer itself broke: surface as a sentinel


#: the race plane's rules (analysis/racegraph.py) — the slice of the
#: catalog whose finding count BENCH_SUMMARY tracks separately
RACE_RULES = (
    "unsynchronized-shared-write",
    "inconsistent-lockset",
    "unguarded-flag-check",
)


def count_race_findings(root: str = None) -> int:
    """Total race-plane findings, new AND baselined — bench.py surfaces
    this as ``race_findings=`` so the burn-down trajectory (fix or WHY
    each one away) is visible next to the perf numbers. Unlike
    :func:`count_new_findings` this counts the baseline too: a baselined
    race is debt being tracked, not debt paid."""
    try:
        new, known = analyze(root, list(RACE_RULES))
        return len(new) + len(known)
    except Exception:
        return -1  # analyzer itself broke: surface as a sentinel
