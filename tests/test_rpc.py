"""Network RPC tests: msgpack-RPC over TCP with protocol muxing, leader
forwarding, and a remote node agent executing a job (ref nomad/rpc.go,
helper/pool, client/rpc.go)."""

import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.agent import ClientAgent, ServerAgent
from nomad_tpu.raft import RaftConfig
from nomad_tpu.rpc import ConnPool, RpcError, ServerProxy


FAST_RAFT = dict(
    heartbeat_interval=0.05,
    # election windows must tolerate GIL pauses on a loaded interpreter
    # (a single slow gc/compile stall past the window flaps leadership
    # mid-test, which can fail an in-flight eval)
    election_timeout_min=0.3,
    election_timeout_max=0.6,
)


def make_tcp_cluster(n=3, config=None):
    agents = [
        ServerAgent(f"s{i}", config=dict(config or {"seed": 42, "heartbeat_ttl": 60.0}))
        for i in range(n)
    ]
    voters = {a.name: a.address for a in agents}
    for a in agents:
        a.config.setdefault("seed", 42)
        a.start(voters=voters, num_workers=1, wait_for_leader=0.0)
        a.server.raft.config.heartbeat_interval = FAST_RAFT["heartbeat_interval"]
        a.server.raft.config.election_timeout_min = FAST_RAFT["election_timeout_min"]
        a.server.raft.config.election_timeout_max = FAST_RAFT["election_timeout_max"]
    return agents


def wait_leader(agents, timeout=8.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [a for a in agents if a.server.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.02)
    raise AssertionError("no single leader over TCP")


class TestRemoteClientFS:
    def test_remote_alloc_logs_forwarded_over_rpc(self, tmp_path):
        """A job on a REMOTE (TCP) client: the server's HTTP agent serves
        its logs/fs/exec by forwarding over the client's RPC listener (the
        client_fs_endpoint.go server→client path)."""
        from nomad_tpu.api.client import ApiClient
        from nomad_tpu.api.http import HTTPServer

        server = ServerAgent("fs-s1", config={"seed": 42, "heartbeat_ttl": 60.0})
        server.start(num_workers=1, wait_for_leader=5.0)
        client = ClientAgent([server.address], data_dir=str(tmp_path))
        http = HTTPServer(server.server, port=0)  # NO agent ref: not local
        http.start()
        api = ApiClient(address=f"http://127.0.0.1:{http.port}")
        try:
            client.start()
            job = mock.batch_job()
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {
                "command": "/bin/sh",
                "args": ["-c", "echo remote-hello; echo marker > f.txt"],
            }
            task.resources.networks = []
            pool = ConnPool()
            pool.call(server.address, "Job.Register", {"job": job.to_dict()})

            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                allocs = server.server.state.allocs_by_job(job.namespace, job.id)
                if allocs and allocs[0].client_status == "complete":
                    break
                time.sleep(0.05)
            (alloc,) = server.server.state.allocs_by_job(job.namespace, job.id)
            assert alloc.client_status == "complete"

            logs = api.get(
                f"/v1/client/fs/logs/{alloc.id}", task="web", type="stdout"
            )[0]
            assert "remote-hello" in logs["Data"]
            entries = api.get(f"/v1/client/fs/ls/{alloc.id}", path="web")[0]
            assert any(e["Name"] == "f.txt" for e in entries)
            cat = api.get(f"/v1/client/fs/cat/{alloc.id}", path="web/f.txt")[0]
            assert cat["Data"].strip() == "marker"
            resp = api.put(
                f"/v1/client/exec/{alloc.id}",
                body={"Task": "web", "Cmd": ["/bin/cat", "f.txt"]},
            )[0]
            assert resp["ExitCode"] == 0 and resp["Stdout"].strip() == "marker"
        finally:
            http.stop()
            client.stop()
            server.stop()


class TestRpcCluster:
    def test_tcp_cluster_schedules_and_forwards(self):
        agents = make_tcp_cluster(3)
        pool = ConnPool()
        try:
            leader = wait_leader(agents)
            follower = next(a for a in agents if a is not leader)

            # registering via a FOLLOWER works: not_leader error carries the
            # leader's rpc addr, pool retries there (leader forwarding)
            for _ in range(2):
                pool.call(
                    follower.address, "Node.Register",
                    {"node": mock.node().to_dict()},
                )
            job = mock.job()
            job.task_groups[0].count = 2
            eval_id = pool.call(
                follower.address, "Job.Register", {"job": job.to_dict()}
            )
            assert eval_id

            # generous deadlines: under full-suite load (TCP + raft
            # elections + concurrent JAX compiles) 10s flakes
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                ev = leader.server.state.eval_by_id(eval_id)
                if ev is not None and ev.status == "complete":
                    break
                time.sleep(0.05)
            assert leader.server.state.eval_by_id(eval_id).status == "complete"

            # replicated everywhere
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if all(
                    len(a.server.state.allocs_by_job(job.namespace, job.id)) == 2
                    for a in agents
                ):
                    break
                time.sleep(0.05)
            for a in agents:
                assert len(a.server.state.allocs_by_job(job.namespace, job.id)) == 2

            # status endpoints
            st = pool.call(follower.address, "Status.Leader", {})
            assert st["leader_id"] == leader.name
            peers = pool.call(follower.address, "Status.Peers", {})
            assert len(peers["peers"]) == 3
        finally:
            pool.close()
            for a in agents:
                a.stop()

    def test_http_write_on_follower_forwards_without_gossip(self):
        """A follower-addressed HTTP write succeeds in a VOTERS-ONLY
        topology (no gossip, no static server_http_addrs): the follower
        resolves the leader's HTTP address over the server RPC tier
        (Status.HTTPAddr at the leader's raft address — ref
        nomad/rpc.go:280-340 forward(), which likewise needs only the
        existing server RPC connections)."""
        from nomad_tpu.api.client import ApiClient
        from nomad_tpu.api.http import HTTPServer

        agents = make_tcp_cluster(3)
        https = []
        try:
            for a in agents:
                h = HTTPServer(a.server, port=0)
                h.start()
                https.append(h)
            leader = wait_leader(agents)
            assert all(a.server.gossip is None for a in agents)
            assert all(
                not a.server.config.get("server_http_addrs") for a in agents
            )

            follower_idx = next(
                i for i, a in enumerate(agents) if a is not leader
            )
            api = ApiClient(address=https[follower_idx].address)
            job = mock.job()
            job.task_groups[0].count = 1
            resp = api.register_job(job.to_dict())
            assert resp.get("EvalID")

            # the write really landed: visible through the leader's state
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if leader.server.state.job_by_id(job.namespace, job.id):
                    break
                time.sleep(0.05)
            assert leader.server.state.job_by_id(job.namespace, job.id)

            # the learned address is cached for subsequent forwards
            assert (
                agents[follower_idx].server._peer_http_addrs
            ), "Status.HTTPAddr result should be cached"

            # Status.HTTPAddr itself answers with the advertised address
            pool = ConnPool()
            try:
                got = pool.call(leader.address, "Status.HTTPAddr", {})
                assert got["http_addr"] == next(
                    h.address for h, a in zip(https, agents) if a is leader
                )
            finally:
                pool.close()
        finally:
            for h in https:
                h.stop()
            for a in agents:
                a.stop()

    def test_unknown_method_and_validation_errors(self):
        agents = make_tcp_cluster(1)
        pool = ConnPool()
        try:
            wait_leader(agents)
            with pytest.raises(RpcError) as exc:
                pool.call(agents[0].address, "No.Such", {})
            assert exc.value.code == "not_found"
            with pytest.raises(RpcError) as exc:
                pool.call(agents[0].address, "Job.Register", {"job": {}})
            assert exc.value.code == "invalid"
        finally:
            pool.close()
            agents[0].stop()


class TestRemoteClient:
    def test_client_agent_runs_job_over_rpc(self):
        """Full network slice: server agent + remote node agent with the
        mock driver; job placed, executed, status flows back via RPC."""
        server = ServerAgent("s0", config={"seed": 7, "heartbeat_ttl": 5.0})
        server.start(num_workers=2)
        client = ClientAgent([server.address])
        try:
            client.start()
            # wait node registration propagates
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if server.server.state.node_by_id(client.node.id) is not None:
                    break
                time.sleep(0.05)
            assert server.server.state.node_by_id(client.node.id) is not None

            job = mock.job()
            job.task_groups[0].count = 1
            job.task_groups[0].tasks[0].driver = "mock_driver"
            job.task_groups[0].tasks[0].config["run_for"] = "0.2s"
            server.server.job_register(job)

            deadline = time.monotonic() + 15
            ok = False
            while time.monotonic() < deadline:
                allocs = server.server.state.allocs_by_job(job.namespace, job.id)
                if allocs and allocs[0].client_status in ("running", "complete"):
                    ok = True
                    break
                time.sleep(0.1)
            assert ok, "alloc never ran via the remote client"
        finally:
            client.stop()
            server.stop()
