"""Jobspec: HCL → Job model (ref jobspec/parse.go:27 and the per-stanza
parse_*.go files)."""

from __future__ import annotations

from typing import Any, Optional

from ..structs.model import (
    Affinity,
    Constraint,
    DispatchPayloadConfig,
    EphemeralDisk,
    Job,
    LogConfig,
    MigrateStrategy,
    NetworkResource,
    ParameterizedJobConfig,
    PeriodicConfig,
    Port,
    ReschedulePolicy,
    Resources,
    RestartPolicy,
    RequestedDevice,
    CheckRestart,
    ConsulConnect,
    ConsulProxy,
    ConsulSidecarService,
    ConsulUpstream,
    Service,
    ServiceCheck,
    Spread,
    SpreadTarget,
    Task,
    TaskArtifact,
    TaskGroup,
    Template,
    UpdateStrategy,
    Vault,
    VolumeMount,
    VolumeRequest,
)
from .hcl import HCLError, parse as hcl_parse, parse_duration


def _listify(v) -> list:
    if v is None:
        return []
    if isinstance(v, list):
        return v
    return [v]


def _labeled_blocks(v) -> list[tuple[str, dict]]:
    """A labeled-block family parsed as {label: body} or the HCL1 list form."""
    out = []
    if v is None:
        return out
    if isinstance(v, dict):
        for label, body in v.items():
            for b in _listify(body):
                out.append((label, b))
    elif isinstance(v, list):
        for item in v:
            out.extend(_labeled_blocks(item))
    return out


def parse_constraint(d: dict) -> Constraint:
    """ref jobspec/parse.go parseConstraints: 'attribute' is LTarget,
    'value' RTarget; operator shorthands map to operands."""
    operand = d.get("operator", "=")
    l_target = d.get("attribute", "")
    r_target = str(d.get("value", "")) if d.get("value") is not None else ""
    for shorthand in (
        "version", "regexp", "distinct_hosts", "distinct_property",
        "set_contains", "set_contains_any",
    ):
        if shorthand in d:
            operand = shorthand
            val = d[shorthand]
            if shorthand in ("distinct_hosts",):
                if not val:
                    operand = "="
            else:
                r_target = str(val)
    return Constraint(l_target=l_target, r_target=r_target, operand=operand)


def parse_affinity(d: dict) -> Affinity:
    c = parse_constraint(d)
    return Affinity(
        l_target=c.l_target,
        r_target=c.r_target,
        operand=c.operand,
        weight=int(d.get("weight", 50)),
    )


def parse_spread(d: dict) -> Spread:
    targets = []
    for label, body in _labeled_blocks(d.get("target")):
        targets.append(
            SpreadTarget(value=label, percent=int(body.get("percent", 0)))
        )
    return Spread(
        attribute=d.get("attribute", ""),
        weight=int(d.get("weight", 50)),
        spread_target=targets,
    )


def parse_update(d: dict) -> UpdateStrategy:
    return UpdateStrategy(
        stagger=parse_duration(d.get("stagger", 0)),
        max_parallel=int(d.get("max_parallel", 1)),
        health_check=d.get("health_check", "checks"),
        min_healthy_time=parse_duration(d.get("min_healthy_time", "10s")),
        healthy_deadline=parse_duration(d.get("healthy_deadline", "5m")),
        progress_deadline=parse_duration(d.get("progress_deadline", "10m")),
        auto_revert=bool(d.get("auto_revert", False)),
        auto_promote=bool(d.get("auto_promote", False)),
        canary=int(d.get("canary", 0)),
    )


def parse_network(d: dict) -> NetworkResource:
    net = NetworkResource(mbits=int(d.get("mbits", 10)), mode=d.get("mode", ""))
    for label, body in _labeled_blocks(d.get("port")):
        port = Port(label=label)
        if "static" in body:
            port.value = int(body["static"])
            net.reserved_ports.append(port)
        else:
            port.to = int(body.get("to", 0))
            net.dynamic_ports.append(port)
    return net


def parse_resources(d: dict) -> Resources:
    res = Resources(
        cpu=int(d.get("cpu", 100)),
        memory_mb=int(d.get("memory", 300)),
    )
    if "network" in d:
        for body in _listify(d["network"]):
            res.networks.append(parse_network(body))
    for label, body in _labeled_blocks(d.get("device")):
        res.devices.append(
            RequestedDevice(
                name=label,
                count=int(body.get("count", 1)),
                constraints=[
                    parse_constraint(c) for c in _listify(body.get("constraint"))
                ],
                affinities=[
                    parse_affinity(a) for a in _listify(body.get("affinity"))
                ],
            )
        )
    return res


def parse_service(name_default: str, d: dict) -> Service:
    svc = Service(
        name=d.get("name", name_default),
        port_label=str(d.get("port", "")),
        tags=[str(t) for t in _listify(d.get("tags"))],
        canary_tags=[str(t) for t in _listify(d.get("canary_tags"))],
    )
    for body in _listify(d.get("check")):
        check = ServiceCheck(
            name=body.get("name", ""),
            type=body.get("type", ""),
            command=body.get("command", ""),
            args=[str(a) for a in _listify(body.get("args"))],
            path=body.get("path", ""),
            protocol=body.get("protocol", ""),
            port_label=str(body.get("port", "")),
            interval=parse_duration(body.get("interval", 0)),
            timeout=parse_duration(body.get("timeout", 0)),
        )
        for cr in _listify(body.get("check_restart")):
            cr = cr or {}
            check.check_restart = CheckRestart(
                limit=int(cr.get("limit", 0)),
                grace=parse_duration(cr.get("grace", 0)),
            )
        svc.checks.append(check)
    for body in _listify(d.get("connect")):
        connect = ConsulConnect()
        for sidecar in _listify(body.get("sidecar_service")):
            sidecar = sidecar or {}
            proxy = None
            for pbody in _listify(sidecar.get("proxy")):
                pbody = pbody or {}
                proxy = ConsulProxy(
                    upstreams=[
                        ConsulUpstream(
                            destination_name=u.get("destination_name", ""),
                            local_bind_port=int(u.get("local_bind_port", 0)),
                        )
                        for u in _listify(pbody.get("upstreams"))
                    ]
                )
            connect.sidecar_service = ConsulSidecarService(
                port=str(sidecar.get("port", "")), proxy=proxy
            )
        svc.connect = connect
    return svc


def parse_task(name: str, d: dict) -> Task:
    task = Task(
        name=name,
        driver=d.get("driver", ""),
        user=d.get("user", ""),
        config=d.get("config", {}) or {},
        env={k: str(v) for k, v in (d.get("env") or {}).items()},
        meta={k: str(v) for k, v in (d.get("meta") or {}).items()},
        kill_signal=d.get("kill_signal", ""),
        leader=bool(d.get("leader", False)),
    )
    if "kill_timeout" in d:
        task.kill_timeout = parse_duration(d["kill_timeout"])
    if "shutdown_delay" in d:
        task.shutdown_delay = parse_duration(d["shutdown_delay"])
    if "resources" in d:
        task.resources = parse_resources(d["resources"] or {})
    for body in _listify(d.get("constraint")):
        task.constraints.append(parse_constraint(body))
    for body in _listify(d.get("affinity")):
        task.affinities.append(parse_affinity(body))
    for body in _listify(d.get("service")):
        task.services.append(parse_service(name, body))
    for body in _listify(d.get("artifact")):
        task.artifacts.append(
            TaskArtifact(
                getter_source=body.get("source", ""),
                getter_options={
                    k: str(v) for k, v in (body.get("options") or {}).items()
                },
                getter_mode=body.get("mode", "any"),
                relative_dest=body.get("destination", ""),
            )
        )
    for body in _listify(d.get("template")):
        task.templates.append(
            Template(
                source_path=body.get("source", ""),
                dest_path=body.get("destination", ""),
                embedded_tmpl=body.get("data", ""),
                change_mode=body.get("change_mode", "restart"),
                change_signal=body.get("change_signal", ""),
                splay=parse_duration(body.get("splay", "5s")),
                perms=str(body.get("perms", "0644")),
            )
        )
    if "vault" in d:
        body = d["vault"] or {}
        task.vault = Vault(
            policies=[str(p) for p in _listify(body.get("policies"))],
            env=bool(body.get("env", True)),
            change_mode=body.get("change_mode", "restart"),
            change_signal=body.get("change_signal", ""),
        )
    if "logs" in d:
        body = d["logs"] or {}
        task.log_config = LogConfig(
            max_files=int(body.get("max_files", 10)),
            max_file_size_mb=int(body.get("max_file_size", 10)),
        )
    if "dispatch_payload" in d:
        task.dispatch_payload = DispatchPayloadConfig(
            file=(d["dispatch_payload"] or {}).get("file", "")
        )
    for body in _listify(d.get("volume_mount")):
        task.volume_mounts.append(
            VolumeMount(
                volume=body.get("volume", ""),
                destination=body.get("destination", ""),
                read_only=bool(body.get("read_only", False)),
            )
        )
    return task


def parse_group(name: str, d: dict) -> TaskGroup:
    tg = TaskGroup(
        name=name,
        count=int(d.get("count", 1)),
        meta={k: str(v) for k, v in (d.get("meta") or {}).items()},
    )
    for body in _listify(d.get("constraint")):
        tg.constraints.append(parse_constraint(body))
    for body in _listify(d.get("affinity")):
        tg.affinities.append(parse_affinity(body))
    for body in _listify(d.get("spread")):
        tg.spreads.append(parse_spread(body))
    if "restart" in d:
        body = d["restart"] or {}
        tg.restart_policy = RestartPolicy(
            attempts=int(body.get("attempts", 2)),
            interval=parse_duration(body.get("interval", "30m")),
            delay=parse_duration(body.get("delay", "15s")),
            mode=body.get("mode", "fail"),
        )
    if "reschedule" in d:
        body = d["reschedule"] or {}
        tg.reschedule_policy = ReschedulePolicy(
            attempts=int(body.get("attempts", 0)),
            interval=parse_duration(body.get("interval", 0)),
            delay=parse_duration(body.get("delay", "30s")),
            delay_function=body.get("delay_function", "exponential"),
            max_delay=parse_duration(body.get("max_delay", "1h")),
            unlimited=bool(body.get("unlimited", True)),
        )
    if "migrate" in d:
        body = d["migrate"] or {}
        tg.migrate = MigrateStrategy(
            max_parallel=int(body.get("max_parallel", 1)),
            health_check=body.get("health_check", "checks"),
            min_healthy_time=parse_duration(body.get("min_healthy_time", "10s")),
            healthy_deadline=parse_duration(body.get("healthy_deadline", "5m")),
        )
    if "update" in d:
        tg.update = parse_update(d["update"] or {})
    if "ephemeral_disk" in d:
        body = d["ephemeral_disk"] or {}
        tg.ephemeral_disk = EphemeralDisk(
            sticky=bool(body.get("sticky", False)),
            size_mb=int(body.get("size", 150)),
            migrate=bool(body.get("migrate", False)),
        )
    if "network" in d:
        for body in _listify(d["network"]):
            tg.networks.append(parse_network(body))
    for label, body in _labeled_blocks(d.get("volume")):
        tg.volumes[label] = VolumeRequest(
            name=label,
            type=body.get("type", "host"),
            source=body.get("source", ""),
            read_only=bool(body.get("read_only", False)),
        )
    for label, body in _labeled_blocks(d.get("task")):
        tg.tasks.append(parse_task(label, body))
    return tg


def parse_job(src: str) -> Job:
    """Parse an HCL jobspec into a Job (ref jobspec/parse.go:27)."""
    root = hcl_parse(src)
    jobs = _labeled_blocks(root.get("job"))
    if len(jobs) != 1:
        raise HCLError(f"expected exactly one job block, found {len(jobs)}")
    job_id, d = jobs[0]

    job = Job(
        id=d.get("id", job_id),
        name=d.get("name", job_id),
        type=d.get("type", "service"),
        priority=int(d.get("priority", 50)),
        region=d.get("region", "global"),
        all_at_once=bool(d.get("all_at_once", False)),
        datacenters=[str(x) for x in _listify(d.get("datacenters"))] or ["dc1"],
        namespace=d.get("namespace", "default"),
        meta={k: str(v) for k, v in (d.get("meta") or {}).items()},
    )
    for body in _listify(d.get("constraint")):
        job.constraints.append(parse_constraint(body))
    for body in _listify(d.get("affinity")):
        job.affinities.append(parse_affinity(body))
    for body in _listify(d.get("spread")):
        job.spreads.append(parse_spread(body))
    if "update" in d:
        job.update = parse_update(d["update"] or {})
    if "periodic" in d:
        body = d["periodic"] or {}
        job.periodic = PeriodicConfig(
            enabled=bool(body.get("enabled", True)),
            spec=body.get("cron", body.get("spec", "")),
            spec_type="cron",
            prohibit_overlap=bool(body.get("prohibit_overlap", False)),
            time_zone=body.get("time_zone", "UTC"),
        )
    if "parameterized" in d:
        body = d["parameterized"] or {}
        job.parameterized_job = ParameterizedJobConfig(
            payload=body.get("payload", ""),
            meta_required=[str(x) for x in _listify(body.get("meta_required"))],
            meta_optional=[str(x) for x in _listify(body.get("meta_optional"))],
        )
    for label, body in _labeled_blocks(d.get("group")):
        job.task_groups.append(parse_group(label, body))

    # standalone task at job level becomes its own group (HCL1 behavior)
    for label, body in _labeled_blocks(d.get("task")):
        job.task_groups.append(
            parse_group(label, {"task": {label: body}})
        )
    return job
