"""Scalar scheduler — the correctness oracle (ref scheduler/)."""

from .context import EvalContext, EvalEligibility
from .generic import GenericScheduler
from .rank import BinPackIterator, RankedNode
from .reconcile import AllocReconciler, ReconcileResults
from .scheduler import BUILTIN_SCHEDULERS, Planner, new_scheduler
from .stack import GenericStack, SelectOptions, SystemStack
from .system import SystemScheduler
from .testing import Harness, RejectPlan
