"""Pure-stdlib sampling wall-clock profiler (the Go pprof CPU-profile
role, ref command/agent/http.go:218-222 + `nomad operator debug`'s
pprof captures).

``sys._current_frames()`` is walked at ~100Hz on a dedicated thread;
every live thread's Python stack is folded into flame-graph lines
(``class:thread;file:func;file:func count``) bucketed by the thread's
**name-derived class** — which is why every spawn in the tree carries a
descriptive ``name=`` (enforced by the ``thread-unnamed`` analysis
rule). Because the sampler sees wall-clock, not CPU, it attributes
*blocked* time too: a sample whose innermost Python frame sits inside
``threading.py``/``queue.py`` is a parked thread, and the nearest
application frame below the park is charged as the **blocked site**.

That blocked-site table is the whole-process complement to the trace
plane's per-eval critical path: ROADMAP item 2's worker-scaling knee
shows up here as worker-class threads spending most of their wall time
parked at ``core/plan_apply.py:wait`` (``PendingPlan.wait`` — the
serialized applier's completion future), reported as the single number
``applier_block_frac`` without any span instrumentation in the loop.

Zero third-party deps, no signals, no C extensions: safe to run inside
the live agent behind ``enable_debug``.
"""

from __future__ import annotations

import gc
import queue
import re
import sys
import threading
import time
import traceback

#: thread-name substring -> class, first match wins (names are the
#: contract: see the thread-unnamed analysis rule)
_CLASS_RULES = (
    ("plan-applier", "applier"),
    ("plan-commit", "applier"),
    ("worker", "worker"),
    ("drain-eval", "worker"),
    ("raft", "raft"),
    ("rpc", "rpc"),
    ("mux", "rpc"),
    ("http", "http"),
    ("broker", "broker"),
    ("timer-wheel", "broker"),
    ("mirror", "mirror"),
    ("reaper", "leader"),
    ("core-gc", "leader"),
    ("periodic-dispatch", "leader"),
    ("deployments-watcher", "leader"),
    ("node-drainer", "leader"),
    ("vault", "leader"),
    ("acl-replication", "leader"),
    ("heartbeat", "heartbeat"),
    ("hb-", "heartbeat"),
    ("gossip", "gossip"),
    ("swim", "gossip"),
    ("ldg-", "loadgen"),
    ("debug-", "debug"),
    ("metrics", "metrics"),
    ("MainThread", "main"),
)

#: files whose frames are a *park*, not application code: the
#: blocked-site walk skips them to find the frame that owns the wait.
#: The lockdep witness wrappers (tier-1 default) are park frames too —
#: a thread blocked in a wrapped Lock.acquire has its innermost Python
#: frame in lockdep.py, and missing it would charge convoy wait as
#: on-CPU time (breaking the sampler↔lockdep.contention() agreement)
from ..testing import lockdep as _lockdep

_PARK_FILES = frozenset(
    {threading.__file__, queue.__file__, _lockdep.__file__}
)

#: frames matching (file suffix, function) that mean "this worker is
#: waiting on the serialized plan applier" (PendingPlan.wait)
_APPLIER_WAIT = (("core/plan_apply.py", "wait"),)


def classify_thread(name: str) -> str:
    for needle, cls in _CLASS_RULES:
        if needle in name:
            return cls
    return "other"


#: per-instance id suffixes stripped from fold keys: drain lanes spawn a
#: uniquely-named thread PER EVAL (drain-eval-<hex8>) — folding by raw
#: name would mint O(evals sampled) singleton stacks and overflow
#: max_stacks exactly under the storm the profiler exists for
_FOLD_ID_RE = re.compile(r"-[0-9a-f]{4,}$")


def fold_name(name: str) -> str:
    return _FOLD_ID_RE.sub("", name)


def _short(filename: str) -> str:
    parts = filename.replace("\\", "/").split("/")
    return "/".join(parts[-2:]) if len(parts) >= 2 else filename


class SamplingProfiler:
    """Start/stop sampler; ``report()`` is valid after ``stop()``.

    All accounting happens on the sampler thread; ``report()`` reads it
    after the join, so there is no lock on the sampling path.
    """

    def __init__(self, hz: float = 100.0, max_stacks: int = 8192):
        self.hz = max(float(hz), 1.0)
        self.max_stacks = max_stacks
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # nta: ignore[unbounded-cache] WHY: capped at max_stacks in
        # _tick (overflow counted into _dropped, never silent)
        self._folded: dict[str, int] = {}
        self._dropped = 0
        # nta: ignore[unbounded-cache] WHY: keyed by thread class — a
        # code-fixed vocabulary (_CLASS_RULES + "other")
        self._classes: dict[str, int] = {}
        # nta: ignore[unbounded-cache] WHY: keyed by (class, code site)
        # — cardinality bounded by distinct park sites in the source
        self._blocked: dict[tuple[str, str], int] = {}
        self._applier_blocked = 0
        self._ticks = 0
        self._t0 = 0.0
        self._t1 = 0.0

    # ------------------------------------------------------------------
    def start(self):
        self._t0 = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="debug-profiler"
        )
        self._thread.start()
        return self

    def stop(self) -> dict:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._t1 = time.monotonic()
        return self.report()

    # ------------------------------------------------------------------
    def _run(self):
        period = 1.0 / self.hz
        next_t = time.monotonic() + period
        me = threading.get_ident()
        while not self._stop.is_set():
            delay = next_t - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                break
            # clamp, don't catch up: after a stall (GC pause, slow tick)
            # a burst of back-to-back ticks would over-weight whatever
            # runs right after the stall — skip the missed samples
            next_t = max(next_t + period, time.monotonic())
            try:
                self._tick(me)
            except Exception:
                # a sampler tick must never kill the sampler (frames can
                # disappear mid-walk); one lost tick is one lost sample
                # nta: ignore[unsynchronized-shared-write] WHY: report()
                # is join-ordered after stop() (class docstring) — the
                # "caller" reader cannot run concurrently with the
                # sampler thread
                self._dropped += 1

    def _tick(self, me: int):
        # nta: ignore[unsynchronized-shared-write] WHY: report() is
        # join-ordered after stop() — no concurrent reader
        self._ticks += 1
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            name = names.get(ident, str(ident))
            cls = classify_thread(name)
            self._classes[cls] = self._classes.get(cls, 0) + 1
            # innermost-first frame walk (f_back chain)
            stack = []
            f = frame
            while f is not None:
                stack.append((f.f_code.co_filename, f.f_code.co_name))
                f = f.f_back
            # blocked attribution: an innermost frame inside
            # threading/queue is a park; charge the nearest app frame
            if stack and stack[0][0] in _PARK_FILES:
                site = None
                for fn, func in stack:
                    if fn not in _PARK_FILES and fn != __file__:
                        site = f"{_short(fn)}:{func}"
                        break
                if site is not None:
                    key = (cls, site)
                    self._blocked[key] = self._blocked.get(key, 0) + 1
            if cls == "worker" and any(
                fn.replace("\\", "/").endswith(suffix) and func == name_
                for fn, func in stack
                for suffix, name_ in _APPLIER_WAIT
            ):
                # nta: ignore[unsynchronized-shared-write] WHY: report()
                # is join-ordered after stop() — no concurrent reader
                self._applier_blocked += 1
            folded = f"{cls}:{fold_name(name)};" + ";".join(
                f"{_short(fn)}:{func}" for fn, func in reversed(stack)
            )
            if folded in self._folded:
                self._folded[folded] += 1
            elif len(self._folded) < self.max_stacks:
                self._folded[folded] = 1
            else:
                # nta: ignore[unsynchronized-shared-write] WHY: report()
                # is join-ordered after stop() — no concurrent reader
                self._dropped += 1

    # ------------------------------------------------------------------
    def report(self) -> dict:
        duration = max((self._t1 or time.monotonic()) - self._t0, 1e-9)
        total = sum(self._classes.values())
        worker = self._classes.get("worker", 0)
        rows = [
            {
                "site": site,
                "class": cls,
                "samples": n,
                "seconds": round(n * duration / max(self._ticks, 1), 3),
                "share": round(n / max(total, 1), 4),
            }
            for (cls, site), n in self._blocked.items()
        ]
        rows.sort(key=lambda r: (-r["samples"], r["site"]))
        return {
            "duration_s": round(duration, 3),
            "hz": self.hz,
            "hz_actual": round(self._ticks / duration, 1),
            "ticks": self._ticks,
            "samples": total,
            "dropped": self._dropped,
            "threads": dict(sorted(self._classes.items())),
            "folded": self._folded,
            "blocked_sites": rows[:50],
            "applier_block_frac": round(
                self._applier_blocked / max(worker, 1), 4
            ),
        }

    def top_blocked_site(self, cls: str = "worker"):
        """(site, samples) most-parked site for one thread class — the
        lock/wait table's headline row ('what are the workers waiting
        on'). None when that class was never seen parked."""
        best = None
        for (c, site), n in self._blocked.items():
            if c != cls:
                continue
            if best is None or n > best[1]:
                best = (site, n)
        return best


def profile(seconds: float, hz: float = 100.0) -> dict:
    """Blocking convenience: sample for ``seconds`` and return the
    report (the ``/debug/pprof/profile?seconds=N`` handler body)."""
    prof = SamplingProfiler(hz=hz).start()
    time.sleep(max(float(seconds), 0.0))
    return prof.stop()


def render_folded(report: dict) -> str:
    """Flamegraph-ready folded text (``stack count`` per line), sorted
    for deterministic artifacts."""
    folded = report.get("folded", {})
    return "\n".join(
        f"{stack} {count}"
        for stack, count in sorted(folded.items(), key=lambda e: (-e[1], e[0]))
    )


def thread_dump() -> dict:
    """One-shot thread stacks + gc stats — the original ``/debug/pprof``
    response, shape-stable (``threads``/``thread_count``/``gc``)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for ident, frame in sys._current_frames().items():
        label = names.get(ident, str(ident))
        # shared static names (rpc-conn, connect-proxy-pump, ...) must
        # not clobber each other's stacks — disambiguate duplicates,
        # keeping the bare name for the first so the legacy shape (and
        # name-keyed consumers) are unchanged for unique threads
        if label in stacks:
            n = 2
            while f"{label}#{n}" in stacks:
                n += 1
            label = f"{label}#{n}"
        stacks[label] = traceback.format_stack(frame)
    return {
        "threads": stacks,
        "thread_count": len(stacks),
        "gc": {
            "counts": gc.get_count(),
            "stats": gc.get_stats(),
        },
    }
