"""Named storm scenarios.

- ``smoke`` — the tier-1 gate: a ~30s seeded mixed storm (submit / scale
  / update / flap / drain / dispatch / GC) against a mid-size cluster on
  the pure-python scheduler path, cheap enough to run in every suite;
- ``soak`` — the production-scale churn soak (ROADMAP item 3): ramp a
  10K-node fleet over the RPC surface, preload ~1M allocations through
  real job registrations on the tpu-batch scheduler, then sustain
  minutes of mixed churn. ``slow``-marked / CLI-only.

Scale knobs are env-overridable (``SOAK_NODES``, ``SOAK_ALLOCS``,
``SOAK_CHURN_S``) so the same scenario definition runs on the driver
bench box and on a laptop; the artifact records what actually ran.
"""

from __future__ import annotations

import os

from .grammar import Phase, Scenario


def smoke(nodes: int = 48, churn_s: float = 16.0) -> Scenario:
    """~30s storm: every op kind fires, the fleet flaps and drains under
    a floor, and the run must end with zero invariant violations."""
    common = {
        "node_fleet": nodes,
        "job_slots": 48,
        "job_floor": 3,
        "ready_floor": max(4, nodes // 3),
        "count_range": (1, 4),
        "cpu_choices": (50, 100, 250),
        "memory_choices": (32, 64, 128),
        "job_categories": {"svc": 2.0, "bat": 1.0},
        "dispatch_slots": 2,
        "dispatch_fanout": (1, 3),
        "drain_deadline_s": (2.0, 8.0),
    }
    return Scenario(
        name="smoke",
        description="tier-1 mixed churn storm (~30s, mid-size cluster)",
        n_workers=2,
        server_config={
            "seed": 42,
            "heartbeat_ttl": 3600.0,
            "nack_timeout": 5.0,
            "initial_nack_delay": 0.1,
            "subsequent_nack_delay": 0.5,
        },
        phases=[
            # single-kind uniform ramps place an exact op count, so the
            # whole fleet is registered before the churn starts
            Phase(
                name="ramp_nodes",
                duration=3.0,
                rate=nodes / 3.0,
                uniform=True,
                mix={"node.register": 1.0},
                params=common,
            ),
            Phase(
                name="ramp_jobs",
                duration=3.0,
                rate=16.0 / 3.0,
                uniform=True,
                mix={"job.submit": 1.0},
                params=common,
            ),
            Phase(
                name="ramp_dsp",
                duration=1.0,
                rate=2.0,
                uniform=True,
                mix={"job.dispatch_register": 1.0},
                params=common,
            ),
            Phase(
                name="churn",
                duration=churn_s,
                rate=10.0,
                mix={
                    "job.submit": 2.0,
                    "job.scale": 3.0,
                    "job.update": 2.0,
                    "job.stop": 1.0,
                    "job.dispatch": 1.0,
                    "job.evaluate": 0.5,
                    "node.down": 0.8,
                    "node.up": 1.0,
                    "node.drain": 0.6,
                    "node.drain_off": 0.8,
                    "system.gc": 0.3,
                },
                params=common,
            ),
            Phase(
                name="wind_down",
                duration=6.0,
                rate=5.0,
                mix={
                    "job.stop": 2.0,
                    "node.up": 2.0,
                    "node.drain_off": 2.0,
                    "system.gc": 0.3,
                },
                params=common,
            ),
        ],
        quiesce_timeout=60.0,
        sample_interval=0.5,
        invariants_every=4,
        probes=2,
        slos={
            "max_invariant_violations": 0,
            "max_op_failure_rate": 0.02,
            "max_shed_rate": 0.0,
            # post-ramp slope on a mid-size cluster: allocator arena noise
            # only; a real leak class shows up far above this
            "max_rss_tail_slope_mb_per_min": 120.0,
            "max_subscriber_lag": 50_000,
        },
    )


def soak() -> Scenario:
    """The million-object churn soak over the real server path."""
    nodes = int(os.environ.get("SOAK_NODES", "10000"))
    target_allocs = int(os.environ.get("SOAK_ALLOCS", "1000000"))
    churn_s = float(os.environ.get("SOAK_CHURN_S", "180"))
    # fat batch jobs carry the bulk (few evals, large placements); svc
    # jobs carry the rolling-deploy churn; both live across the storm
    bat_count = max(1000, target_allocs // 100)
    bat_slots = max(1, round(target_allocs * 0.98 / bat_count))
    svc_slots = 40
    svc_count = max(1, round(target_allocs * 0.02 / svc_slots))
    common = {
        "node_fleet": nodes,
        "ready_floor": max(16, nodes * 3 // 4),
        "job_floor": bat_slots // 2,
        "drain_deadline_s": (5.0, 30.0),
        "dispatch_slots": 4,
        "dispatch_fanout": (2, 8),
    }
    node_ramp_rate = float(os.environ.get("SOAK_NODE_RATE", "120"))
    preload_rate = float(os.environ.get("SOAK_PRELOAD_RATE", "0.5"))
    return Scenario(
        name="soak",
        description=(
            f"sustained churn at ~{target_allocs} allocs x {nodes} nodes "
            "over the real RPC/HTTP surface"
        ),
        n_workers=int(os.environ.get("SOAK_WORKERS", "2")),
        server_config={
            "seed": 42,
            "heartbeat_ttl": 86400.0,
            "default_scheduler": "tpu-batch",
            "batch_drain": 8,
            "plan_apply_batch": 8,
            "nack_timeout": 120.0,
            "event_broker": {"event_buffer_size": 16384},
        },
        phases=[
            Phase(
                name="node_ramp",
                duration=nodes / node_ramp_rate,
                rate=node_ramp_rate,
                uniform=True,
                mix={"node.register": 1.0},
                params=common,
            ),
            Phase(
                name="preload",
                duration=(bat_slots + svc_slots) / preload_rate,
                rate=preload_rate,
                uniform=True,
                mix={"job.submit": 1.0},
                params={
                    **common,
                    "job_slots": bat_slots + svc_slots,
                    "job_categories": {
                        "bat": float(bat_slots),
                        "svc": float(svc_slots),
                    },
                    "count_range_by_category": {
                        "bat": (bat_count * 3 // 4, bat_count),
                        "svc": (max(1, svc_count // 2), svc_count),
                    },
                    "cpu_choices": (50, 100),
                    "memory_choices": (32, 64),
                },
            ),
            Phase(
                name="preload_dsp",
                duration=4.0,
                rate=1.0,
                uniform=True,
                mix={"job.dispatch_register": 1.0},
                params=common,
            ),
            Phase(
                name="churn",
                duration=churn_s,
                rate=float(os.environ.get("SOAK_CHURN_RATE", "1.2")),
                mix={
                    "job.scale": 2.5,
                    "job.update": 1.5,
                    "job.submit": 0.5,
                    "job.stop": 0.4,
                    "job.dispatch": 1.0,
                    "job.evaluate": 0.4,
                    "node.down": 0.8,
                    "node.up": 1.0,
                    "node.drain": 0.5,
                    "node.drain_off": 0.7,
                    "system.gc": 0.1,
                },
                params={
                    **common,
                    "job_slots": bat_slots + svc_slots,
                    # churn-phase submits are svc-sized, not preload-sized
                    "job_categories": {"svc": 1.0},
                    "count_range": (10, 50),
                    # ~1-5% per scale op: hundreds of allocs churned per
                    # op against the fat preload jobs
                    "scale_frac": 0.05,
                    "cpu_choices": (50, 100),
                    "memory_choices": (32, 64),
                },
            ),
            Phase(
                name="wind_down",
                duration=20.0,
                rate=1.0,
                mix={
                    "node.up": 2.0,
                    "node.drain_off": 2.0,
                    "system.gc": 0.5,
                },
                params=common,
            ),
        ],
        quiesce_timeout=float(os.environ.get("SOAK_QUIESCE_S", "600")),
        sample_interval=2.0,
        invariants_every=5,
        probes=3,
        slos={
            "max_invariant_violations": 0,
            "max_op_failure_rate": 0.02,
            "max_shed_rate": 0.01,
            # churn-window growth ceiling: the table COW churns gigabytes
            # of transient garbage at this scale; a LEAK shows as a
            # sustained slope, transient garbage as sawtooth around flat
            "max_rss_tail_slope_mb_per_min": 600.0,
            "max_subscriber_lag": 500_000,
        },
    )


_SCENARIOS = {
    "smoke": smoke,
    "soak": soak,
}


def list_scenarios() -> list[str]:
    return sorted(_SCENARIOS)


def get_scenario(name: str, **kwargs) -> Scenario:
    try:
        builder = _SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; have {list_scenarios()}"
        ) from None
    return builder(**kwargs)
