"""Batch-scheduler corpus ported from the reference
(scheduler/generic_sched_test.go TestBatchSched_* — cited per test).
Batch semantics pivot on terminal-alloc handling: completed work must
never re-run, failed/lost work must."""

from nomad_tpu import mock
from nomad_tpu.structs.model import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_RUNNING,
    ALLOC_DESIRED_STATUS_RUN,
    TaskState,
    now_ns,
)
from test_scheduler import run_eval, setup_harness
from test_sched_port_service import planned_allocs, stopped_allocs

SECOND_NS = 1_000_000_000


def batch_alloc_on(job, node, i, client_status):
    a = mock.alloc()
    a.job = job
    a.job_id = job.id
    a.namespace = job.namespace
    a.node_id = node.id
    a.name = f"{job.id}.web[{i}]"
    a.client_status = client_status
    if client_status in (ALLOC_CLIENT_STATUS_COMPLETE, ALLOC_CLIENT_STATUS_FAILED):
        now = now_ns()
        # finished in the past (the Go tests use now-10s) so a reschedule
        # delay of 5s is already due — otherwise the policy defers to a
        # follow-up eval instead of replacing now
        a.task_states = {
            "web": TaskState(
                state="dead",
                failed=client_status == ALLOC_CLIENT_STATUS_FAILED,
                started_at=now - 3600 * SECOND_NS,
                finished_at=now - 10 * SECOND_NS,
            )
        }
    return a


def setup_batch(h, count=1, status=ALLOC_CLIENT_STATUS_COMPLETE, nodes=None):
    job = mock.batch_job()
    job.task_groups[0].count = count
    h.state.upsert_job(h.next_index(), job)
    job = h.state.job_by_id(job.namespace, job.id)
    allocs = [
        batch_alloc_on(job, nodes[i % len(nodes)], i, status)
        for i in range(count)
    ]
    h.state.upsert_allocs(h.next_index(), allocs)
    return job, allocs


class TestBatchSchedPort:
    def test_run_complete_alloc_not_replaced(self):
        """ref TestBatchSched_Run_CompleteAlloc: completed batch work is
        done — a new eval must not re-place it."""
        h, nodes = setup_harness(1)
        job, allocs = setup_batch(h, nodes=nodes)
        sched, _ = run_eval(h, job, sched_type="batch")
        assert len(h.plans) == 0
        out = h.state.allocs_by_job(job.namespace, job.id)
        assert len(out) == 1
        assert h.evals[-1].status == "complete"

    def test_run_failed_alloc_replaced(self):
        """ref TestBatchSched_Run_FailedAlloc: failed batch work re-runs
        (reschedule with the tracker carried)."""
        h, nodes = setup_harness(1)
        job, allocs = setup_batch(
            h, status=ALLOC_CLIENT_STATUS_FAILED, nodes=nodes
        )
        run_eval(h, job, sched_type="batch")
        out = h.state.allocs_by_job(job.namespace, job.id)
        assert len(out) == 2
        new = [a for a in out if a.previous_allocation == allocs[0].id]
        assert len(new) == 1
        assert h.evals[-1].status == "complete"

    def test_run_lost_alloc_replaced(self):
        """ref TestBatchSched_Run_LostAlloc: a lost alloc (down node) is
        re-placed; desired=stop + client=lost on the old one."""
        h, nodes = setup_harness(2)
        job = mock.batch_job()
        job.task_groups[0].count = 1
        h.state.upsert_job(h.next_index(), job)
        job = h.state.job_by_id(job.namespace, job.id)
        a = batch_alloc_on(job, nodes[0], 0, ALLOC_CLIENT_STATUS_RUNNING)
        h.state.upsert_allocs(h.next_index(), [a])
        down = nodes[0].copy()
        down.status = "down"
        h.state.upsert_node(h.next_index(), down)
        run_eval(h, job, sched_type="batch", triggered_by="node-update")
        plan = h.plans[0]
        stopped = stopped_allocs(plan)
        assert len(stopped) == 1 and stopped[0].client_status == "lost"
        placed = planned_allocs(plan)
        assert len(placed) == 1 and placed[0].node_id == nodes[1].id

    def test_failed_alloc_queued_when_no_room(self):
        """ref TestBatchSched_Run_FailedAllocQueuedAllocations: the re-run
        that can't place shows as queued."""
        h, nodes = setup_harness(1)
        # node full of someone else's work? simplest: make it ineligible
        h.state.update_node_drain(h.next_index(), nodes[0].id, True)
        job, allocs = setup_batch(
            h, status=ALLOC_CLIENT_STATUS_FAILED, nodes=nodes
        )
        sched, _ = run_eval(h, job, sched_type="batch")
        assert sched.queued_allocs.get("web") == 1

    def test_rerun_finished_alloc_on_drained_node(self):
        """ref TestBatchSched_ReRun_SuccessfullyFinishedAlloc: a completed
        alloc on a DRAINED node must not be re-run by a fresh eval of the
        same job version — batch work that finished is finished."""
        h, nodes = setup_harness(2)
        h.state.update_node_drain(h.next_index(), nodes[0].id, True)
        job, allocs = setup_batch(h, nodes=nodes)
        run_eval(h, job, sched_type="batch")
        assert len(h.plans) == 0
        out = h.state.allocs_by_job(job.namespace, job.id)
        assert len(out) == 1
        assert out[0].id == allocs[0].id
        assert h.evals[-1].status == "complete"

    def test_job_modify_inplace_terminal_noop(self):
        """ref TestBatchSched_JobModify_InPlace_Terminal: a same-version
        eval over terminal batch allocs is a no-op."""
        h, nodes = setup_harness(2)
        job, allocs = setup_batch(h, count=2, nodes=nodes)
        sched, _ = run_eval(h, job, sched_type="batch")
        assert len(h.plans) == 0

    def test_job_modify_destructive_terminal_noop(self):
        """ref TestBatchSched_JobModify_Destructive_Terminal: completed
        allocs of the CURRENT job version are done — a destructive change
        whose allocs already completed on the new version places nothing.
        (Old-version terminal allocs WOULD re-run: filterOldTerminalAllocs
        ignores them; covered implicitly by the version semantics.)"""
        h, nodes = setup_harness(2)
        job = mock.batch_job()
        job.task_groups[0].count = 2
        job.task_groups[0].tasks[0].config = dict(
            job.task_groups[0].tasks[0].config or {}, command="/bin/other"
        )
        h.state.upsert_job(h.next_index(), job)
        job = h.state.job_by_id(job.namespace, job.id)
        # completed allocs embedding the NEW version
        allocs = [
            batch_alloc_on(job, nodes[i], i, ALLOC_CLIENT_STATUS_COMPLETE)
            for i in range(2)
        ]
        h.state.upsert_allocs(h.next_index(), allocs)
        run_eval(h, job, sched_type="batch")
        assert len(h.plans) == 0

    def test_old_version_terminal_reruns(self):
        """ref reconcile.go:543-561 filterOldTerminalAllocs: terminal
        batch allocs from an OLDER job version are ignored, so the new
        version re-runs the work."""
        h, nodes = setup_harness(2)
        job, allocs = setup_batch(h, nodes=nodes)
        job2 = job.copy()
        job2.task_groups[0].tasks[0].config = dict(
            job2.task_groups[0].tasks[0].config or {}, command="/bin/other"
        )
        h.state.upsert_job(h.next_index(), job2)
        run_eval(h, job2, sched_type="batch")
        placed = [a for p in h.plans for a in planned_allocs(p)]
        assert len(placed) == 1, "new version re-runs the batch work"

    def test_node_drain_running_old_job_migrates(self):
        """ref TestBatchSched_NodeDrain_Running_OldJob: RUNNING batch work
        on a draining node migrates."""
        h, nodes = setup_harness(2)
        job = mock.batch_job()
        job.task_groups[0].count = 1
        h.state.upsert_job(h.next_index(), job)
        job = h.state.job_by_id(job.namespace, job.id)
        a = batch_alloc_on(job, nodes[0], 0, ALLOC_CLIENT_STATUS_RUNNING)
        a.desired_transition.migrate = True
        h.state.upsert_allocs(h.next_index(), [a])
        h.state.update_node_drain(h.next_index(), nodes[0].id, True)
        run_eval(h, job, sched_type="batch", triggered_by="node-update")
        plan = h.plans[0]
        assert len(stopped_allocs(plan)) == 1
        placed = planned_allocs(plan)
        assert len(placed) == 1 and placed[0].node_id == nodes[1].id

    def test_node_drain_complete_not_migrated(self):
        """ref TestBatchSched_NodeDrain_Complete: COMPLETED batch work on a
        draining node is left alone."""
        h, nodes = setup_harness(2)
        job, allocs = setup_batch(h, nodes=nodes)
        h.state.update_node_drain(h.next_index(), nodes[0].id, True)
        run_eval(h, job, sched_type="batch", triggered_by="node-update")
        assert len(h.plans) == 0

    def test_scale_down_same_name(self):
        """ref TestBatchSched_ScaleDown_SameName: shrinking count keeps
        the surviving name and stops the rest."""
        h, nodes = setup_harness(5)
        job = mock.batch_job()
        job.task_groups[0].count = 5
        h.state.upsert_job(h.next_index(), job)
        job = h.state.job_by_id(job.namespace, job.id)
        allocs = [
            batch_alloc_on(job, nodes[i], i, ALLOC_CLIENT_STATUS_RUNNING)
            for i in range(5)
        ]
        h.state.upsert_allocs(h.next_index(), allocs)
        job2 = job.copy()
        job2.task_groups[0].count = 1
        h.state.upsert_job(h.next_index(), job2)
        run_eval(h, job2, sched_type="batch")
        plan = h.plans[0]
        assert len(stopped_allocs(plan)) == 4
        remaining = [
            a
            for a in h.state.allocs_by_job(job.namespace, job.id)
            if a.desired_status == ALLOC_DESIRED_STATUS_RUN
        ]
        assert len(remaining) == 1
        assert remaining[0].name.endswith("[0]")
