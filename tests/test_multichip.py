"""Sharded execution of all three placement planners on the 8-device
virtual mesh (conftest.py): the node axis — the framework's scale axis — is
partitioned with NamedSharding(P("nodes")) and every planner must produce
EXACTLY the placements of its unsharded run (GSPMD inserts the cross-shard
argmax/gather collectives; semantics may not drift).

The cluster/problem builders live in nomad_tpu.tpu.multichip (the scored
bench drives the same definitions, so bench and test clusters can never
drift), and the sharding specs come from nomad_tpu.tpu.shard — the ONE
placement source the runtime paths use.

Beyond the per-planner equality pins, this file carries:

- the seeded cross-shard property suite: uneven node counts whose real
  rows end mid-shard, spread classes interleaved across every shard, and
  multiple seeds — placements, spread counts and propertyset behavior
  must be bit-identical sharded vs unsharded;
- the forced-host fallback leg: with the device tier faulted, a sharded
  scheduler eval must degrade to the SAME exact-np host placements the
  unsharded one degrades to (sharding is a layout choice even when the
  mesh is on fire);
- MULTICHIP artifact hygiene: the noise filter that keeps XLA CPU-AOT
  machine-feature spam out of the artifact tail, and the capped writer.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nomad_tpu.tpu import multichip, shard
from nomad_tpu.tpu.kernel import (
    BatchArgs,
    BatchState,
    RunArgs,
    WindowArgs,
    plan_batch,
    plan_batch_runs,
    plan_batch_windowed,
)
from nomad_tpu.tpu.multichip import (
    build_cluster,
    exact_problem,
    pad_cluster,
    runs_problem,
    window_problem,
)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    if len(devices) < N_DEV:
        pytest.skip(f"need {N_DEV} virtual devices, have {len(devices)}")
    return Mesh(np.array(devices[:N_DEV]), ("nodes",))


def _put_exact(args, init, mesh):
    aspec, sspec = shard.batch_specs()
    return (
        shard.put(BatchArgs(*[jnp.asarray(a) for a in args]), aspec, mesh),
        shard.put(BatchState(*[jnp.asarray(s) for s in init]), sspec, mesh),
    )


def _put_runs(args, init, mesh):
    aspec, ispec = shard.run_specs()
    return (
        shard.put(RunArgs(*[jnp.asarray(a) for a in args]), aspec, mesh),
        shard.put(tuple(jnp.asarray(x) for x in init), ispec, mesh),
    )


def test_exact_scan_sharded_equals_unsharded(mesh):
    """Exact sequential-scan kernel at 1K nodes: node axis over 8 devices."""
    c = build_cluster(1024, 96)
    args, init = exact_problem(c)
    n_real = 1024

    _, want = plan_batch(
        BatchArgs(*[jnp.asarray(a) for a in args]),
        BatchState(*[jnp.asarray(s) for s in init]),
        n_real,
    )
    want = np.asarray(want)

    d_args, d_init = _put_exact(args, init, mesh)
    _, got = plan_batch(d_args, d_init, n_real)
    got = np.asarray(got)

    assert (want >= 0).sum() == c["n_allocs"]
    np.testing.assert_array_equal(want, got)


def test_runs_planner_sharded_equals_unsharded(mesh):
    """Run-based full-ring planner under NamedSharding(P('nodes'))."""
    c = build_cluster(1024, 512, seed=3)
    rargs, init = runs_problem(c)
    a_pad = 512

    want = np.asarray(
        plan_batch_runs(
            RunArgs(*[jnp.asarray(a) for a in rargs]),
            tuple(jnp.asarray(x) for x in init),
            a_pad,
            False,
        )
    )

    d_args, d_init = _put_runs(rargs, init, mesh)
    got = np.asarray(plan_batch_runs(d_args, d_init, a_pad, False))

    assert (want >= 0).sum() > 0
    np.testing.assert_array_equal(want, got)


def test_windowed_planner_sharded_equals_unsharded(mesh):
    """Rotation-parallel windowed planner under NamedSharding(P('nodes'))."""
    c = build_cluster(1024, 512, seed=5)
    n_real, a_pad = 1024, 512
    wargs, used0, coll0 = window_problem(c, limit=10)  # log2(1024)

    want = np.asarray(
        plan_batch_windowed(
            WindowArgs(*[jnp.asarray(a) for a in wargs]),
            jnp.asarray(used0),
            jnp.asarray(coll0),
            n_real,
            a_pad,
        )
    )

    aspec, (uspec, cspec) = shard.window_specs()
    d_args = shard.put(WindowArgs(*[jnp.asarray(a) for a in wargs]), aspec, mesh)
    got = np.asarray(
        plan_batch_windowed(
            d_args,
            shard.put(jnp.asarray(used0), uspec, mesh),
            shard.put(jnp.asarray(coll0), cspec, mesh),
            n_real,
            a_pad,
        )
    )

    assert (want >= 0).sum() > 0
    np.testing.assert_array_equal(want, got)


def test_exact_scan_sharded_multi_group(mesh):
    """Two groups with different demands sharing the usage plane, sharded."""
    n_nodes, n_allocs = 512, 64
    c = build_cluster(n_nodes, n_allocs, seed=9)
    args, init = exact_problem(c, spread=False)
    # second group: double demand, no spread
    args = args._replace(
        feasible=np.concatenate([args.feasible, args.feasible]),
        affinity=np.concatenate([args.affinity, args.affinity]),
        affinity_present=np.concatenate(
            [args.affinity_present, args.affinity_present]
        ),
        group_count=np.array([n_allocs // 2, n_allocs // 2], dtype=np.int32),
        group_eval=np.zeros(2, dtype=np.int32),
        node_value=np.concatenate([args.node_value, args.node_value]),
        spread_desired=np.full((2, c["n_values"]), -1.0, dtype=np.float32),
        spread_implicit=np.full(2, -1.0, dtype=np.float32),
        spread_weight_frac=np.zeros(2, dtype=np.float32),
        spread_even=np.zeros(2, dtype=bool),
        spread_active=np.zeros(2, dtype=bool),
        demands=np.where(
            (np.arange(n_allocs) % 2 == 0)[:, None],
            c["demand"],
            c["demand"] * 2,
        ).astype(np.int32),
        groups=(np.arange(n_allocs) % 2).astype(np.int32),
    )
    init = init._replace(
        collisions=np.zeros((2, n_nodes), dtype=np.int32),
        spread_counts=np.zeros((2, c["n_values"]), dtype=np.int32),
        spread_present=np.zeros((2, c["n_values"]), dtype=bool),
    )

    _, want = plan_batch(
        BatchArgs(*[jnp.asarray(a) for a in args]),
        BatchState(*[jnp.asarray(s) for s in init]),
        n_nodes,
    )
    want = np.asarray(want)

    d_args, d_init = _put_exact(args, init, mesh)
    _, got = plan_batch(d_args, d_init, n_nodes)

    assert (want >= 0).sum() == n_allocs
    np.testing.assert_array_equal(want, np.asarray(got))


# ---------------------------------------------------------------------------
# cross-shard property suite (ISSUE 10 satellite): uneven last shard,
# spread/propertyset across every boundary, seeded
# ---------------------------------------------------------------------------


class TestCrossShardProperty:
    #: real node count whose rows end MID-shard after bucketing: 2059
    #: buckets to 3072 = 8×384, so shards 0–4 are fully real, shard 5 is
    #: part-real part-padding, shards 6–7 are pure padding
    N_UNEVEN = 2059

    @pytest.mark.parametrize("seed", [11, 29, 47])
    def test_runs_spread_counts_cross_boundaries(self, mesh, seed):
        """The spread/propertyset reductions couple EVERY shard (classes
        interleave `node % V`), the last shard is uneven, and the run
        planner's fill/sweep mechanics must resolve identically."""
        n_allocs = 384
        c = pad_cluster(
            build_cluster(self.N_UNEVEN, n_allocs, seed=seed),
            shard.node_bucket(self.N_UNEVEN, mesh),
        )
        rargs, init = runs_problem(c)

        want = np.asarray(
            plan_batch_runs(
                RunArgs(*[jnp.asarray(a) for a in rargs]),
                tuple(jnp.asarray(x) for x in init),
                n_allocs,
                False,
            )
        )
        d_args, d_init = _put_runs(rargs, init, mesh)
        got = np.asarray(plan_batch_runs(d_args, d_init, n_allocs, False))

        assert (want >= 0).sum() == n_allocs
        np.testing.assert_array_equal(want, got)

        # the placements must actually CROSS shards: with 4 spread
        # classes interleaved over node ids, every one of the 5+ real
        # shards receives placements (a single-shard solution would
        # mean the boost reductions never left one device)
        rows_per_shard = c["capacity"].shape[0] // N_DEV
        placed_nodes = want[want >= 0]
        touched = {int(n) // rows_per_shard for n in placed_nodes}
        assert len(touched) >= 5, (
            f"placements stayed on shards {touched}; the property needs "
            "cross-boundary spread pressure"
        )

    @pytest.mark.parametrize("seed", [13, 31])
    def test_exact_scan_uneven_last_shard(self, mesh, seed):
        n_allocs = 96
        c = pad_cluster(
            build_cluster(self.N_UNEVEN, n_allocs, seed=seed),
            shard.node_bucket(self.N_UNEVEN, mesh),
        )
        args, init = exact_problem(c)

        _, want = plan_batch(
            BatchArgs(*[jnp.asarray(a) for a in args]),
            BatchState(*[jnp.asarray(s) for s in init]),
            self.N_UNEVEN,
        )
        want = np.asarray(want)
        d_args, d_init = _put_exact(args, init, mesh)
        _, got = plan_batch(d_args, d_init, self.N_UNEVEN)

        assert (want >= 0).sum() == n_allocs
        np.testing.assert_array_equal(want, np.asarray(got))

    def test_deterministic_flavor_bit_parity(self, mesh, monkeypatch):
        """The deterministic compile flavor (NOMAD_TPU_DETERMINISTIC=1 →
        kernel.DET_COMPILER_OPTIONS) is what the scored multichip bench
        and bench.py's sharded parity pin dispatch through: with fusion
        remat out of the picture, sharded placements are bit-identical
        to unsharded BY CONSTRUCTION — this pins the machinery at a
        boundary-crossing scale (the fused production pair is pinned by
        the tests above; at much larger scales fused pairs may legally
        disagree on sub-ulp score ties, which is exactly why this
        flavor exists)."""
        monkeypatch.setenv("NOMAD_TPU_DETERMINISTIC", "1")
        n_allocs = 256
        c = pad_cluster(
            build_cluster(self.N_UNEVEN, n_allocs, seed=23),
            shard.node_bucket(self.N_UNEVEN, mesh),
        )
        rargs, init = runs_problem(c)
        want = np.asarray(
            plan_batch_runs(
                RunArgs(*[jnp.asarray(a) for a in rargs]),
                tuple(jnp.asarray(x) for x in init),
                n_allocs,
                False,
            )
        )
        d_args, d_init = _put_runs(rargs, init, mesh)
        got = np.asarray(plan_batch_runs(d_args, d_init, n_allocs, False))
        assert (want >= 0).sum() == n_allocs
        np.testing.assert_array_equal(want, got)

    def test_forced_host_fallback_matches_oracle(self, mesh, monkeypatch):
        """The fallback leg: with the device tier faulted, a SHARDED
        scheduler eval must degrade to exact-np and produce the same
        placements the unsharded degraded eval produces — the mesh must
        be invisible to the host path."""
        from nomad_tpu import mock
        from nomad_tpu.state import StateStore
        from nomad_tpu.structs import compute_class
        from nomad_tpu.structs.model import Evaluation, generate_uuid
        from nomad_tpu.testing import faults
        from nomad_tpu.tpu import batch_sched
        from nomad_tpu.tpu.batch_sched import TPUBatchScheduler

        # shard small clusters too (the mock cluster is 520 nodes)
        monkeypatch.setattr(shard, "MIN_NODES", 256)

        import random

        def build_state():
            state = StateStore()
            rng = random.Random(5)
            nodes = []
            for i in range(520):
                n = mock.node()
                n.id = f"node-{i:04d}"
                n.node_resources.cpu.cpu_shares = rng.choice([8000, 16000])
                n.node_resources.memory.memory_mb = rng.choice([16384, 32768])
                n.node_resources.networks = []
                n.reserved_resources.networks.reserved_host_ports = ""
                compute_class(n)
                nodes.append(n)
            state.upsert_nodes(1, nodes)
            job = mock.job()
            job.id = "job-fallback"  # deterministic alloc names across arms
            tg = job.task_groups[0]
            tg.count = 64
            tg.tasks[0].resources.networks = []
            state.upsert_job(2, job)
            return state, job

        class Planner:
            def __init__(self):
                self.plans = []

            def submit_plan(self, plan):
                from nomad_tpu.structs.model import PlanResult

                self.plans.append(plan)
                return PlanResult(
                    node_update=plan.node_update,
                    node_allocation=plan.node_allocation,
                    node_preemptions=plan.node_preemptions,
                    alloc_index=1,
                ), None

            def update_eval(self, ev):
                pass

            def create_eval(self, ev):
                pass

        def run(sharded: bool) -> dict:
            plane = faults.install(faults.FaultPlane(seed=3))
            plane.rule("point", "error", method="tpu.kernel", count=100)
            try:
                shard.configure(N_DEV, enabled=sharded)
                state, job = build_state()
                planner = Planner()
                sched = TPUBatchScheduler(
                    state.snapshot(), planner, rng=random.Random(17)
                )
                ev = Evaluation(
                    id=generate_uuid(), namespace=job.namespace,
                    priority=job.priority, type=job.type,
                    triggered_by="job-register", job_id=job.id,
                    status="pending",
                )
                sched.process(ev)
                assert batch_sched.LAST_KERNEL_STATS.get("mode") in (
                    "exact-np-degraded",
                ), batch_sched.LAST_KERNEL_STATS.get("mode")
                return {
                    a.name: a.node_id
                    for allocs in planner.plans[0].node_allocation.values()
                    for a in allocs
                }
            finally:
                faults.uninstall()
                shard.configure(enabled=False)

        placed_sharded = run(sharded=True)
        placed_plain = run(sharded=False)
        assert placed_sharded, "fallback placed nothing"
        assert placed_sharded == placed_plain


# ---------------------------------------------------------------------------
# MULTICHIP artifact hygiene (ISSUE 10 satellite)
# ---------------------------------------------------------------------------


class TestArtifactHygiene:
    NOISE = (
        "E0731 00:12:00.683562 16739 cpu_aot_loader.cc:210] Loading "
        "XLA:CPU AOT result. Target machine feature +prefer-no-gather "
        "is not supported on the host machine."
    )
    SIGNAL = "RuntimeError: sharded placements diverge at 3/512 positions"

    def test_noise_lines_filtered_signal_kept(self):
        text = "\n".join([self.NOISE, self.SIGNAL, self.NOISE, "", "ok line"])
        out = multichip.filter_noise_tail(text)
        assert "cpu_aot_loader" not in out
        assert "SIGILL" not in out
        assert self.SIGNAL in out
        assert "ok line" in out

    def test_unknown_error_lines_never_dropped(self):
        """The filter is specific by design: a novel XLA error must
        survive it verbatim."""
        novel = "F0801 12:00:00.1 pjrt_client.cc:99] device mesh lost"
        out = multichip.filter_noise_tail(novel)
        assert out == novel

    def test_tail_capped_at_line_boundary(self):
        text = "\n".join(f"line-{i:06d} " + "x" * 40 for i in range(200))
        out = multichip.filter_noise_tail(text, cap=500)
        assert len(out) <= 500
        assert out.startswith("line-"), out[:20]  # no mid-line start
        assert out.endswith("line-000199 " + "x" * 40)

    def test_artifact_writer_filters_and_caps(self, tmp_path):
        path = str(tmp_path / "MULTICHIP_r99.json")
        report = {"n_devices": 8, "ok": True, "skipped": False}
        tail_in = "\n".join([self.NOISE] * 50 + [self.SIGNAL])
        out_path = multichip.write_artifact(report, tail=tail_in, path=path)
        with open(out_path) as f:
            data = json.load(f)
        assert data["ok"] is True
        assert "cpu_aot_loader" not in data["tail"]
        assert self.SIGNAL in data["tail"]
        assert len(data["tail"]) <= multichip.TAIL_CAP

    def test_next_artifact_path_advances_round(self, tmp_path):
        (tmp_path / "MULTICHIP_r05.json").write_text("{}")
        (tmp_path / "MULTICHIP_r11.json").write_text("{}")
        assert multichip.next_artifact_path(str(tmp_path)).endswith(
            "MULTICHIP_r12.json"
        )

    def test_summary_line_carries_timings(self):
        report = {
            "n_devices": 8, "nodes": 1024, "allocs": 256, "ok": True,
            "skipped": False,
            "planners": {
                "runs": {
                    "sharded_s": 0.5, "speedup": 1.9, "parity": 1.0,
                    "recompiles": 0,
                },
            },
        }
        line = multichip.summary_line(report)
        assert line.startswith("MULTICHIP_SUMMARY ")
        assert "runs=0.5s/x1.9/parity1.0/rc0" in line
        assert "ok=1" in line
