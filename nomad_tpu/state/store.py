"""MVCC state store with immutable snapshots and blocking queries.

The reference stores server state in go-memdb (13 tables, nomad/state/
schema.go:72-611) with watch-set blocking queries (state_store.go:188) and
atomic plan commits (UpsertPlanResults, :227). This implementation keeps the
same table set and semantics but uses table-level copy-on-write generations:
every write transaction swaps in a new immutable ``Generation``, so a snapshot
is one pointer read and readers never block writers — the property the TPU
batch scheduler relies on to build columnar mirrors without locking.

Objects stored here are treated as immutable; mutators must insert copies.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Iterable, Optional

from ..structs.model import (
    ALLOC_CLIENT_STATUS_COMPLETE,
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_LOST,
    ALLOC_CLIENT_STATUS_PENDING,
    AclPolicy,
    AclToken,
    ALLOC_CLIENT_STATUS_RUNNING,
    ALLOC_DESIRED_STATUS_EVICT,
    ALLOC_DESIRED_STATUS_STOP,
    EVAL_STATUS_BLOCKED,
    JOB_STATUS_DEAD,
    JOB_STATUS_PENDING,
    JOB_STATUS_RUNNING,
    JOB_TYPE_SYSTEM,
    NODE_SCHED_ELIGIBLE,
    NODE_SCHED_INELIGIBLE,
    NODE_STATUS_DOWN,
    DEPLOYMENT_STATUS_DESC_RUNNING,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    Allocation,
    Deployment,
    DeploymentStatus,
    DeploymentStatusUpdate,
    Evaluation,
    Job,
    fast_alloc_clone,
    JobSummary,
    Node,
    Plan,
    PlanResult,
    TaskGroupSummary,
)

from .planes import CommittedPlanes

JOB_TRACKED_VERSIONS = 6


@dataclass(frozen=True)
class Generation:
    """One immutable version of all tables. Table maps must never be mutated
    after publication — writers copy, modify, and publish a new Generation."""

    index: int = 0
    nodes: dict[str, Node] = field(default_factory=dict)
    jobs: dict[tuple[str, str], Job] = field(default_factory=dict)
    job_versions: dict[tuple[str, str, int], Job] = field(default_factory=dict)
    job_summaries: dict[tuple[str, str], JobSummary] = field(default_factory=dict)
    evals: dict[str, Evaluation] = field(default_factory=dict)
    allocs: dict[str, Allocation] = field(default_factory=dict)
    deployments: dict[str, Deployment] = field(default_factory=dict)
    periodic_launch: dict[tuple[str, str], dict] = field(default_factory=dict)
    scheduler_config: Optional[dict] = None
    autopilot_config: Optional[dict] = None
    acl_policies: dict[str, "AclPolicy"] = field(default_factory=dict)
    acl_tokens: dict[str, "AclToken"] = field(default_factory=dict)  # by accessor
    vault_accessors: dict[str, dict] = field(default_factory=dict)  # by accessor
    table_indexes: dict[str, int] = field(default_factory=dict)


class StateReader:
    """Read methods shared by live store and snapshots. Mirrors the accessor
    surface of the reference StateStore (AllocsByNode, JobByID, ...)."""

    _gen: Generation

    # -- indexes ----------------------------------------------------------
    def latest_index(self) -> int:
        return self._gen.index

    def table_index(self, table: str) -> int:
        return self._gen.table_indexes.get(table, 0)

    # -- nodes ------------------------------------------------------------
    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._gen.nodes.get(node_id)

    def nodes(self) -> Iterable[Node]:
        return self._gen.nodes.values()

    def node_by_prefix(self, prefix: str) -> list[Node]:
        return [n for nid, n in self._gen.nodes.items() if nid.startswith(prefix)]

    # -- jobs -------------------------------------------------------------
    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        return self._gen.jobs.get((namespace, job_id))

    def jobs(self) -> Iterable[Job]:
        return self._gen.jobs.values()

    def jobs_by_namespace(self, namespace: str) -> list[Job]:
        return [j for (ns, _), j in self._gen.jobs.items() if ns == namespace]

    def jobs_by_scheduler(self, scheduler_type: str) -> list[Job]:
        return [j for j in self._gen.jobs.values() if j.type == scheduler_type]

    def jobs_by_periodic(self) -> list[Job]:
        return [j for j in self._gen.jobs.values() if j.is_periodic()]

    def job_by_id_and_version(
        self, namespace: str, job_id: str, version: int
    ) -> Optional[Job]:
        return self._gen.job_versions.get((namespace, job_id, version))

    def job_versions(self, namespace: str, job_id: str) -> list[Job]:
        versions = [
            j
            for (ns, jid, _), j in self._gen.job_versions.items()
            if ns == namespace and jid == job_id
        ]
        versions.sort(key=lambda j: j.version, reverse=True)
        return versions

    def job_summaries(self) -> Iterable[JobSummary]:
        return self._gen.job_summaries.values()

    def job_summary_by_id(self, namespace: str, job_id: str) -> Optional[JobSummary]:
        return self._gen.job_summaries.get((namespace, job_id))

    # -- evals ------------------------------------------------------------
    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._gen.evals.get(eval_id)

    def evals(self) -> Iterable[Evaluation]:
        return self._gen.evals.values()

    def evals_by_job(self, namespace: str, job_id: str) -> list[Evaluation]:
        return [
            e
            for e in self._gen.evals.values()
            if e.namespace == namespace and e.job_id == job_id
        ]

    # -- allocs -----------------------------------------------------------
    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self._gen.allocs.get(alloc_id)

    def allocs(self) -> Iterable[Allocation]:
        return self._gen.allocs.values()

    def _alloc_node_index(self) -> dict[str, list[Allocation]]:
        """Lazy per-generation secondary index node_id → allocs (the memdb
        ``alloc.node_id`` index, schema.go:472). Generations are immutable
        after publication, so the index is built at most once per generation
        on first by-node read and shared by every snapshot of it; one build
        costs the same single table scan a lone allocs_by_node() used to,
        after which lookups are O(allocs on node) — the difference between
        O(A) and O(A²) for per-node sweeps like the port/device post-passes.
        Benign if two threads race: both build identical maps and the
        attribute publish is atomic."""
        gen = self._gen
        idx = gen.__dict__.get("_by_node")
        if idx is None:
            idx = {}
            for a in gen.allocs.values():
                bucket = idx.get(a.node_id)
                if bucket is None:
                    bucket = idx[a.node_id] = []
                bucket.append(a)
            object.__setattr__(gen, "_by_node", idx)
        return idx

    def _alloc_job_index(self) -> dict[tuple[str, str], list[Allocation]]:
        """Lazy per-generation index (namespace, job_id) → allocs; same
        contract as ``_alloc_node_index``."""
        gen = self._gen
        idx = gen.__dict__.get("_by_job")
        if idx is None:
            idx = {}
            for a in gen.allocs.values():
                key = (a.namespace, a.job_id)
                bucket = idx.get(key)
                if bucket is None:
                    bucket = idx[key] = []
                bucket.append(a)
            object.__setattr__(gen, "_by_job", idx)
        return idx

    def allocs_by_node(self, node_id: str) -> list[Allocation]:
        return list(self._alloc_node_index().get(node_id, ()))

    def allocs_by_node_terminal(
        self, node_id: str, terminal: bool
    ) -> list[Allocation]:
        return [
            a
            for a in self._alloc_node_index().get(node_id, ())
            if a.terminal_status() == terminal
        ]

    def allocs_by_job(
        self, namespace: str, job_id: str, any_create_index: bool = True
    ) -> list[Allocation]:
        """Allocs for a job; with any_create_index=False only allocs belonging
        to the currently registered incarnation of the job are returned
        (ref state_store.go AllocsByJob)."""
        out = list(self._alloc_job_index().get((namespace, job_id), ()))
        if not any_create_index:
            job = self._gen.jobs.get((namespace, job_id))
            if job is not None:
                out = [
                    a
                    for a in out
                    if a.job is None or a.job.create_index == job.create_index
                ]
        return out

    def allocs_by_eval(self, eval_id: str) -> list[Allocation]:
        return [a for a in self._gen.allocs.values() if a.eval_id == eval_id]

    def allocs_by_deployment(self, deployment_id: str) -> list[Allocation]:
        return [
            a for a in self._gen.allocs.values() if a.deployment_id == deployment_id
        ]

    # -- deployments ------------------------------------------------------
    def deployment_by_id(self, deployment_id: str) -> Optional[Deployment]:
        return self._gen.deployments.get(deployment_id)

    def deployments(self) -> Iterable[Deployment]:
        return self._gen.deployments.values()

    def deployments_by_job(self, namespace: str, job_id: str) -> list[Deployment]:
        return [
            d
            for d in self._gen.deployments.values()
            if d.namespace == namespace and d.job_id == job_id
        ]

    def latest_deployment_by_job_id(
        self, namespace: str, job_id: str
    ) -> Optional[Deployment]:
        ds = self.deployments_by_job(namespace, job_id)
        if not ds:
            return None
        return max(ds, key=lambda d: d.create_index)

    # -- periodic launches -----------------------------------------------
    def periodic_launch_by_id(self, namespace: str, job_id: str) -> Optional[dict]:
        return self._gen.periodic_launch.get((namespace, job_id))

    def periodic_launches(self) -> Iterable[dict]:
        return self._gen.periodic_launch.values()

    # -- config -----------------------------------------------------------
    def scheduler_config(self) -> Optional[dict]:
        return self._gen.scheduler_config

    def autopilot_config(self) -> Optional[dict]:
        return self._gen.autopilot_config

    # -- vault ------------------------------------------------------------
    def vault_accessors(self) -> list[dict]:
        return list(self._gen.vault_accessors.values())

    # -- acl --------------------------------------------------------------
    def acl_policies(self) -> Iterable["AclPolicy"]:
        return self._gen.acl_policies.values()

    def acl_policy_by_name(self, name: str) -> Optional["AclPolicy"]:
        return self._gen.acl_policies.get(name)

    def acl_tokens(self) -> Iterable["AclToken"]:
        return self._gen.acl_tokens.values()

    def acl_token_by_accessor(self, accessor: str) -> Optional["AclToken"]:
        return self._gen.acl_tokens.get(accessor)

    def acl_token_by_secret(self, secret: str) -> Optional["AclToken"]:
        for t in self._gen.acl_tokens.values():
            if t.secret_id == secret:
                return t
        return None

    # -- event-plane snapshot extraction ----------------------------------
    def snapshot_events(self, topics=None) -> list:
        """Synthetic ``<Topic>Snapshot`` events for every live object in
        this generation — the event stream's snapshot-on-subscribe source
        (events/broker.py). Each event's payload is the object's
        canonical ``to_dict()`` document, byte-identical to what a store
        query at this generation's index serves, and its ``index`` is the
        object's own modify_index (the raft index that last changed it);
        the broker stamps the enclosing snapshot frame with this
        generation's ``latest_index()``. ``topics`` (a set) narrows the
        extraction; None extracts every snapshot-able topic. NodeEvent
        and PlanResult have no standing state objects, so they
        contribute nothing here — their history lives only in the
        ring."""
        from ..events import (
            TOPIC_ALLOC,
            TOPIC_DEPLOYMENT,
            TOPIC_EVAL,
            TOPIC_JOB,
            TOPIC_NODE,
            Event,
        )

        gen = self._gen
        out: list = []

        def want(topic: str) -> bool:
            return topics is None or topic in topics

        if want(TOPIC_NODE):
            for n in gen.nodes.values():
                out.append(
                    Event(
                        topic=TOPIC_NODE,
                        type="NodeSnapshot",
                        key=n.id,
                        index=n.modify_index,
                        payload=n.to_dict(),
                    )
                )
        if want(TOPIC_JOB):
            for (ns, _), j in gen.jobs.items():
                out.append(
                    Event(
                        topic=TOPIC_JOB,
                        type="JobSnapshot",
                        key=j.id,
                        index=j.modify_index,
                        namespace=ns,
                        payload=j.to_dict(),
                    )
                )
        if want(TOPIC_EVAL):
            for e in gen.evals.values():
                out.append(
                    Event(
                        topic=TOPIC_EVAL,
                        type="EvalSnapshot",
                        key=e.id,
                        index=e.modify_index,
                        namespace=e.namespace,
                        payload=e.to_dict(),
                        filter_keys=tuple(
                            k
                            for k in (e.job_id, e.deployment_id)
                            if k
                        ),
                    )
                )
        if want(TOPIC_ALLOC):
            for a in gen.allocs.values():
                out.append(
                    Event(
                        topic=TOPIC_ALLOC,
                        type="AllocationSnapshot",
                        key=a.id,
                        index=a.modify_index,
                        namespace=a.namespace,
                        payload=a.to_dict(),
                        filter_keys=tuple(
                            k
                            for k in (
                                a.job_id,
                                a.eval_id,
                                a.deployment_id,
                            )
                            if k
                        ),
                    )
                )
        if want(TOPIC_DEPLOYMENT):
            for d in gen.deployments.values():
                out.append(
                    Event(
                        topic=TOPIC_DEPLOYMENT,
                        type="DeploymentSnapshot",
                        key=d.id,
                        index=d.modify_index,
                        namespace=d.namespace,
                        payload=d.to_dict(),
                        filter_keys=(d.job_id,) if d.job_id else (),
                    )
                )
        return out

    # -- ready nodes ------------------------------------------------------
    def ready_nodes_in_dcs(self, datacenters: list[str]) -> tuple[list[Node], dict[str, int]]:
        """Ready nodes in any of the given datacenters + per-DC availability
        counts (ref scheduler/util.go:224)."""
        dcs = set(datacenters)
        out = []
        by_dc: dict[str, int] = {}
        for n in self._gen.nodes.values():
            if not n.ready():
                continue
            if n.datacenter not in dcs:
                continue
            out.append(n)
            by_dc[n.datacenter] = by_dc.get(n.datacenter, 0) + 1
        return out, by_dc


class StateSnapshot(StateReader):
    """An immutable point-in-time view."""

    def __init__(self, gen: Generation):
        self._gen = gen


def _write_txn(method):
    """Serialize a whole read-copy-publish write transaction. In the
    reference, writes are serialized by the raft FSM apply loop; here the
    store enforces it so any caller layering is safe.

    Every write method takes ``index`` as its first argument; passing None
    allocates the next index *inside* the mutex (callers computing
    latest_index()+1 outside the lock would race and publish two writes
    under one index, starving blocking queries)."""

    @functools.wraps(method)
    def wrapper(self, index=None, *args, **kwargs):
        with self._write_mutex:
            if index is None:
                index = self._gen.index + 1
            return method(self, index, *args, **kwargs)

    return wrapper


class StateStore(StateReader):
    """The live, writable store."""

    def __init__(self):
        self._gen = Generation()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._write_mutex = threading.RLock()
        #: the dense columnar planes, patched by the SAME write transaction
        #: that swaps the tables and stamped at every publish — see
        #: state/planes.py for the commit protocol
        self.planes = CommittedPlanes()
        # commit the (empty) planes so readers are served from birth
        self.planes.commit(self._gen, self._gen.index)

    # ------------------------------------------------------------------
    # snapshots + blocking queries
    # ------------------------------------------------------------------
    def snapshot(self) -> StateSnapshot:
        return StateSnapshot(self._gen)

    def snapshot_min_index(self, index: int, timeout: float = 5.0) -> StateSnapshot:
        """Wait until the store has applied at least ``index`` then snapshot
        (ref state_store.go:114 SnapshotMinIndex)."""
        with self._cond:
            if not self._cond.wait_for(lambda: self._gen.index >= index, timeout):
                raise TimeoutError(
                    f"timed out waiting for index {index} (at {self._gen.index})"
                )
            return StateSnapshot(self._gen)

    def blocking_query(
        self,
        run: Callable[[StateSnapshot], Any],
        min_index: int = 0,
        timeout: float = 300.0,
    ) -> tuple[Any, int]:
        """Long-poll: run ``run`` against snapshots until the store index
        exceeds min_index (or timeout), then return (result, index)
        (ref state_store.go:188 BlockingQuery)."""
        deadline = threading.TIMEOUT_MAX if timeout is None else timeout
        with self._cond:
            self._cond.wait_for(lambda: self._gen.index > min_index, deadline)
            gen = self._gen
        return run(StateSnapshot(gen)), gen.index

    def _publish(self, **updates):
        """Swap in a new generation (must hold no external refs to mutated
        tables) and wake blocked queries. The committed planes are stamped
        with the new generation in the same critical section — plane
        freshness IS generation identity, never an event subscription."""
        with self._cond:
            self._gen = replace(self._gen, **updates)
            self.planes.commit(self._gen, self._gen.index)
            self._cond.notify_all()

    @staticmethod
    def _bump(gen: Generation, index: int, *tables: str) -> dict[str, int]:
        ti = dict(gen.table_indexes)
        for t in tables:
            ti[t] = index
        return ti

    # ------------------------------------------------------------------
    # nodes
    # ------------------------------------------------------------------
    @_write_txn
    def upsert_node(self, index: int, node: Node):
        self.upsert_nodes(index, [node])

    #: events retained per node (ref structs.go MaxRetainedNodeEvents)
    MAX_NODE_EVENTS = 10

    @staticmethod
    def _node_event(node: Node, subsystem: str, message: str, at_ns: int):
        """Append to the node's bounded event ring (ref state_store.go
        appendNodeEvents + UpsertNodeEventsType). ``at_ns`` comes from the
        raft payload, never local wall clock — replicas and log replays
        must produce identical state."""
        node.events = (list(node.events) + [
            {
                "timestamp": at_ns,
                "subsystem": subsystem,
                "message": message,
            }
        ])[-StateStore.MAX_NODE_EVENTS :]

    @_write_txn
    def upsert_nodes(self, index: int, nodes: list[Node]):
        """Bulk node insert: one generation swap for the whole batch (used by
        simulation/benchmark cluster bootstrap; avoids O(N²) COW copies)."""
        gen = self._gen
        table = dict(gen.nodes)
        for node in nodes:
            node = node.copy()
            existing = table.get(node.id)
            if existing is not None:
                node.create_index = existing.create_index
                node.drain = existing.drain
                # strategy must survive re-registration too, or a draining
                # client restart loses its force deadline and the drain can
                # never force-complete (ref state_store.go upsertNodeTxn)
                node.drain_strategy = existing.drain_strategy
                node.scheduling_eligibility = existing.scheduling_eligibility
                node.events = list(existing.events)
                self._node_event(
                    node, "Cluster", "Node re-registered", node.status_updated_at
                )
            else:
                node.create_index = index
                self._node_event(
                    node, "Cluster", "Node registered", node.status_updated_at
                )
            node.modify_index = index
            table[node.id] = node
        # join / re-register may change resources or attributes: the node
        # axis (and every plane keyed to it) rebuilds at commit time
        self.planes.invalidate_axis()
        self._publish(
            index=index, nodes=table, table_indexes=self._bump(gen, index, "nodes")
        )

    @_write_txn
    def upsert_node_events(self, index: int, events_by_node: dict[str, list[dict]]):
        """Append operational events to nodes' bounded event rings
        (ref state_store.go UpsertNodeEvents). Unknown node ids are
        skipped — an event for a node GC'd between emission and apply is
        not an error."""
        gen = self._gen
        table = dict(gen.nodes)
        changed = False
        for node_id, events in events_by_node.items():
            node = table.get(node_id)
            if node is None or not events:
                continue
            node = node.copy()
            node.events = (list(node.events) + list(events))[
                -self.MAX_NODE_EVENTS:
            ]
            node.modify_index = index
            table[node_id] = node
            # resources unchanged: the committed planes just swap the
            # node object so identity reads stay current
            self.planes.swap_node(node)
            changed = True
        # publish even when nothing matched: the raft index must land in
        # the store so min-index waiters see this entry applied
        self._publish(
            index=index,
            nodes=table if changed else gen.nodes,
            table_indexes=(
                self._bump(gen, index, "nodes")
                if changed
                else self._bump(gen, index)
            ),
        )

    @_write_txn
    def delete_node(self, index: int, node_id: str):
        gen = self._gen
        nodes = dict(gen.nodes)
        if nodes.pop(node_id, None) is not None:
            self.planes.invalidate_axis()
        self._publish(
            index=index, nodes=nodes, table_indexes=self._bump(gen, index, "nodes")
        )

    @_write_txn
    def update_node_status(
        self,
        index: int,
        node_id: str,
        status: str,
        updated_at_ns: int = 0,
        event: Optional[dict] = None,
    ):
        self._update_node(
            index, node_id, status=status, status_updated_at=updated_at_ns,
            _event=("Cluster", f"Node status changed to {status}", updated_at_ns),
        )

    @_write_txn
    def update_node_drain(
        self,
        index: int,
        node_id: str,
        drain: bool,
        strategy=None,
        mark_eligible: bool = False,
        updated_at_ns: int = 0,
    ):
        """ref state_store.go UpdateNodeDrain: entering drain makes the node
        ineligible; completing a drain keeps it ineligible unless the caller
        explicitly re-marks it eligible."""
        if drain:
            elig = NODE_SCHED_INELIGIBLE
        elif mark_eligible:
            elig = NODE_SCHED_ELIGIBLE
        else:
            existing = self._gen.nodes.get(node_id)
            elig = (
                existing.scheduling_eligibility
                if existing is not None
                else NODE_SCHED_INELIGIBLE
            )
        self._update_node(
            index,
            node_id,
            drain=drain,
            drain_strategy=strategy if drain else None,
            scheduling_eligibility=elig,
            _event=(
                "Drain",
                "Node drain strategy set" if drain else "Node drain complete",
                updated_at_ns,
            ),
        )

    @_write_txn
    def update_node_eligibility(
        self, index: int, node_id: str, eligibility: str, updated_at_ns: int = 0
    ):
        self._update_node(
            index, node_id, scheduling_eligibility=eligibility,
            _event=("Cluster", f"Node marked as {eligibility}", updated_at_ns),
        )

    def _update_node(self, index: int, node_id: str, _event=None, **attrs):
        gen = self._gen
        existing = gen.nodes.get(node_id)
        if existing is None:
            raise KeyError(f"node not found: {node_id}")
        node = existing.copy()
        for k, v in attrs.items():
            setattr(node, k, v)
        if _event is not None:
            self._node_event(node, *_event)
        node.modify_index = index
        nodes = dict(gen.nodes)
        nodes[node_id] = node
        # status / drain / eligibility flap: same resources — O(1) object
        # swap in the committed planes, no dense-plane mutation
        self.planes.swap_node(node)
        self._publish(
            index=index, nodes=nodes, table_indexes=self._bump(gen, index, "nodes")
        )

    # ------------------------------------------------------------------
    # jobs
    # ------------------------------------------------------------------
    @_write_txn
    def upsert_job(self, index: int, job: Job, keep_version: bool = False):
        gen = self._gen
        jobs = dict(gen.jobs)
        versions = dict(gen.job_versions)
        summaries = dict(gen.job_summaries)
        job = job.copy()
        self._upsert_job_impl(gen, jobs, versions, summaries, index, job, keep_version)
        self._publish(
            index=index,
            jobs=jobs,
            job_versions=versions,
            job_summaries=summaries,
            table_indexes=self._bump(gen, index, "jobs", "job_summary", "job_version"),
        )

    def _upsert_job_impl(self, gen, jobs, versions, summaries, index, job, keep_version):
        """ref state_store.go:1005 upsertJobImpl"""
        key = (job.namespace, job.id)
        existing = jobs.get(key)
        if existing is not None:
            job.create_index = existing.create_index
            job.modify_index = index
            if not keep_version:
                job.job_modify_index = index
                job.version = existing.version + 1
            job.status = self._job_status(job, gen.allocs, gen.evals)
        else:
            job.create_index = index
            job.modify_index = index
            job.job_modify_index = index
            job.version = 0
            if not job.status:
                job.status = JOB_STATUS_PENDING
            job.status = self._job_status(job, gen.allocs, gen.evals)

        # Job summary (ref updateSummaryWithJob)
        summary = summaries.get(key)
        if summary is None or summary.create_index != job.create_index:
            summary = JobSummary(
                job_id=job.id,
                namespace=job.namespace,
                create_index=job.create_index,
            )
        else:
            summary = summary.copy()
        for tg in job.task_groups:
            if tg.name not in summary.summary:
                summary.summary[tg.name] = TaskGroupSummary()
        summary.modify_index = index
        summaries[key] = summary

        # Version history (ref upsertJobVersion): keep most recent N versions
        versions[(job.namespace, job.id, job.version)] = job
        all_versions = sorted(
            (k for k in versions if k[0] == job.namespace and k[1] == job.id),
            key=lambda k: k[2],
            reverse=True,
        )
        for stale in all_versions[JOB_TRACKED_VERSIONS:]:
            del versions[stale]

        jobs[key] = job

    @_write_txn
    def delete_job(self, index: int, namespace: str, job_id: str):
        gen = self._gen
        key = (namespace, job_id)
        if key not in gen.jobs:
            raise KeyError(f"job not found: {key}")
        jobs = dict(gen.jobs)
        del jobs[key]
        versions = {
            k: v
            for k, v in gen.job_versions.items()
            if not (k[0] == namespace and k[1] == job_id)
        }
        summaries = dict(gen.job_summaries)
        summaries.pop(key, None)
        launches = dict(gen.periodic_launch)
        launches.pop(key, None)
        self._publish(
            index=index,
            jobs=jobs,
            job_versions=versions,
            job_summaries=summaries,
            periodic_launch=launches,
            table_indexes=self._bump(
                gen, index, "jobs", "job_summary", "job_version", "periodic_launch"
            ),
        )

    @staticmethod
    def _job_status(job: Job, allocs_map: dict, evals_map: dict) -> str:
        """ref state_store.go:3264 getJobStatus. Takes the in-transaction
        alloc/eval tables so status reflects this write's edits."""
        if job.type == JOB_TYPE_SYSTEM or job.is_parameterized() or job.is_periodic():
            return JOB_STATUS_DEAD if job.stop else JOB_STATUS_RUNNING

        has_alloc = False
        for a in allocs_map.values():
            if a.namespace == job.namespace and a.job_id == job.id:
                has_alloc = True
                if not a.terminal_status():
                    return JOB_STATUS_RUNNING

        has_eval = False
        for e in evals_map.values():
            if e.namespace == job.namespace and e.job_id == job.id:
                has_eval = True
                if not e.terminal_status():
                    return JOB_STATUS_PENDING

        if has_eval or has_alloc:
            return JOB_STATUS_DEAD
        return JOB_STATUS_PENDING

    @_write_txn
    def upsert_job_summary(self, index: int, summary: JobSummary):
        gen = self._gen
        summaries = dict(gen.job_summaries)
        summary = summary.copy()
        summary.modify_index = index
        summaries[(summary.namespace, summary.job_id)] = summary
        self._publish(
            index=index,
            job_summaries=summaries,
            table_indexes=self._bump(gen, index, "job_summary"),
        )

    @_write_txn
    def reconcile_job_summaries(self, index: int):
        """Rebuild every job summary from the allocation table (ref
        state_store.go ReconcileJobSummaries / fsm.go reconcileSummaries):
        the repair path behind PUT /v1/system/reconcile/summaries."""
        gen = self._gen
        summaries: dict[tuple[str, str], JobSummary] = {}
        for (ns, jid), job in gen.jobs.items():
            old = gen.job_summaries.get((ns, jid))
            s = JobSummary(
                namespace=ns,
                job_id=jid,
                create_index=job.create_index,
                modify_index=index,
                children_pending=old.children_pending if old else 0,
                children_running=old.children_running if old else 0,
                children_dead=old.children_dead if old else 0,
            )
            for tg in job.task_groups:
                s.summary[tg.name] = TaskGroupSummary()
            summaries[(ns, jid)] = s
        for a in gen.allocs.values():
            s = summaries.get((a.namespace, a.job_id))
            tg = s.summary.get(a.task_group) if s is not None else None
            if tg is None:
                continue
            cs = a.client_status
            if cs == ALLOC_CLIENT_STATUS_PENDING:
                tg.starting += 1
            elif cs == ALLOC_CLIENT_STATUS_RUNNING:
                tg.running += 1
            elif cs == ALLOC_CLIENT_STATUS_COMPLETE:
                tg.complete += 1
            elif cs == ALLOC_CLIENT_STATUS_FAILED:
                tg.failed += 1
            elif cs == ALLOC_CLIENT_STATUS_LOST:
                tg.lost += 1
        self._publish(
            index=index,
            job_summaries=summaries,
            table_indexes=self._bump(gen, index, "job_summary"),
        )

    # ------------------------------------------------------------------
    # evals
    # ------------------------------------------------------------------
    @_write_txn
    def upsert_evals(self, index: int, evals: list[Evaluation]):
        gen = self._gen
        table = dict(gen.evals)
        jobs_touched: dict[tuple[str, str], str] = {}
        for e in evals:
            self._nested_upsert_eval(gen, table, index, e.copy(), jobs_touched)
        jobs = self._set_job_statuses(
            dict(gen.jobs), gen.allocs, table, index, jobs_touched
        )
        self._publish(
            index=index,
            evals=table,
            jobs=jobs,
            table_indexes=self._bump(gen, index, "evals", "jobs"),
        )

    def _nested_upsert_eval(self, gen, table, index, ev, jobs_touched):
        """ref state_store.go:1647 nestedUpsertEvaluation"""
        existing = table.get(ev.id)
        if existing is not None:
            ev.create_index = existing.create_index
            ev.modify_index = index
        else:
            ev.create_index = index
            ev.modify_index = index

        # Update blocked-queued counts in the job summary when a blocked
        # eval records queued allocations (simplified from the reference's
        # job_summary queue accounting).
        table[ev.id] = ev
        jobs_touched.setdefault((ev.namespace, ev.job_id), "")

    @_write_txn
    def delete_evals(self, index: int, eval_ids: list[str], alloc_ids: list[str]):
        gen = self._gen
        evals = dict(gen.evals)
        allocs = dict(gen.allocs)
        for eid in eval_ids:
            evals.pop(eid, None)
        for aid in alloc_ids:
            if allocs.pop(aid, None) is not None:
                self.planes.remove_alloc(aid)
        self._publish(
            index=index,
            evals=evals,
            allocs=allocs,
            table_indexes=self._bump(gen, index, "evals", "allocs"),
        )

    # ------------------------------------------------------------------
    # allocs
    # ------------------------------------------------------------------
    @_write_txn
    def upsert_allocs(self, index: int, allocs: list[Allocation]):
        gen = self._gen
        table = dict(gen.allocs)
        summaries = dict(gen.job_summaries)
        deployments = dict(gen.deployments)
        jobs_touched: dict[tuple[str, str], str] = {}
        for a in allocs:
            stored = self._upsert_alloc_impl(
                gen, table, summaries, deployments, index, a.copy(), jobs_touched
            )
            self.planes.apply_alloc(stored)
        jobs = self._set_job_statuses(
            dict(gen.jobs), table, gen.evals, index, jobs_touched
        )
        self._publish(
            index=index,
            allocs=table,
            jobs=jobs,
            job_summaries=summaries,
            deployments=deployments,
            table_indexes=self._bump(
                gen, index, "allocs", "jobs", "job_summary", "deployment"
            ),
        )

    # shallow clone for plan-apply inserts: the upsert mutates only
    # top-level bookkeeping fields plus deployment_status.modify_index
    _fast_alloc_clone = staticmethod(fast_alloc_clone)

    def _upsert_alloc_impl(
        self, gen, table, summaries, deployments, index, alloc, jobs_touched
    ):
        """ref state_store.go:2050 upsertAllocsImpl"""
        exist = table.get(alloc.id)
        if exist is None:
            alloc.create_index = index
            alloc.modify_index = index
            alloc.alloc_modify_index = index
            if alloc.deployment_status is not None:
                alloc.deployment_status.modify_index = index
            if alloc.job is None:
                raise ValueError(
                    f"attempting to upsert allocation {alloc.id} without a job"
                )
        else:
            alloc.create_index = exist.create_index
            alloc.modify_index = index
            alloc.alloc_modify_index = index
            # Keep the client's task states
            alloc.task_states = exist.task_states
            # Unless the scheduler is marking the alloc lost, retain the
            # client-reported status
            if alloc.client_status != ALLOC_CLIENT_STATUS_LOST:
                alloc.client_status = exist.client_status
                alloc.client_description = exist.client_description
            if alloc.job is None:
                alloc.job = exist.job

        self._update_summary_with_alloc(gen, summaries, index, alloc, exist)
        self._update_deployment_with_alloc(deployments, index, alloc, exist)

        table[alloc.id] = alloc

        if alloc.previous_allocation:
            prev = table.get(alloc.previous_allocation)
            if prev is not None:
                prev = self._fast_alloc_clone(prev)
                prev.next_allocation = alloc.id
                prev.modify_index = index
                table[prev.id] = prev

        # Force job running while the alloc runs (ref: forceStatus)
        force = ""
        if not alloc.terminal_status():
            force = JOB_STATUS_RUNNING
        jobs_touched[(alloc.namespace, alloc.job_id)] = force
        return alloc

    @_write_txn
    def update_allocs_from_client(self, index: int, allocs: list[Allocation]):
        """Apply client status updates (ref state_store.go:1933). Only
        client-owned fields are taken from the update."""
        gen = self._gen
        table = dict(gen.allocs)
        summaries = dict(gen.job_summaries)
        deployments = dict(gen.deployments)
        jobs_touched: dict[tuple[str, str], str] = {}
        for update in allocs:
            exist = table.get(update.id)
            if exist is None:
                continue
            alloc = exist.copy()
            alloc.client_status = update.client_status
            alloc.client_description = update.client_description
            alloc.task_states = update.task_states
            # sidecar listener endpoints are client-owned (the client binds
            # them); the catalog serves them for Connect upstream resolution
            alloc.connect_proxies = update.connect_proxies
            # The client may only set deployment health + timestamp
            # (ref state_store.go:1977-1992)
            if alloc.deployment_status is not None and update.deployment_status is not None:
                old_has = alloc.deployment_status.healthy is not None
                new_has = update.deployment_status.healthy is not None
                if new_has and (
                    not old_has
                    or alloc.deployment_status.healthy != update.deployment_status.healthy
                ):
                    alloc.deployment_status.healthy = update.deployment_status.healthy
                    alloc.deployment_status.timestamp = update.deployment_status.timestamp
                    alloc.deployment_status.modify_index = index
            elif update.deployment_status is not None:
                alloc.deployment_status = update.deployment_status.copy()
                alloc.deployment_status.modify_index = index
            alloc.modify_index = index
            alloc.modify_time = update.modify_time
            self._update_summary_with_alloc(gen, summaries, index, alloc, exist)
            self._update_deployment_with_alloc(deployments, index, alloc, exist)
            table[alloc.id] = alloc
            self.planes.apply_alloc(alloc)
            force = "" if alloc.terminal_status() else JOB_STATUS_RUNNING
            jobs_touched[(alloc.namespace, alloc.job_id)] = force
        jobs = self._set_job_statuses(
            dict(gen.jobs), table, gen.evals, index, jobs_touched
        )
        self._publish(
            index=index,
            allocs=table,
            jobs=jobs,
            job_summaries=summaries,
            deployments=deployments,
            table_indexes=self._bump(
                gen, index, "allocs", "jobs", "job_summary", "deployment"
            ),
        )

    @staticmethod
    def _fast_summary_clone(summary):
        """Shallow clone of a JobSummary: only top-level bookkeeping and the
        per-task-group counters mutate, so rebind those instead of the deep
        dict-roundtrip copy() (which dominated bulk plan commits at ~100µs
        × one call per placed alloc)."""
        c = type(summary).__new__(type(summary))
        c.__dict__ = dict(summary.__dict__)
        c.summary = {k: replace(v) for k, v in summary.summary.items()}
        return c

    def _update_summary_with_alloc(self, gen, summaries, index, alloc, exist):
        """ref state_store.go:3469 updateSummaryWithAlloc"""
        if alloc.job is None:
            return
        key = (alloc.namespace, alloc.job_id)
        summary = summaries.get(key)
        if summary is None:
            return
        if summary.create_index != alloc.job.create_index:
            return
        summary = self._fast_summary_clone(summary)
        tg = summary.summary.get(alloc.task_group)
        if tg is None:
            return
        changed = False
        if exist is None:
            if alloc.client_status == ALLOC_CLIENT_STATUS_PENDING:
                tg.starting += 1
                if tg.queued > 0:
                    tg.queued -= 1
                changed = True
        elif exist.client_status != alloc.client_status:
            if alloc.client_status == ALLOC_CLIENT_STATUS_RUNNING:
                tg.running += 1
            elif alloc.client_status == ALLOC_CLIENT_STATUS_FAILED:
                tg.failed += 1
            elif alloc.client_status == ALLOC_CLIENT_STATUS_PENDING:
                tg.starting += 1
            elif alloc.client_status == "complete":
                tg.complete += 1
            elif alloc.client_status == ALLOC_CLIENT_STATUS_LOST:
                tg.lost += 1
            if exist.client_status == ALLOC_CLIENT_STATUS_RUNNING and tg.running > 0:
                tg.running -= 1
            elif exist.client_status == ALLOC_CLIENT_STATUS_PENDING and tg.starting > 0:
                tg.starting -= 1
            elif exist.client_status == ALLOC_CLIENT_STATUS_LOST and tg.lost > 0:
                tg.lost -= 1
            changed = True
        if changed:
            summary.modify_index = index
            summaries[key] = summary

    def _update_deployment_with_alloc(self, deployments, index, alloc, exist):
        """Track placed/healthy/unhealthy counts on the alloc's deployment
        (ref state_store.go updateDeploymentWithAlloc)."""
        if not alloc.deployment_id:
            return
        d = deployments.get(alloc.deployment_id)
        if d is None or not d.active():
            return
        placed = healthy = unhealthy = 0
        if exist is None:
            placed += 1
        existing_healthy = exist is not None and exist.deployment_status is not None and exist.deployment_status.healthy is not None
        new_healthy = alloc.deployment_status is not None and alloc.deployment_status.healthy is not None
        if not existing_healthy and new_healthy:
            if alloc.deployment_status.is_healthy():
                healthy += 1
            else:
                unhealthy += 1
        if placed == 0 and healthy == 0 and unhealthy == 0:
            return
        d = d.copy()
        d.modify_index = index
        state = d.task_groups.get(alloc.task_group)
        if state is None:
            return
        state.placed_allocs += placed
        state.healthy_allocs += healthy
        state.unhealthy_allocs += unhealthy
        if (
            alloc.deployment_status is not None
            and alloc.deployment_status.canary
            and exist is None
        ):
            state.placed_canaries = list(state.placed_canaries) + [alloc.id]
        deployments[d.id] = d

    def _set_job_statuses(self, jobs, allocs_map, evals_map, index, jobs_touched):
        """Recompute job statuses after alloc/eval writes, against the
        in-transaction tables (ref state_store.go:3139 setJobStatuses)."""
        for key, force in jobs_touched.items():
            job = jobs.get(key)
            if job is None:
                continue
            new_status = force or self._job_status(job, allocs_map, evals_map)
            old_status = job.status if index != job.create_index else ""
            if new_status == old_status:
                continue
            job = job.copy()
            job.status = new_status
            job.modify_index = index
            jobs[key] = job
        return jobs

    # ------------------------------------------------------------------
    # deployments
    # ------------------------------------------------------------------
    @_write_txn
    def upsert_deployment(self, index: int, deployment: Deployment):
        gen = self._gen
        deployments = dict(gen.deployments)
        self._upsert_deployment_impl(deployments, index, deployment.copy())
        self._publish(
            index=index,
            deployments=deployments,
            table_indexes=self._bump(gen, index, "deployment"),
        )

    @staticmethod
    def _upsert_deployment_impl(deployments, index, deployment):
        existing = deployments.get(deployment.id)
        if existing is not None:
            deployment.create_index = existing.create_index
            deployment.modify_index = index
        else:
            deployment.create_index = index
            deployment.modify_index = index
        deployments[deployment.id] = deployment

    @_write_txn
    def update_deployment_status(self, index: int, update: DeploymentStatusUpdate):
        gen = self._gen
        deployments = dict(gen.deployments)
        jobs = dict(gen.jobs)
        versions = dict(gen.job_versions)
        stabilized = self._apply_deployment_update(
            deployments, jobs, versions, index, update
        )
        if stabilized:
            self._publish(
                index=index,
                deployments=deployments,
                jobs=jobs,
                job_versions=versions,
                table_indexes=self._bump(
                    gen, index, "deployment", "jobs", "job_version"
                ),
            )
        else:
            self._publish(
                index=index,
                deployments=deployments,
                table_indexes=self._bump(gen, index, "deployment"),
            )

    @classmethod
    def _apply_deployment_update(cls, deployments, jobs, versions, index, update):
        """Returns True when the jobs/job_versions tables were touched."""
        d = deployments.get(update.deployment_id)
        if d is None:
            return False
        d = d.copy()
        d.status = update.status
        d.status_description = update.status_description
        d.modify_index = index
        deployments[d.id] = d
        # A successful deployment marks its job version stable
        # (ref state_store.go updateDeploymentStatusImpl → UpdateJobStability)
        if update.status == DEPLOYMENT_STATUS_SUCCESSFUL:
            cls._stabilize_job_impl(
                jobs, versions, index, d.namespace, d.job_id, d.job_version, True
            )
            return True
        return False

    @staticmethod
    def _stabilize_job_impl(jobs, versions, index, namespace, job_id, version, stable):
        """Flip the stable flag on a job version in-transaction (shared by
        deployment success and explicit UpdateJobStability)."""
        vj = versions.get((namespace, job_id, version))
        if vj is not None:
            vj = vj.copy()
            vj.stable = stable
            vj.modify_index = index
            versions[(namespace, job_id, version)] = vj
        cur = jobs.get((namespace, job_id))
        if cur is not None and cur.version == version:
            cur = cur.copy()
            cur.stable = stable
            cur.modify_index = index
            jobs[(namespace, job_id)] = cur

    @_write_txn
    def update_deployment_promotion(
        self, index: int, deployment_id: str, groups: list[str], all_groups: bool
    ):
        """Promote canaries for the requested groups (ref state_store.go
        UpdateDeploymentPromotion): each promoted group needs at least one
        healthy canary; when no group still requires promotion the
        deployment returns to plain running."""
        gen = self._gen
        deployments = dict(gen.deployments)
        d = deployments.get(deployment_id)
        if d is None:
            raise KeyError(f"deployment not found: {deployment_id}")
        if not d.active():
            raise ValueError(f"deployment {deployment_id} is terminal")
        d = d.copy()

        healthy_canaries: dict[str, int] = {}
        for alloc in gen.allocs.values():
            if alloc.deployment_id != deployment_id:
                continue
            ds = alloc.deployment_status
            if ds is not None and ds.canary and ds.is_healthy():
                healthy_canaries[alloc.task_group] = (
                    healthy_canaries.get(alloc.task_group, 0) + 1
                )

        unhealthy_err = []
        for group_name, state in d.task_groups.items():
            if not all_groups and group_name not in groups:
                continue
            if state.desired_canaries == 0 or state.promoted:
                continue
            healthy = healthy_canaries.get(group_name, 0)
            if healthy < state.desired_canaries:
                unhealthy_err.append(
                    f'Task group "{group_name}" has {healthy}/'
                    f"{state.desired_canaries} healthy canaries"
                )
                continue
            state.promoted = True
        if unhealthy_err:
            raise ValueError("; ".join(unhealthy_err))

        if not d.requires_promotion():
            d.status_description = DEPLOYMENT_STATUS_DESC_RUNNING
        d.modify_index = index
        deployments[d.id] = d
        self._publish(
            index=index,
            deployments=deployments,
            table_indexes=self._bump(gen, index, "deployment"),
        )

    @_write_txn
    def update_deployment_alloc_health(
        self,
        index: int,
        deployment_id: str,
        healthy_ids: list[str],
        unhealthy_ids: list[str],
        timestamp_ns: int = 0,
    ):
        """Record alloc deployment health + bump the deployment's per-group
        healthy/unhealthy counters (ref state_store.go
        UpdateDeploymentAllocHealth)."""
        gen = self._gen
        deployments = dict(gen.deployments)
        d = deployments.get(deployment_id)
        if d is None:
            raise KeyError(f"deployment not found: {deployment_id}")
        if not d.active():
            raise ValueError(f"deployment {deployment_id} is terminal")
        d = d.copy()
        allocs_table = dict(gen.allocs)

        def mark(alloc_id: str, healthy: bool):
            alloc = allocs_table.get(alloc_id)
            if alloc is None or alloc.deployment_id != deployment_id:
                return
            alloc = alloc.copy()
            prev = (
                alloc.deployment_status.healthy
                if alloc.deployment_status is not None
                else None
            )
            if alloc.deployment_status is None:
                alloc.deployment_status = DeploymentStatus()
            alloc.deployment_status.healthy = healthy
            alloc.deployment_status.timestamp = timestamp_ns
            alloc.deployment_status.modify_index = index
            alloc.modify_index = index
            allocs_table[alloc_id] = alloc
            state = d.task_groups.get(alloc.task_group)
            if state is not None and prev != healthy:
                if healthy:
                    state.healthy_allocs += 1
                    if prev is False:
                        state.unhealthy_allocs -= 1
                else:
                    state.unhealthy_allocs += 1
                    if prev is True:
                        state.healthy_allocs -= 1

        for aid in healthy_ids:
            mark(aid, True)
        for aid in unhealthy_ids:
            mark(aid, False)

        d.modify_index = index
        deployments[d.id] = d
        self._publish(
            index=index,
            allocs=allocs_table,
            deployments=deployments,
            table_indexes=self._bump(gen, index, "allocs", "deployment"),
        )

    @_write_txn
    def update_job_stability(
        self, index: int, namespace: str, job_id: str, version: int, stable: bool
    ):
        """Flip the stable flag on a job version (ref state_store.go
        UpdateJobStability) — used by deployment auto-revert and
        `job revert`."""
        gen = self._gen
        versions = dict(gen.job_versions)
        jobs = dict(gen.jobs)
        self._stabilize_job_impl(
            jobs, versions, index, namespace, job_id, version, stable
        )
        self._publish(
            index=index,
            jobs=jobs,
            job_versions=versions,
            table_indexes=self._bump(gen, index, "jobs", "job_version"),
        )

    @_write_txn
    def delete_deployment(self, index: int, deployment_ids: list[str]):
        gen = self._gen
        deployments = dict(gen.deployments)
        for did in deployment_ids:
            deployments.pop(did, None)
        self._publish(
            index=index,
            deployments=deployments,
            table_indexes=self._bump(gen, index, "deployment"),
        )

    # ------------------------------------------------------------------
    # periodic launches / scheduler config
    # ------------------------------------------------------------------
    @_write_txn
    def upsert_periodic_launch(self, index: int, namespace: str, job_id: str, launch_ns: int):
        gen = self._gen
        launches = dict(gen.periodic_launch)
        launches[(namespace, job_id)] = {
            "namespace": namespace,
            "job_id": job_id,
            "launch": launch_ns,
            "modify_index": index,
        }
        self._publish(
            index=index,
            periodic_launch=launches,
            table_indexes=self._bump(gen, index, "periodic_launch"),
        )

    @_write_txn
    def upsert_vault_accessors(self, index: int, accessors: list[dict]):
        """ref state_store.go UpsertVaultAccessor"""
        gen = self._gen
        table = dict(gen.vault_accessors)
        for a in accessors:
            table[a["accessor"]] = dict(a, create_index=index)
        self._publish(
            index=index,
            vault_accessors=table,
            table_indexes=self._bump(gen, index, "vault_accessors"),
        )

    @_write_txn
    def delete_vault_accessors(self, index: int, accessors: list[str]):
        gen = self._gen
        drop = set(accessors)
        table = {
            k: v for k, v in gen.vault_accessors.items() if k not in drop
        }
        self._publish(
            index=index,
            vault_accessors=table,
            table_indexes=self._bump(gen, index, "vault_accessors"),
        )

    @_write_txn
    def upsert_acl_policies(self, index: int, policies: list):
        """ref state_store.go UpsertACLPolicies"""
        gen = self._gen
        table = dict(gen.acl_policies)
        for p in policies:
            policy = AclPolicy.from_dict(p) if isinstance(p, dict) else p
            existing = table.get(policy.name)
            policy.create_index = (
                existing.create_index if existing is not None else index
            )
            policy.modify_index = index
            table[policy.name] = policy
        self._publish(
            index=index,
            acl_policies=table,
            table_indexes=self._bump(gen, index, "acl_policy"),
        )

    @_write_txn
    def delete_acl_policies(self, index: int, names: list[str]):
        gen = self._gen
        table = {k: v for k, v in gen.acl_policies.items() if k not in set(names)}
        self._publish(
            index=index,
            acl_policies=table,
            table_indexes=self._bump(gen, index, "acl_policy"),
        )

    @_write_txn
    def upsert_acl_tokens(self, index: int, tokens: list, bootstrap: bool = False):
        """ref state_store.go UpsertACLTokens; ``bootstrap`` also stamps the
        one-shot bootstrap marker (BootstrapACLTokens' index record)."""
        gen = self._gen
        table = dict(gen.acl_tokens)
        for t in tokens:
            token = AclToken.from_dict(t) if isinstance(t, dict) else t
            existing = table.get(token.accessor_id)
            token.create_index = (
                existing.create_index if existing is not None else index
            )
            token.modify_index = index
            table[token.accessor_id] = token
        bumped = ("acl_token", "acl_bootstrap") if bootstrap else ("acl_token",)
        self._publish(
            index=index,
            acl_tokens=table,
            table_indexes=self._bump(gen, index, *bumped),
        )

    @_write_txn
    def delete_acl_tokens(self, index: int, accessors: list[str]):
        gen = self._gen
        table = {
            k: v for k, v in gen.acl_tokens.items() if k not in set(accessors)
        }
        self._publish(
            index=index,
            acl_tokens=table,
            table_indexes=self._bump(gen, index, "acl_token"),
        )

    @_write_txn
    def set_scheduler_config(self, index: int, config: dict):
        gen = self._gen
        self._publish(
            index=index,
            scheduler_config=dict(config),
            table_indexes=self._bump(gen, index, "scheduler_config"),
        )

    @_write_txn
    def set_autopilot_config(self, index: int, config: dict):
        gen = self._gen
        self._publish(
            index=index,
            autopilot_config=dict(config),
            table_indexes=self._bump(gen, index, "autopilot_config"),
        )

    # ------------------------------------------------------------------
    # plan apply (the atomic commit; ref state_store.go:227)
    # ------------------------------------------------------------------
    @_write_txn
    def upsert_plan_results(self, index: int, plan: Plan, result: PlanResult,
                            preemption_evals: Optional[list[Evaluation]] = None):
        """Atomically apply a verified plan result."""
        gen = self._gen
        allocs_table = dict(gen.allocs)
        summaries = dict(gen.job_summaries)
        deployments = dict(gen.deployments)
        evals_table = dict(gen.evals)
        jobs_table = dict(gen.jobs)
        versions_table = dict(gen.job_versions)
        jobs_touched: dict[tuple[str, str], str] = {}

        if result.deployment is not None:
            self._upsert_deployment_impl(deployments, index, result.deployment.copy())
        for update in result.deployment_updates:
            self._apply_deployment_update(
                deployments, jobs_table, versions_table, index, update
            )

        if plan.eval_id and plan.eval_id in evals_table:
            ev = evals_table[plan.eval_id].copy()
            ev.modify_index = index
            evals_table[plan.eval_id] = ev

        to_upsert: list[Allocation] = []
        for allocs in result.node_update.values():
            to_upsert.extend(allocs)
        for allocs in result.node_allocation.values():
            to_upsert.extend(allocs)
        for allocs in result.node_preemptions.values():
            to_upsert.extend(allocs)

        for a in to_upsert:
            a = self._fast_alloc_clone(a)
            # Re-attach the job pulled out of the plan payload
            if a.job is None:
                a.job = plan.job
            stored = self._upsert_alloc_impl(
                gen, allocs_table, summaries, deployments, index, a, jobs_touched
            )
            self.planes.apply_alloc(stored)

        for ev in preemption_evals or []:
            self._nested_upsert_eval(gen, evals_table, index, ev.copy(), jobs_touched)

        jobs = self._set_job_statuses(
            jobs_table, allocs_table, evals_table, index, jobs_touched
        )
        self._publish(
            index=index,
            allocs=allocs_table,
            jobs=jobs,
            job_versions=versions_table,
            evals=evals_table,
            job_summaries=summaries,
            deployments=deployments,
            table_indexes=self._bump(
                gen, index, "allocs", "jobs", "job_version", "evals",
                "job_summary", "deployment"
            ),
        )
        return index

    # ------------------------------------------------------------------
    # whole-store persistence (ref nomad/fsm.go:1059 Snapshot / :1073
    # Restore — the FSM serializes every table into the raft snapshot)
    # ------------------------------------------------------------------
    def persist(self) -> dict:
        """Serialize the current generation into a plain (msgpack-able)
        dict. Tuple-keyed tables are emitted as object lists; keys are
        rebuilt on restore."""
        gen = self._gen
        return {
            "index": gen.index,
            "nodes": [n.to_dict() for n in gen.nodes.values()],
            "jobs": [j.to_dict() for j in gen.jobs.values()],
            "job_versions": [j.to_dict() for j in gen.job_versions.values()],
            "job_summaries": [s.to_dict() for s in gen.job_summaries.values()],
            "evals": [e.to_dict() for e in gen.evals.values()],
            "allocs": [a.to_dict() for a in gen.allocs.values()],
            "deployments": [d.to_dict() for d in gen.deployments.values()],
            "periodic_launch": list(gen.periodic_launch.values()),
            "scheduler_config": gen.scheduler_config,
            "autopilot_config": gen.autopilot_config,
            "acl_policies": [p.to_dict() for p in gen.acl_policies.values()],
            "acl_tokens": [t.to_dict() for t in gen.acl_tokens.values()],
            "vault_accessors": list(gen.vault_accessors.values()),
            "table_indexes": dict(gen.table_indexes),
            # the committed dense planes ride the same snapshot: restore
            # installs them instead of cold-rebuilding O(N + A) state
            "planes": self.planes.persist_for(gen),
        }

    def restore(self, data: dict):
        """Replace all tables with the persisted snapshot (ref fsm.go:1073
        Restore: blows away current state, installs the snapshot)."""
        with self._write_mutex:
            gen = Generation(
                index=data.get("index", 0),
                nodes={
                    n.id: n
                    for n in (Node.from_dict(d) for d in data.get("nodes", []))
                },
                jobs={
                    (j.namespace, j.id): j
                    for j in (Job.from_dict(d) for d in data.get("jobs", []))
                },
                job_versions={
                    (j.namespace, j.id, j.version): j
                    for j in (Job.from_dict(d) for d in data.get("job_versions", []))
                },
                job_summaries={
                    (s.namespace, s.job_id): s
                    for s in (
                        JobSummary.from_dict(d)
                        for d in data.get("job_summaries", [])
                    )
                },
                evals={
                    e.id: e
                    for e in (
                        Evaluation.from_dict(d) for d in data.get("evals", [])
                    )
                },
                allocs={
                    a.id: a
                    for a in (
                        Allocation.from_dict(d) for d in data.get("allocs", [])
                    )
                },
                deployments={
                    d.id: d
                    for d in (
                        Deployment.from_dict(x) for x in data.get("deployments", [])
                    )
                },
                periodic_launch={
                    (pl["namespace"], pl["job_id"]): pl
                    for pl in data.get("periodic_launch", [])
                },
                scheduler_config=data.get("scheduler_config"),
                autopilot_config=data.get("autopilot_config"),
                acl_policies={
                    p.name: p
                    for p in (
                        AclPolicy.from_dict(d)
                        for d in data.get("acl_policies", [])
                    )
                },
                acl_tokens={
                    t.accessor_id: t
                    for t in (
                        AclToken.from_dict(d) for d in data.get("acl_tokens", [])
                    )
                },
                vault_accessors={
                    a["accessor"]: a for a in data.get("vault_accessors", [])
                },
                table_indexes=dict(data.get("table_indexes", {})),
            )
            # stage the snapshot's planes for installation at the publish
            # below (an old snapshot without them cold-rebuilds instead)
            self.planes.stage_restore(data.get("planes"))
            self._publish(**{f: getattr(gen, f) for f in (
                "index", "nodes", "jobs", "job_versions", "job_summaries",
                "evals", "allocs", "deployments", "periodic_launch",
                "scheduler_config", "autopilot_config",
                "acl_policies", "acl_tokens",
                "vault_accessors", "table_indexes",
            )})
