"""Device-mesh sharding of the planner node axis.

The node axis is the framework's scale axis (clusters grow in nodes, not
resource columns), so it is the ONE axis partitioned across the device
mesh: every planner's capacity/usable/used planes split by node rows,
the per-group feasibility/affinity/value planes split by node columns,
and the small per-group / per-alloc tables replicate. GSPMD then keeps
feasibility + rank compute local to each shard and inserts the
collectives for the cross-shard reductions (the argmax over candidate
scores, the spread/propertyset count updates, the fit all-reduce) —
the kernels themselves are unchanged, and `tests/test_multichip.py`
pins sharded == unsharded placements value-for-value.

Mechanics (the SNIPPETS compile-helper pattern, adapted):

- one :class:`~jax.sharding.Mesh` over ``('nodes',)``, built lazily from
  ``NOMAD_TPU_SHARD_DEVICES`` (default: every visible device) and gated
  by ``NOMAD_TPU_SHARD`` — sharding is strictly opt-in, a single-chip
  box never pays a collective;
- per-planner :class:`~jax.sharding.PartitionSpec` trees
  (:func:`batch_specs` / :func:`run_specs` / :func:`window_specs`) —
  the single source the runtime paths, the warmup prewarm and the
  multichip bench all place arrays through, so the compiled input
  layouts can never drift between them (a layout mismatch is a silent
  recompile, the exact class the zero-recompile pin guards);
- :func:`put` — ``jax.device_put`` of a planner arg tree with its
  matching ``NamedSharding`` tree (scalars and small tables placed
  replicated EXPLICITLY: an uncommitted host array next to sharded
  inputs would let XLA pick a layout warmup never compiled);
- :func:`node_bucket` — the one padding policy for the node axis under
  a mesh: ``batch_sched._bucket`` rounded up to a multiple of the mesh
  size, so every shard holds the same row count and the last shard
  carries the padding rows.

Everything degrades to the unsharded path when no mesh is active: the
helpers return their inputs untouched and the planners run exactly the
single-chip programs the BASELINE numbers were taken on.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

logger = logging.getLogger("nomad_tpu.tpu.shard")

#: the mesh axis every node-dimension plane is partitioned over
AXIS = "nodes"

#: clusters below this many real nodes never shard even when a mesh is
#: configured — per-shard work would be smaller than the collective
#: latency it buys (the same shape of gate as SMALL_EVAL_ORACLE_MAX)
MIN_NODES = int(os.environ.get("NOMAD_TPU_SHARD_MIN_NODES", "4096"))

_lock = threading.Lock()
_state = {"configured": False, "mesh": None}


def _env_enabled() -> bool:
    return os.environ.get("NOMAD_TPU_SHARD", "0") == "1"


def configure(n_devices: Optional[int] = None, enabled: bool = True):
    """Build (or tear down, with ``enabled=False``) the process mesh.
    Returns the active mesh or None. Safe to call repeatedly; bench and
    tests call it explicitly, the server path calls it from config."""
    import jax
    from jax.sharding import Mesh
    import numpy as np

    with _lock:
        _state["configured"] = True
        if not enabled:
            _state["mesh"] = None
            return None
        devices = jax.devices()
        want = n_devices or int(
            os.environ.get("NOMAD_TPU_SHARD_DEVICES", str(len(devices)))
        )
        if want < 2 or len(devices) < want:
            logger.warning(
                "shard: %d devices requested, %d visible; staying unsharded",
                want, len(devices),
            )
            _state["mesh"] = None
            return None
        _state["mesh"] = Mesh(np.array(devices[:want]), (AXIS,))
        return _state["mesh"]


def active_mesh(n_nodes: Optional[int] = None):
    """The process mesh, or None when sharding is off (or ``n_nodes`` is
    given and below the MIN_NODES gate). First call resolves the env
    gate so library code never needs an explicit configure()."""
    with _lock:
        configured = _state["configured"]
        mesh = _state["mesh"]
    if not configured:
        mesh = configure(enabled=_env_enabled())
    if mesh is None:
        return None
    if n_nodes is not None and n_nodes < MIN_NODES:
        return None
    return mesh


def mesh_size(mesh) -> int:
    """Devices in ``mesh`` (1 for None — the unsharded degenerate).
    Takes the mesh EXPLICITLY: callers that were gated off (small
    cluster, sharding disabled) pass None and must get 1, never a
    re-resolved global mesh."""
    return int(mesh.devices.size) if mesh is not None else 1


def shard_tags(mesh) -> dict:
    """Trace-span tags describing the dispatch's shard topology."""
    return {"shards": int(mesh.devices.size), "mesh_axis": AXIS}


def node_bucket(n: int, mesh) -> int:
    """Padded node-axis size under ``mesh`` (None → unsharded): the ONE
    bucketing policy (batch_sched._bucket) rounded up to a mesh-size
    multiple so shards are equal-sized (the last shard absorbs the
    padding rows)."""
    from .batch_sched import _bucket

    b = _bucket(n)
    k = mesh_size(mesh)
    if k > 1 and b % k:
        b = ((b // k) + 1) * k
    return b


# ---------------------------------------------------------------------------
# PartitionSpec trees, one per planner (the single placement source)
# ---------------------------------------------------------------------------


def batch_specs():
    """(BatchArgs, BatchState) PartitionSpec trees for the exact-scan
    multi-eval planner: node rows/cols sharded, group/alloc tables
    replicated (they are O(evals), not O(cluster))."""
    from jax.sharding import PartitionSpec as P

    from .kernel import BatchArgs, BatchState

    rows, cols, rep = P(AXIS, None), P(None, AXIS), P()
    args = BatchArgs(
        capacity=rows, usable=rows, feasible=cols, affinity=cols,
        affinity_present=cols, group_count=rep, group_eval=rep,
        node_value=cols, spread_desired=rep, spread_implicit=rep,
        spread_weight_frac=rep, spread_even=rep, spread_active=rep,
        perm=cols, ring=rep, demands=rep, groups=rep, limits=rep,
        valid=rep,
    )
    state = BatchState(
        used=rows, collisions=cols, spread_counts=rep,
        spread_present=rep, offset=rep,
    )
    return args, state


def wavefront_specs():
    """(BatchArgs, BatchState) PartitionSpec trees for the wavefront
    planner. The wavefront is an alternative DRIVE over the exact-scan
    batch — same planes, same carry — so its layout IS ``batch_specs()``;
    re-exported under the planner's own name so dispatch sites, the
    warmup ladder and the multichip bench reference the planner they
    compile (and a future wavefront-only plane has one place to land).
    The tournament reduction depends on this layout: the contiguous
    node-row split is what makes the ``[S, N/S]`` local stage
    communication-free."""
    return batch_specs()


def run_specs():
    """(RunArgs, init-tuple) PartitionSpec trees for the run-based
    full-ring planner (the spread/affinity headline path)."""
    from jax.sharding import PartitionSpec as P

    from .kernel import RunArgs

    rows, node, rep = P(AXIS, None), P(AXIS), P()
    args = RunArgs(
        capacity=rows, usable=rows, feasible=node, affinity=node,
        affinity_present=node, group_count=rep, node_value=node,
        spread_desired=rep, spread_implicit=rep, spread_weight_frac=rep,
        spread_even=rep, spread_active=rep, perm=node, demand=rep,
        n_allocs=rep,
    )
    init = (rows, node, rep, rep)
    return args, init


def window_specs():
    """(WindowArgs, (used0, collisions0)) PartitionSpec trees for the
    rotation-parallel windowed planner."""
    from jax.sharding import PartitionSpec as P

    from .kernel import WindowArgs

    rows, node, rep = P(AXIS, None), P(AXIS), P()
    args = WindowArgs(
        capacity=rows, usable=rows, feasible=node, perm=node,
        demand=rep, group_count=rep, limit=rep, n_allocs=rep,
    )
    return args, (rows, node)


def paged_specs():
    """(static-tile, dynamic-tile) PartitionSpec trees for the paged
    planner's tile stream (tpu/paging.py). A tile is a contiguous
    node-row slab, so the layout is the windowed planner's restricted to
    one tile: static planes (capacity rows, usable rows, feasible lane,
    node-id lane) and dynamic planes (used rows, collisions lane) all
    split over the node axis — ``paging.tile_rows`` rounds the tile to a
    mesh multiple so shards stay equal-sized."""
    from jax.sharding import PartitionSpec as P

    rows, node = P(AXIS, None), P(AXIS)
    return (rows, rows, node, node), (rows, node)


def put(tree, spec_tree, mesh):
    """``device_put`` a planner arg tree with its PartitionSpec tree.
    Every leaf — including the replicated scalars — is placed with an
    explicit NamedSharding so the committed layouts match what the
    warmup prewarm compiled (the zero-recompile contract).

    ``spec_tree`` mirrors ``tree``'s structure with PartitionSpec leaves
    (a PartitionSpec is itself a tuple, but ``tree``'s structure wins in
    tree_map, so each spec rides through whole at its leaf position).

    Placement routes through ``devprof.device_put`` — THE counted
    wrapper — so every host→device byte the mesh path moves lands in
    the transfer ledger (debug/devprof.py; the ``transfer-uncounted``
    analysis rule keeps this exhaustive)."""
    import jax
    from jax.sharding import NamedSharding

    from ..debug import devprof as _devprof

    def _put(x, spec):
        return _devprof.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(_put, tree, spec_tree)
