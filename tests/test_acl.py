"""ACL system + Search endpoint (ref acl/policy.go, acl/acl.go,
nomad/acl.go, acl_endpoint.go Bootstrap, search_endpoint.go)."""

import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.acl import compile_acl, parse_policy
from nomad_tpu.acl.policy import PolicyError
from nomad_tpu.api.client import APIError, ApiClient
from nomad_tpu.api.http import HTTPServer
from nomad_tpu.core.server import Server
from nomad_tpu.raft import InmemTransport, RaftConfig


class TestPolicyParse:
    def test_coarse_expansion(self):
        p = parse_policy('namespace "default" { policy = "read" }')
        (ns,) = p.namespaces
        assert ns.capabilities == {"list-jobs", "read-job"}
        p = parse_policy('namespace "default" { policy = "write" }')
        assert "submit-job" in p.namespaces[0].capabilities

    def test_capabilities_and_domains(self):
        p = parse_policy(
            """
namespace "ops-*" { capabilities = ["read-job", "submit-job"] }
node { policy = "read" }
operator { policy = "write" }
"""
        )
        assert p.namespaces[0].name == "ops-*"
        assert p.node == "read" and p.operator == "write"

    def test_invalid_policy_rejected(self):
        with pytest.raises(PolicyError):
            parse_policy('namespace "x" { policy = "root" }')


class TestACLEval:
    def test_namespace_glob_longest_match(self):
        acl = compile_acl(
            [
                parse_policy('namespace "ops-*" { policy = "read" }'),
                parse_policy('namespace "ops-prod-*" { policy = "write" }'),
            ]
        )
        assert acl.allow_namespace_operation("ops-dev", "read-job")
        assert not acl.allow_namespace_operation("ops-dev", "submit-job")
        assert acl.allow_namespace_operation("ops-prod-1", "submit-job")
        assert not acl.allow_namespace_operation("other", "read-job")

    def test_deny_dominates(self):
        acl = compile_acl(
            [
                parse_policy('namespace "default" { policy = "write" }'),
                parse_policy('namespace "default" { policy = "deny" }'),
            ]
        )
        assert not acl.allow_namespace_operation("default", "read-job")

    def test_coarse_domains(self):
        acl = compile_acl([parse_policy('node { policy = "read" }')])
        assert acl.allow_node_read()
        assert not acl.allow_node_write()
        assert not acl.allow_operator_read()


def make_acl_server():
    cfg = {
        "seed": 42,
        "heartbeat_ttl": 600.0,
        "acl": {"enabled": True},
        "raft": {
            "node_id": "s0",
            "address": "raft0",
            "voters": {"s0": "raft0"},
            "transport": InmemTransport(),
            "config": RaftConfig(
                heartbeat_interval=0.02,
                election_timeout_min=0.05,
                election_timeout_max=0.10,
            ),
        },
    }
    s = Server(cfg)
    s.start(num_workers=1, wait_for_leader=5.0)
    return s


class TestACLEnforcement:
    def test_bootstrap_token_enforcement_flow(self):
        """Bootstrap → anonymous denied → management allowed → scoped client
        token gets exactly its grants. The full acl_endpoint + http
        enforcement loop."""
        server = make_acl_server()
        http = HTTPServer(server, port=0)
        http.start()
        try:
            anon = ApiClient(address=f"http://127.0.0.1:{http.port}")
            # anonymous: denied before bootstrap completes the loop
            with pytest.raises(APIError) as e:
                anon.jobs()
            assert e.value.status == 403

            boot = anon.put("/v1/acl/bootstrap")[0]
            assert boot["SecretID"] and boot["Type"] == "management"

            # second bootstrap is rejected
            with pytest.raises(APIError):
                anon.put("/v1/acl/bootstrap")

            mgmt = ApiClient(
                address=f"http://127.0.0.1:{http.port}", token=boot["SecretID"]
            )
            assert mgmt.jobs() == []

            # scoped policy + client token
            mgmt.put(
                "/v1/acl/policy/readonly",
                body={
                    "Rules": 'namespace "default" { policy = "read" }',
                },
            )
            tok = mgmt.put(
                "/v1/acl/token",
                body={"Name": "ro", "Type": "client", "Policies": ["readonly"]},
            )[0]
            ro = ApiClient(
                address=f"http://127.0.0.1:{http.port}", token=tok["SecretID"]
            )
            assert ro.jobs() == []  # list-jobs granted
            job = mock.job()
            job.task_groups[0].tasks[0].resources.networks = []
            with pytest.raises(APIError) as e:
                ro.register_job(job.to_dict())  # submit-job NOT granted
            assert e.value.status == 403
            # node reads denied too (no node policy)
            with pytest.raises(APIError):
                ro.get("/v1/nodes")
            # acl admin is management-only
            with pytest.raises(APIError):
                ro.get("/v1/acl/tokens")

            # bogus token outright rejected
            bad = ApiClient(
                address=f"http://127.0.0.1:{http.port}", token="nope"
            )
            with pytest.raises(APIError) as e:
                bad.jobs()
            assert e.value.status == 403

            # management can schedule end-to-end with ACLs on
            server.node_register(mock.node())
            resp = mgmt.register_job(job.to_dict())
            assert resp["EvalID"]
        finally:
            http.stop()
            server.stop()

    def test_acl_disabled_allows_all(self):
        cfg_server = Server(
            {
                "seed": 1,
                "heartbeat_ttl": 600.0,
                "raft": {
                    "node_id": "s0",
                    "address": "r0",
                    "voters": {"s0": "r0"},
                    "transport": InmemTransport(),
                    "config": RaftConfig(
                        heartbeat_interval=0.02,
                        election_timeout_min=0.05,
                        election_timeout_max=0.10,
                    ),
                },
            }
        )
        cfg_server.start(num_workers=0, wait_for_leader=5.0)
        http = HTTPServer(cfg_server, port=0)
        http.start()
        try:
            anon = ApiClient(address=f"http://127.0.0.1:{http.port}")
            assert anon.jobs() == []
        finally:
            http.stop()
            cfg_server.stop()


class TestCrossNamespace:
    def test_body_namespace_cannot_escape_checked_namespace(self):
        """A token scoped to one namespace can't register into another by
        putting a different namespace in the job body (the gate checks the
        query namespace; the handler must re-check the resource's)."""
        server = make_acl_server()
        http = HTTPServer(server, port=0)
        http.start()
        try:
            boot = server.acl_bootstrap()
            mgmt = ApiClient(
                address=f"http://127.0.0.1:{http.port}", token=boot.secret_id
            )
            mgmt.put(
                "/v1/acl/policy/dev-only",
                body={"Rules": 'namespace "dev" { policy = "write" }'},
            )
            tok = mgmt.put(
                "/v1/acl/token",
                body={"Type": "client", "Policies": ["dev-only"]},
            )[0]
            dev = ApiClient(
                address=f"http://127.0.0.1:{http.port}", token=tok["SecretID"]
            )
            job = mock.job()
            job.namespace = "prod"
            job.task_groups[0].tasks[0].resources.networks = []
            with pytest.raises(APIError) as e:
                dev.put("/v1/jobs?namespace=dev", body={"Job": job.to_dict()})
            assert e.value.status == 403
        finally:
            http.stop()
            server.stop()


class TestSearch:
    def test_prefix_search_contexts(self):
        server = make_acl_server()
        http = HTTPServer(server, port=0)
        http.start()
        try:
            boot = server.acl_bootstrap()
            mgmt = ApiClient(
                address=f"http://127.0.0.1:{http.port}", token=boot.secret_id
            )
            node = mock.node()
            server.node_register(node)
            job = mock.job()
            job.id = "web-frontend"
            job.task_groups[0].count = 1
            job.task_groups[0].tasks[0].resources.networks = []
            server.job_register(job)

            res = mgmt.put("/v1/search", body={"Prefix": "web-", "Context": "jobs"})[0]
            assert res["matches"]["jobs"] == ["web-frontend"]
            assert "nodes" not in res["matches"]

            res = mgmt.put(
                "/v1/search", body={"Prefix": node.id[:8], "Context": "all"}
            )[0]
            assert node.id in res["matches"]["nodes"]
        finally:
            http.stop()
            server.stop()
