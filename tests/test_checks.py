"""Service health checks: script/http/tcp execution, catalog integration,
deployment health gating (ref command/agent/consul script checks,
allochealth/tracker.go)."""

import http.server
import os
import socket
import threading
import time

import pytest

import nomad_tpu.mock as mock
from nomad_tpu.agent import DevAgent
from nomad_tpu.api.client import ApiClient
from nomad_tpu.api.http import HTTPServer
from nomad_tpu.client.checks import run_check
from nomad_tpu.structs.model import Service, ServiceCheck


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class FakeAlloc:
    allocated_resources = None


class TestRunCheck:
    def test_script_pass_fail(self, tmp_path):
        ok = run_check(
            ServiceCheck(name="ok", type="script", command="/bin/true"),
            FakeAlloc(), "t", str(tmp_path), {},
        )
        assert ok[0] == "passing"
        bad = run_check(
            ServiceCheck(name="bad", type="script", command="/bin/false"),
            FakeAlloc(), "t", str(tmp_path), {},
        )
        assert bad[0] == "critical"

    def test_script_timeout(self, tmp_path):
        status, output = run_check(
            ServiceCheck(
                name="slow", type="script", command="/bin/sleep",
                args=["5"], timeout=int(0.2 * 1e9),
            ),
            FakeAlloc(), "t", str(tmp_path), {},
        )
        assert status == "critical"
        assert "timed out" in output

    def _alloc_with_port(self, port):
        from nomad_tpu.structs.model import (
            AllocatedResources, AllocatedTaskResources, NetworkResource, Port,
        )

        alloc = FakeAlloc()
        alloc.allocated_resources = AllocatedResources(
            tasks={
                "t": AllocatedTaskResources(
                    networks=[
                        NetworkResource(
                            ip="127.0.0.1",
                            reserved_ports=[Port(label="web", value=port)],
                        )
                    ]
                )
            }
        )
        return alloc

    def test_tcp_and_http(self):
        class Quiet(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                code = 500 if self.path == "/broken" else 200
                self.send_response(code)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def log_message(self, *a):
                pass

        httpd = http.server.HTTPServer(("127.0.0.1", 0), Quiet)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            alloc = self._alloc_with_port(port)
            tcp = run_check(
                ServiceCheck(name="tcp", type="tcp", port_label="web"),
                alloc, "t", "", {},
            )
            assert tcp[0] == "passing"
            ok = run_check(
                ServiceCheck(name="http", type="http", port_label="web", path="/health"),
                alloc, "t", "", {},
            )
            assert ok[0] == "passing"
            bad = run_check(
                ServiceCheck(name="http", type="http", port_label="web", path="/broken"),
                alloc, "t", "", {},
            )
            assert bad[0] == "critical"
        finally:
            httpd.shutdown()

    def test_tcp_refused(self):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            free_port = s.getsockname()[1]
        alloc = self._alloc_with_port(free_port)
        status, _ = run_check(
            ServiceCheck(name="tcp", type="tcp", port_label="web"),
            alloc, "t", "", {},
        )
        assert status == "critical"


class TestCheckSurface:
    def test_check_transitions_reach_catalog(self, tmp_path):
        flag = tmp_path / "healthy-flag"
        agent = DevAgent(num_clients=1, server_config={"seed": 89})
        agent.start()
        http_srv = HTTPServer(agent.server, port=0, agent=agent)
        http_srv.start()
        api = ApiClient(address=http_srv.address)
        try:
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": "/bin/sleep", "args": ["60"]}
            task.resources.networks = []
            task.services = [
                Service(
                    name="checked-svc",
                    checks=[
                        ServiceCheck(
                            name="flag-check",
                            type="script",
                            command="/usr/bin/test",
                            args=["-f", str(flag)],
                            interval=int(0.1 * 1e9),
                        )
                    ],
                )
            ]
            agent.server.job_register(job)

            # critical first: the flag file doesn't exist yet
            def catalog_status():
                try:
                    entries = api.get("/v1/service/checked-svc")[0]
                except Exception:
                    return None
                return entries[0]["Status"]

            # the check result must reach replicated server state (the
            # client pushes the transition through its update loop)
            def server_check_status():
                allocs = agent.server.state.allocs_by_job(
                    job.namespace, job.id
                )
                if not allocs:
                    return None
                state = allocs[0].task_states.get("web")
                return state.check_status.get("flag-check") if state else None

            wait_until(
                lambda: server_check_status() == "critical",
                msg="critical check replicated to server state",
            )
            assert catalog_status() == "critical"

            flag.write_text("ok")
            wait_until(
                lambda: catalog_status() == "passing",
                msg="check passing in catalog",
            )
        finally:
            http_srv.stop()
            agent.stop()

    def test_check_restart_recycles_task(self, tmp_path):
        """check_restart: limit consecutive criticals after grace restart
        the task through the user-restart path; a check that starts
        passing (flag present on the relaunch) stops the cycling."""
        from nomad_tpu.structs.model import CheckRestart

        flag = tmp_path / "come-up-healthy"
        agent = DevAgent(num_clients=1, server_config={"seed": 107})
        agent.start()
        try:
            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            task = tg.tasks[0]
            task.driver = "raw_exec"
            # first boot writes the flag, so the SECOND generation's check
            # passes: exactly one health restart expected
            task.config = {
                "command": "/bin/sh",
                "args": ["-c", f"touch {flag}.attempt; sleep 60"],
            }
            task.resources.networks = []
            task.services = [
                Service(
                    name="flappy",
                    checks=[
                        ServiceCheck(
                            name="flag",
                            type="script",
                            command="/usr/bin/test",
                            args=["-f", str(flag)],
                            interval=int(0.1 * 1e9),
                            check_restart=CheckRestart(
                                limit=2, grace=int(0.1 * 1e9)
                            ),
                        )
                    ],
                )
            ]
            agent.server.job_register(job)

            def task_state():
                allocs = agent.server.state.allocs_by_job(
                    job.namespace, job.id
                )
                return (
                    allocs[0].task_states.get("web") if allocs else None
                )

            wait_until(
                lambda: task_state() is not None
                and task_state().restarts >= 1,
                msg="check_restart recycled the task",
            )
            # let the next generation pass its check and stabilize
            flag.write_text("ok")
            wait_until(
                lambda: task_state() is not None
                and task_state().state == "running"
                and task_state().check_status.get("flag") == "passing",
                msg="task healthy after flag appears",
            )
        finally:
            agent.stop()

    def test_failing_check_blocks_deployment_health(self):
        """health_check='checks' (default): a critical check keeps the
        alloc from reporting healthy, failing the deployment at the
        healthy_deadline."""
        agent = DevAgent(num_clients=1, server_config={"seed": 97})
        agent.start()
        try:
            from nomad_tpu.structs.model import UpdateStrategy

            job = mock.job()
            tg = job.task_groups[0]
            tg.count = 1
            tg.update = UpdateStrategy(
                max_parallel=1,
                min_healthy_time=int(0.1 * 1e9),
                healthy_deadline=int(1.5 * 1e9),
                progress_deadline=int(3 * 1e9),
                auto_revert=False,
            )
            task = tg.tasks[0]
            task.driver = "raw_exec"
            task.config = {"command": "/bin/sleep", "args": ["60"]}
            task.resources.networks = []
            task.services = [
                Service(
                    name="never-healthy",
                    checks=[
                        ServiceCheck(
                            name="always-red",
                            type="script",
                            command="/bin/false",
                            interval=int(0.1 * 1e9),
                        )
                    ],
                )
            ]
            agent.server.job_register(job)
            # v2 so a deployment exists
            job2 = job.copy()
            job2.version = 1
            job2.task_groups[0].tasks[0].config = {
                "command": "/bin/sleep",
                "args": ["61"],
            }
            agent.server.job_register(job2)

            def deployment_failed():
                deps = agent.server.state.deployments_by_job(
                    job.namespace, job.id
                )
                return any(d.status == "failed" for d in deps)

            wait_until(
                lambda: deployment_failed(),
                timeout=30,
                msg="deployment failed on critical check",
            )
        finally:
            agent.stop()
