"""TPU-native batched scheduling backend.

The reference scores one allocation against one node at a time inside a Go
iterator chain (scheduler/rank.go:176). Here the same semantics are expressed
as dense array programs: a columnar mirror of cluster state (columnar.py)
feeds a jitted lax.scan kernel (kernel.py) that plans every pending
allocation against every feasible node in one XLA program, and the
``tpu-batch`` scheduler (batch_sched.py) wires it into the factory map with
the scalar oracle as fallback for paths the kernel does not cover.
"""

from .batch_sched import TPUBatchScheduler
from .columnar import ColumnarCluster
from .kernel import plan_batch
