#!/usr/bin/env sh
# CI entry point for the static analyzer (ANALYSIS.md).
# Exit 0 = clean modulo the committed ANALYSIS_BASELINE.json;
# exit 1 = new findings (printed as JSON); exit 2 = analyzer error.
# Extra args pass through, e.g.:
#   scripts/analyze.sh --rules lock-order-cycle nomad_tpu/tpu/
#
# --changed (must be first) limits findings to files touched in the
# working tree / index vs HEAD — the pre-commit loop: analyze only what
# you are about to ship. The whole tree is still PARSED (call graphs
# and lock orders cross file boundaries); only the findings are
# filtered, so a cross-file finding anchored in an untouched file still
# needs the full run (CI does both).
set -eu

cd "$(dirname "$0")/.."

if [ "${1:-}" = "--changed" ]; then
    shift
    changed=$(
        {
            git diff --name-only HEAD -- 'nomad_tpu/*.py' 'nomad_tpu/**/*.py'
            git diff --name-only --cached -- 'nomad_tpu/*.py' 'nomad_tpu/**/*.py'
        } | sort -u
    )
    if [ -z "$changed" ]; then
        echo "analyze.sh --changed: no modified nomad_tpu .py files" >&2
        exit 0
    fi
    # shellcheck disable=SC2086
    exec python -m nomad_tpu.analysis --format json "$@" $changed
fi

exec python -m nomad_tpu.analysis --format json "$@"
